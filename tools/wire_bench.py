#!/usr/bin/env python
"""Wire-format micro-bench for the compressed update transport.

Encodes a resnet-sized pytree of update deltas through every registered
codec and prints ONE JSON line per codec:

- ``bytes_before`` — the full-precision ``safe_dumps`` payload (what the
  wire carried before compression existed);
- ``bytes_after`` — the codec-tagged compressed payload;
- ``ratio`` — bytes_before / bytes_after;
- ``encode_ms`` / ``decode_ms`` — steady-state codec cost (first call
  pays the jit compile and is reported separately as ``compile_ms``);
- ``max_abs_err`` — worst-case element error of decode(encode(x)).

Usage: ``python tools/wire_bench.py [--params N] [--codecs a,b,...]``
(also reachable as ``python bench.py --wire``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def make_resnet_sized_tree(n_params_target: int = 11_000_000, seed: int = 0):
    """A conv-stack-shaped pytree around resnet18 size (~11.2M params)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    tree = {}
    shapes = [("stem/conv", (7, 7, 3, 64)), ("stem/bn", (64,))]
    widths = [(64, 64), (64, 128), (128, 256), (256, 512)]
    for stage, (cin, cout) in enumerate(widths):
        for block in range(2):
            c_in = cin if block == 0 else cout
            shapes.append((f"s{stage}b{block}/conv1", (3, 3, c_in, cout)))
            shapes.append((f"s{stage}b{block}/conv2", (3, 3, cout, cout)))
            shapes.append((f"s{stage}b{block}/bn1", (cout,)))
            shapes.append((f"s{stage}b{block}/bn2", (cout,)))
    shapes.append(("fc/w", (512, 1000)))
    shapes.append(("fc/b", (1000,)))
    for name, shape in shapes:
        # update-delta-scaled values: small, zero-centered
        tree[name] = (rng.normal(size=shape) * 1e-2).astype(np.float32)
    n = sum(v.size for v in tree.values())
    while n < n_params_target:  # pad with extra fc-like blocks
        name = f"extra/w{len(tree)}"
        tree[name] = (rng.normal(size=(512, 1000)) * 1e-2).astype(np.float32)
        n += tree[name].size
    return tree


def bench_codec(name: str, tree, baseline_bytes: int) -> dict:
    import jax
    import numpy as np

    from fedml_tpu.compression import derive_key, get_codec
    from fedml_tpu.utils.serialization import safe_dumps

    codec = get_codec(name)
    key = derive_key(0, 0, 1)

    t0 = time.perf_counter()
    ct = jax.block_until_ready(codec.encode(tree, key=key, is_delta=True))
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ct = jax.block_until_ready(codec.encode(tree, key=key, is_delta=True))
    encode_s = time.perf_counter() - t0

    wire = safe_dumps(ct)

    codec.decode(ct)  # decode compile
    t0 = time.perf_counter()
    decoded = jax.block_until_ready(codec.decode(ct))
    decode_s = time.perf_counter() - t0

    max_err = max(
        float(np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b, np.float32))))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(decoded))
    )
    return {
        "metric": "wire_bytes_per_codec",
        "codec": name,
        "bytes_before": baseline_bytes,
        "bytes_after": len(wire),
        "ratio": round(baseline_bytes / len(wire), 3),
        "encode_ms": round(encode_s * 1e3, 2),
        "decode_ms": round(decode_s * 1e3, 2),
        "compile_ms": round(compile_s * 1e3, 2),
        "max_abs_err": max_err,
        "n_params": int(sum(v.size for v in jax.tree.leaves(tree))),
    }


def run_wire_bench(n_params: int = 11_000_000,
                   codecs=("identity", "bf16", "int8", "topk")) -> list:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fedml_tpu.utils.serialization import safe_dumps

    tree = make_resnet_sized_tree(n_params)
    baseline = len(safe_dumps(tree))
    return [bench_codec(c, tree, baseline) for c in codecs]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--params", type=int, default=11_000_000)
    ap.add_argument("--codecs", type=str, default="identity,bf16,int8,topk")
    args = ap.parse_args()
    for row in run_wire_bench(args.params, args.codecs.split(",")):
        print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
