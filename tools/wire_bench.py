#!/usr/bin/env python
"""Wire-format micro-bench for the compressed update transport.

Encodes a resnet-sized pytree of update deltas through every registered
codec and prints ONE JSON line per codec:

- ``bytes_before`` — the full-precision ``safe_dumps`` payload (what the
  wire carried before compression existed);
- ``bytes_after`` — the codec-tagged compressed payload;
- ``ratio`` — bytes_before / bytes_after;
- ``encode_ms`` / ``decode_ms`` — steady-state codec cost (first call
  pays the jit compile and is reported separately as ``compile_ms``);
- ``max_abs_err`` — worst-case element error of decode(encode(x)).

The 4-bit rows (``int4``/``nf4``) carry ratio GATES (ISSUE 18): the
packed-nibble wire must be at least ``6x`` smaller than the f32 payload
and at least ``1.8x`` smaller than the int8 wire on the same tree —
``ok_ratio_f32`` / ``ok_ratio_int8`` ride each row and ``bench.py
--wire`` exits 1 when either goes false.

Usage: ``python tools/wire_bench.py [--params N] [--codecs a,b,...]``
(also reachable as ``python bench.py --wire``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def make_resnet_sized_tree(n_params_target: int = 11_000_000, seed: int = 0):
    """A conv-stack-shaped pytree around resnet18 size (~11.2M params)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    tree = {}
    shapes = [("stem/conv", (7, 7, 3, 64)), ("stem/bn", (64,))]
    widths = [(64, 64), (64, 128), (128, 256), (256, 512)]
    for stage, (cin, cout) in enumerate(widths):
        for block in range(2):
            c_in = cin if block == 0 else cout
            shapes.append((f"s{stage}b{block}/conv1", (3, 3, c_in, cout)))
            shapes.append((f"s{stage}b{block}/conv2", (3, 3, cout, cout)))
            shapes.append((f"s{stage}b{block}/bn1", (cout,)))
            shapes.append((f"s{stage}b{block}/bn2", (cout,)))
    shapes.append(("fc/w", (512, 1000)))
    shapes.append(("fc/b", (1000,)))
    for name, shape in shapes:
        # update-delta-scaled values: small, zero-centered
        tree[name] = (rng.normal(size=shape) * 1e-2).astype(np.float32)
    n = sum(v.size for v in tree.values())
    while n < n_params_target:  # pad with extra fc-like blocks
        name = f"extra/w{len(tree)}"
        tree[name] = (rng.normal(size=(512, 1000)) * 1e-2).astype(np.float32)
        n += tree[name].size
    return tree


def bench_codec(name: str, tree, baseline_bytes: int) -> dict:
    import jax
    import numpy as np

    from fedml_tpu.compression import derive_key, get_codec
    from fedml_tpu.utils.serialization import safe_dumps

    codec = get_codec(name)
    key = derive_key(0, 0, 1)

    t0 = time.perf_counter()
    ct = jax.block_until_ready(codec.encode(tree, key=key, is_delta=True))
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    ct = jax.block_until_ready(codec.encode(tree, key=key, is_delta=True))
    encode_s = time.perf_counter() - t0

    wire = safe_dumps(ct)

    codec.decode(ct)  # decode compile
    t0 = time.perf_counter()
    decoded = jax.block_until_ready(codec.decode(ct))
    decode_s = time.perf_counter() - t0

    max_err = max(
        float(np.max(np.abs(np.asarray(a, np.float32)
                            - np.asarray(b, np.float32))))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(decoded))
    )
    return {
        "metric": "wire_bytes_per_codec",
        "codec": name,
        "bytes_before": baseline_bytes,
        "bytes_after": len(wire),
        "ratio": round(baseline_bytes / len(wire), 3),
        "encode_ms": round(encode_s * 1e3, 2),
        "decode_ms": round(decode_s * 1e3, 2),
        "compile_ms": round(compile_s * 1e3, 2),
        "max_abs_err": max_err,
        "n_params": int(sum(v.size for v in jax.tree.leaves(tree))),
    }


DEFAULT_CODECS = ("identity", "bf16", "int8", "topk", "int4", "nf4")

# ISSUE 18 acceptance gates for the 4-bit wire on the resnet-sized tree
GATE_MIN_RATIO_VS_F32 = 6.0
GATE_MIN_RATIO_VS_INT8 = 1.8


def apply_wire_gates(rows: list) -> bool:
    """Annotate the 4-bit rows with their ratio gates, True iff all hold.

    ``ratio`` already measures vs the f32 ``safe_dumps`` payload; the
    int8 comparison divides the two wires' actual byte counts, so both
    gates judge what the transport really carries (headers included)."""
    by = {r.get("codec"): r for r in rows}
    int8_after = (by.get("int8") or {}).get("bytes_after")
    all_ok = True
    for name in ("int4", "nf4"):
        row = by.get(name)
        if row is None:
            continue
        row["ok_ratio_f32"] = row["ratio"] >= GATE_MIN_RATIO_VS_F32
        if int8_after:
            row["ratio_vs_int8"] = round(
                int8_after / row["bytes_after"], 3)
            row["ok_ratio_int8"] = (
                row["ratio_vs_int8"] >= GATE_MIN_RATIO_VS_INT8)
        all_ok = all_ok and row["ok_ratio_f32"] and row.get(
            "ok_ratio_int8", True)
    return all_ok


def run_wire_bench(n_params: int = 11_000_000,
                   codecs=DEFAULT_CODECS) -> list:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fedml_tpu.utils.serialization import safe_dumps

    tree = make_resnet_sized_tree(n_params)
    baseline = len(safe_dumps(tree))
    rows = [bench_codec(c, tree, baseline) for c in codecs]
    apply_wire_gates(rows)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--params", type=int, default=11_000_000)
    ap.add_argument("--codecs", type=str, default=",".join(DEFAULT_CODECS))
    args = ap.parse_args()
    rows = run_wire_bench(args.params, args.codecs.split(","))
    for row in rows:
        print(json.dumps(row))
    return 0 if apply_wire_gates(rows) else 1


if __name__ == "__main__":
    sys.exit(main())
