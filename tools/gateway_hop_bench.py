"""Gateway-hop latency (VERDICT r4 task 4, "include the gateway hop").

Measures the added latency of routing through the InferenceGateway vs
hitting the predictor runner directly, with a trivial predictor so the
numbers isolate the proxy (resolve + round-robin + forward + stream-back)
rather than model time. Reference counterpart: the FastAPI gateway at
``model_scheduler/device_model_inference.py:52-132``.

Run:  python tools/gateway_hop_bench.py [--n 200]
"""
import argparse
import json
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, "/root/repo")

import numpy as np

from fedml_tpu.deploy.cache import EndpointCache, EndpointStatus
from fedml_tpu.deploy.gateway import InferenceGateway
from fedml_tpu.serving.inference_runner import FedMLInferenceRunner
from fedml_tpu.serving.predictor import FedMLPredictor

ap = argparse.ArgumentParser()
ap.add_argument("--n", type=int, default=200)
cli = ap.parse_args()


class Echo(FedMLPredictor):
    def predict(self, request):
        return {"echo": request}


runner = FedMLInferenceRunner(Echo(), host="127.0.0.1", port=0)
runner.start()
time.sleep(0.3)
direct = f"http://127.0.0.1:{runner.port}"

with tempfile.TemporaryDirectory() as td:
    cache = EndpointCache(td + "/endpoints.json")
    cache.upsert_endpoint("ep1", endpoint_name="echo", model_name="echo",
                          model_version=1, status=EndpointStatus.DEPLOYED)
    cache.set_replica("ep1", "w1", url=direct,
                      status=EndpointStatus.DEPLOYED)
    gw = InferenceGateway(cache).start()
    via_gw = f"http://127.0.0.1:{gw.port}/inference/ep1"

    def post(url):
        req = urllib.request.Request(
            url if url != direct else url + "/predict",
            data=json.dumps({"x": 1}).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=10) as r:
            r.read()
        return time.perf_counter() - t0

    for _ in range(20):  # warm sockets/handlers
        post(direct)
        post(via_gw)
    td_ms = np.asarray([post(direct) for _ in range(cli.n)]) * 1e3
    tg_ms = np.asarray([post(via_gw) for _ in range(cli.n)]) * 1e3
    gw.stop()

out = {
    "direct_p50_ms": round(float(np.percentile(td_ms, 50)), 2),
    "direct_p99_ms": round(float(np.percentile(td_ms, 99)), 2),
    "gateway_p50_ms": round(float(np.percentile(tg_ms, 50)), 2),
    "gateway_p99_ms": round(float(np.percentile(tg_ms, 99)), 2),
    "hop_added_p50_ms": round(float(np.percentile(tg_ms, 50)
                                    - np.percentile(td_ms, 50)), 2),
    "n": cli.n,
}
print(json.dumps(out))
