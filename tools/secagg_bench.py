#!/usr/bin/env python
"""Secure-aggregation bench: wire cost + dropout-recovery message cost.

Two claims the SecAgg subsystem makes, measured and gated:

1. **Wire** — a masked upload rides the int8 block domain (one
   mask-domain word per element instead of int8 block + per-leaf f32
   scale), so SecAgg wire bytes must stay within **1.2×** of plain int8
   for the same tree. The 4–10× f32 penalty the old
   documented-disabled path paid is the number this gate retires.
2. **Recovery** — a seeded chaos kill during a masked round must close
   via seed-reveal recovery at **≤ 1 extra message round-trip per
   dropout** (one recover-request/reveal wave), and the run must end
   bit-stably (`completed`).

Prints ONE JSON line (same contract as the other ``tools/*_bench.py``;
also reachable as ``python bench.py --secagg``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

WIRE_GATE = 1.2


def bench_wire(n_params: int = 1_000_000, cohort: int = 4) -> dict:
    """Masked vs plain-int8 wire bytes for one resnet-sized delta."""
    import numpy as np

    from fedml_tpu.compression import derive_key, get_codec
    from fedml_tpu.compression.codecs import _tree_meta
    from fedml_tpu.privacy import secagg
    from fedml_tpu.privacy.secagg import masking
    from fedml_tpu.utils.serialization import safe_dumps
    from tools.wire_bench import make_resnet_sized_tree

    import jax

    tree = make_resnet_sized_tree(n_params)
    delta = jax.tree.map(
        lambda x: (0.01 * np.random.default_rng(0).standard_normal(
            x.shape)).astype(np.float32), tree)
    int8_bytes = len(safe_dumps(get_codec("int8").encode(
        delta, key=derive_key(0, 0, 1), is_delta=True)))
    bound = masking.client_bound(cohort)
    codec = get_codec(f"secagg_int8@0.1/{bound}/8")
    meta = _tree_meta(jax.tree.leaves(delta))
    peers = {j: masking.pair_round_seed(j * 7919 + 13, 0)
             for j in range(2, cohort + 1)}
    net_mask = masking.net_mask_leaves(1, peers, meta)
    ct, _ = secagg.masked_encode(
        delta, net_mask, codec, derive_key(0, 0, 1),
        sa={"round": 0, "rank": 1, "roster": list(range(1, cohort + 1))})
    sa_bytes = len(safe_dumps(ct))
    ratio = sa_bytes / float(int8_bytes)
    return {
        "params": int(n_params),
        "cohort": int(cohort),
        "int8_wire_bytes": int(int8_bytes),
        "secagg_wire_bytes": int(sa_bytes),
        "wire_ratio_vs_int8": round(ratio, 4),
        "gate_wire_ok": bool(ratio <= WIRE_GATE),
    }


def bench_recovery(seed: int = 7, rounds: int = 5, clients: int = 3) -> dict:
    """Chaos-killed masked round: recovery waves per dropout + closure."""
    from fedml_tpu.resilience import run_chaos_scenario

    out = run_chaos_scenario(
        seed=seed, rounds=rounds, clients=clients,
        kill_rank=2, kill_round=2, revive_round=3,
        secagg="int8", round_deadline_s=30.0, round_quorum=2.0 / 3.0,
    )
    c = out["counters"]
    dropouts = max(1.0, c.get("clients_evicted", 0.0))
    waves = c.get("recoveries", 0.0)
    # one recovery wave = one extra round-trip (recover request out,
    # reveals back); the gate is ≤ 1 per dropout
    rt_per_dropout = waves / dropouts
    return {
        "completed": bool(out["completed"]),
        "dropouts": dropouts,
        "recovery_waves": waves,
        "seeds_revealed": c.get("seeds_revealed", 0.0),
        "recovery_failures": c.get("recovery_failures", 0.0),
        "round_trips_per_dropout": rt_per_dropout,
        "gate_recovery_ok": bool(
            out["completed"] and waves >= 1 and rt_per_dropout <= 1.0
            and not c.get("recovery_failures", 0.0)),
    }


def run_secagg_bench(n_params: int = 1_000_000, cohort: int = 4,
                     rounds: int = 5, seed: int = 7) -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    wire = bench_wire(n_params, cohort)
    rec = bench_recovery(seed=seed, rounds=rounds)
    return {
        "bench": "secagg",
        **wire,
        **rec,
        "wire_gate": WIRE_GATE,
        "ok": bool(wire["gate_wire_ok"] and rec["gate_recovery_ok"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--params", type=int, default=1_000_000)
    ap.add_argument("--cohort", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    row = run_secagg_bench(args.params, args.cohort, args.rounds, args.seed)
    print(json.dumps(row))
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
