"""Attribution-overhead bench — proves the always-on catalog is free.

The program catalog owns execution of every hot-path program (AOT
executable + last-used fastpath), so its steady-state cost is a
contextvar set/reset, one phase-dict increment, and the compiled call
itself. This bench proves that cost stays under ``tolerance`` (default
1%) of rounds/s, the same two-gate shape ``tools/live_bench.py`` uses:

- ``rounds_per_s_off`` / ``rounds_per_s_on`` — the SAME in-proc SP
  federation with the catalog disabled, then enabled, interleaved
  best-of-``trials`` so slow host-noise drift cancels out of the ratio
  (the honest-but-noisy gate);
- the micro-measured per-call wrapper seam: wall cost of one cataloged
  call minus the same program's raw AOT call, times the measured
  cataloged-calls-per-round, as a fraction of the round wall — the
  deterministic gate at ``tolerance`` (the <1% claim; measured ~0.02%).

The end-to-end ratio gates at ``rounds_tolerance`` (default 2%, the
live_bench precedent) because at CPU-tiny-run scale host noise alone
moves rounds/s by ~1% between back-to-back identical runs — the
deterministic seam is the sub-1% proof, the A/B ratio the honesty check.

Env knobs: ``FEDML_PROFILE_ROUNDS`` / ``FEDML_PROFILE_CLIENTS`` /
``FEDML_PROFILE_TRIALS`` / ``FEDML_PROFILE_TOL`` /
``FEDML_PROFILE_ROUNDS_TOL``.
One JSON line via ``bench.py --profile``.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional


def _run_once(seed: int, rounds: int, clients: int, profile: bool) -> float:
    """One in-proc SP federation; returns wall seconds."""
    import fedml_tpu
    from fedml_tpu import device as device_mod
    from fedml_tpu import models as models_mod
    from fedml_tpu import telemetry
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data import load_federated
    from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI
    from fedml_tpu.telemetry.profiling import get_catalog, reset_catalog

    cfg = {
        "common_args": {"training_type": "simulation", "random_seed": seed},
        "data_args": {"dataset": "synthetic", "train_size": 60 * clients,
                      "test_size": 60, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 32,
            "learning_rate": 0.3,
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    reset_catalog()
    get_catalog().enabled = profile
    api = FedAvgAPI(args, device_mod.get_device(args), ds, model)
    t0 = time.perf_counter()
    api.train()
    wall = time.perf_counter() - t0
    telemetry.reset_registry()
    telemetry.reset_tracer()
    return wall


def _calls_per_round(rounds: int) -> float:
    """Cataloged calls per round in the run that just finished (read off
    the enabled catalog before it is reset)."""
    from fedml_tpu.telemetry.profiling import get_catalog

    total = sum(r.calls for r in get_catalog().records())
    return total / max(rounds, 1)


def _micro_seam_seconds(n: int = 400) -> float:
    """Per-call wrapper seam: a cataloged trivial program vs its own raw
    AOT executable, same program, same arguments — the difference IS the
    catalog's steady-state cost (deterministic gate)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.telemetry.profiling import wrap_jit

    @jax.jit
    def f(x):
        return x * 1.0001

    x = jnp.ones((64,))
    wrapped = wrap_jit("bench/seam_probe", f)
    wrapped(x)  # absorb compile + analysis
    variant = wrapped._last
    if variant is None or variant.fallback or variant.compiled is None:
        # AOT unsupported on this backend: the wrapper already runs the
        # raw jit, so the seam is the contextvar+counters only — report
        # it as unmeasurable-zero rather than crashing the gate
        return 0.0
    raw = variant.compiled
    for _ in range(8):  # warm both call paths
        wrapped(x)
        raw(x)
    t0 = time.perf_counter()
    for _ in range(n):
        raw(x)
    t_raw = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        wrapped(x)
    t_wrapped = time.perf_counter() - t0
    return max(t_wrapped - t_raw, 0.0) / n


def run_profile_bench(rounds: Optional[int] = None,
                      clients: Optional[int] = None,
                      trials: Optional[int] = None,
                      tolerance: Optional[float] = None,
                      rounds_tolerance: Optional[float] = None
                      ) -> Dict[str, Any]:
    rounds = int(rounds or os.environ.get("FEDML_PROFILE_ROUNDS", 6))
    clients = int(clients or os.environ.get("FEDML_PROFILE_CLIENTS", 3))
    trials = int(trials or os.environ.get("FEDML_PROFILE_TRIALS", 3))
    tolerance = float(tolerance
                      or os.environ.get("FEDML_PROFILE_TOL", 0.01))
    rounds_tolerance = float(
        rounds_tolerance
        or os.environ.get("FEDML_PROFILE_ROUNDS_TOL",
                          max(0.02, tolerance)))

    walls_off, walls_on = [], []
    calls_per_round = 0.0
    for t in range(trials):
        # interleaved A/B so slow host-noise drift cancels out of the
        # ratio (live_bench methodology)
        walls_off.append(_run_once(t, rounds, clients, profile=False))
        walls_on.append(_run_once(t, rounds, clients, profile=True))
        calls_per_round = max(calls_per_round, _calls_per_round(rounds))
    wall_off = min(walls_off)
    wall_on = min(walls_on)
    rps_off = rounds / wall_off
    rps_on = rounds / wall_on
    ratio = rps_on / rps_off if rps_off else 0.0

    seam_s = _micro_seam_seconds()
    round_wall_s = wall_on / rounds
    overhead_ratio = (seam_s * calls_per_round / round_wall_s
                      if round_wall_s > 0 else 0.0)

    from fedml_tpu.telemetry.profiling import get_catalog

    return {
        "metric": "profile_attribution_overhead",
        "rounds": rounds,
        "clients": clients,
        "trials": trials,
        "rounds_per_s_off": round(rps_off, 3),
        "rounds_per_s_on": round(rps_on, 3),
        "on_off_ratio": round(ratio, 4),
        "seam_us_per_call": round(seam_s * 1e6, 3),
        "cataloged_calls_per_round": round(calls_per_round, 1),
        "overhead_ratio": round(overhead_ratio, 6),
        "programs_cataloged": len(get_catalog().records()),
        "tolerance": tolerance,
        "rounds_tolerance": rounds_tolerance,
        "ok_overhead": overhead_ratio <= tolerance,
        "ok_rounds": ratio >= 1.0 - rounds_tolerance,
        "completed": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_profile_bench()))
