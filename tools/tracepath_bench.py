"""Causal-tracing overhead bench — proves span streaming is (nearly)
free.

Runs the SAME in-proc cross-silo federation twice — live plane on in
BOTH arms, span streaming (``trace_streaming``) off then on — and
reports:

- ``rounds_per_s_off`` / ``rounds_per_s_on`` (best of ``trials`` each,
  interleaved so host noise drifts cancel) and their ratio, gated at
  ``tolerance`` (default 1%);
- the micro-measured span-batch seam: wall cost of one listener→frame→
  ingest pump over a realistic per-round span batch, as a fraction of
  the measured round wall (``overhead_ratio``, gated < ``tolerance``) —
  this is the deterministic gate; the end-to-end rounds/s ratio is the
  honest-but-noisy one;
- steady-state trace wire bytes per node per round (from the
  ``tracepath/frame_bytes`` counter), gated under
  ``max_bytes_per_round``.

Env knobs: ``FEDML_TRACEPATH_ROUNDS`` / ``FEDML_TRACEPATH_CLIENTS`` /
``FEDML_TRACEPATH_TRIALS`` / ``FEDML_TRACEPATH_TOL`` /
``FEDML_TRACEPATH_MAX_BYTES``. One JSON line via
``bench.py --tracepath``.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional


def _run_once(seed: int, rounds: int, clients: int, tracing: bool,
              run_id: str, log_dir: Optional[str] = None) -> float:
    """One in-proc cross-silo run (live plane always on); returns wall
    seconds."""
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu import telemetry
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.cross_silo.run_inproc import run_cross_silo_inproc
    from fedml_tpu.data import load_federated

    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": seed,
                        "run_id": run_id,
                        **({"log_file_dir": log_dir} if log_dir else {})},
        "data_args": {"dataset": "synthetic", "train_size": 60 * clients,
                      "test_size": 60, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 32,
            "learning_rate": 0.3,
            "live_telemetry": True, "metrics_port": 0,
            "trace_streaming": tracing,
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    t0 = time.perf_counter()
    result = run_cross_silo_inproc(args, ds, model, timeout=300)
    wall = time.perf_counter() - t0
    if result is None:
        raise RuntimeError("federation run did not complete")
    telemetry.reset_live_plane()
    return wall


def _frame_stats():
    """(frames_emitted, frame_bytes) from the process registry."""
    from fedml_tpu.telemetry import get_registry

    frames = bytes_sum = 0.0
    for rec in get_registry().snapshot():
        if rec["name"] == "tracepath/frames_emitted":
            frames += rec.get("value", 0.0)
        elif rec["name"] == "tracepath/frame_bytes":
            bytes_sum += rec.get("value", 0.0)
    return frames, bytes_sum


def _micro_pump_seconds(n: int = 50, spans_per_round: int = 24) -> float:
    """Wall seconds of ONE span-batch listener→frame→ingest pump over a
    realistic per-round span batch (deterministic seam measurement — the
    counterpart of live_bench's registry-pump gate)."""
    from fedml_tpu.telemetry import get_registry
    from fedml_tpu.telemetry.tracing import SpanStreamer, TraceCollector

    reg = get_registry()
    streamer = SpanStreamer("bench", job="tracepath_bench",
                            interval_s=3600.0, registry=reg)
    collector = TraceCollector(job="tracepath_bench", registry=reg)
    base = {"name": "round/0/client/1/train", "ts": 0.0,
            "duration_ms": 1.0, "trace_id": "bench",
            "service": "bench", "attrs": {"round": 0}}
    streamer.pump(collector, force=True)  # absorb the first empty build
    t0 = time.perf_counter()
    for i in range(n):
        for j in range(spans_per_round):
            streamer.on_record({**base, "span_id": f"s{i}_{j}"})
        streamer.pump(collector, force=True)
    return (time.perf_counter() - t0) / n


def run_tracepath_bench(rounds: Optional[int] = None,
                        clients: Optional[int] = None,
                        trials: Optional[int] = None,
                        tolerance: Optional[float] = None,
                        max_bytes_per_round: Optional[float] = None
                        ) -> Dict[str, Any]:
    rounds = int(rounds or os.environ.get("FEDML_TRACEPATH_ROUNDS", 5))
    clients = int(clients or os.environ.get("FEDML_TRACEPATH_CLIENTS", 3))
    trials = int(trials or os.environ.get("FEDML_TRACEPATH_TRIALS", 3))
    tolerance = float(tolerance
                      or os.environ.get("FEDML_TRACEPATH_TOL", 0.01))
    max_bytes = float(
        max_bytes_per_round
        or os.environ.get("FEDML_TRACEPATH_MAX_BYTES", 256 * 1024))

    walls_off, walls_on = [], []
    frames0, bytes0 = _frame_stats()
    for t in range(trials):
        # interleaved A/B so slow host-noise drift cancels out of the
        # ratio (same methodology as live_bench)
        walls_off.append(_run_once(t, rounds, clients, tracing=False,
                                   run_id=f"tracebench_off_{t}"))
        walls_on.append(_run_once(t, rounds, clients, tracing=True,
                                  run_id=f"tracebench_on_{t}"))
    frames1, bytes1 = _frame_stats()
    wall_off = min(walls_off)
    wall_on = min(walls_on)
    rps_off = rounds / wall_off
    rps_on = rounds / wall_on
    ratio = rps_on / rps_off if rps_off else 0.0

    # steady-state wire cost: every emitted span frame, averaged over the
    # tracing runs' rounds. In-proc there is ONE streaming node (the
    # plane's loopback streamer); multiprocess deployments add one per
    # rank.
    n_frames = frames1 - frames0
    frame_bytes = bytes1 - bytes0
    bytes_per_node_per_round = (frame_bytes / (trials * rounds)
                                if trials * rounds else 0.0)

    pump_s = _micro_pump_seconds()
    round_wall_s = wall_on / rounds
    overhead_ratio = (pump_s / round_wall_s) if round_wall_s > 0 else 0.0

    return {
        "metric": "tracepath_overhead",
        "rounds": rounds,
        "clients": clients,
        "trials": trials,
        "rounds_per_s_off": round(rps_off, 3),
        "rounds_per_s_on": round(rps_on, 3),
        "on_off_ratio": round(ratio, 4),
        "pump_ms": round(pump_s * 1e3, 3),
        "overhead_ratio": round(overhead_ratio, 5),
        "frames": int(n_frames),
        "frame_bytes": int(frame_bytes),
        "bytes_per_node_per_round": round(bytes_per_node_per_round, 1),
        "tolerance": tolerance,
        "max_bytes_per_round": max_bytes,
        "ok_overhead": overhead_ratio <= tolerance,
        "ok_bytes": bytes_per_node_per_round <= max_bytes,
        "ok_rounds": ratio >= 1.0 - max(tolerance, 0.02),
        "completed": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_tracepath_bench()))
