"""On-chip krum over a cohort whose stacked N×D fp32 exceeds 16 GB HBM
(VERDICT r4 task 3's measured proof).

N=8 clients x D=600M coords -> 19.2 GB stacked fp32: cannot be
device-resident on a v5e (16 GB). The blockwise path streams [N, C]
slices and accumulates the N x N gram on device; client 0 is a planted
byzantine (large-scale noise) that krum must drop.

Blocks are SYNTHESIZED ON DEVICE from per-(client, block) PRNG keys —
pushing 19 GB of host numpy through the axon tunnel would measure the
tunnel, not the defense (PERF_NOTES "Measurement methodology"). The
math exercised (per-block generation + gram update + selection) is
byte-identical to what host-streamed blocks would run.

Timing: the gram carry chains every block program (real data
dependency); one readback at the end; long-minus-short over full passes.

Run:  python tools/defense_big_bench.py [--d 600000000] [--clients 8]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense.blockwise import _gram_update
from fedml_tpu.core.security.defense.krum import select_krum

ap = argparse.ArgumentParser()
ap.add_argument("--d", type=int, default=600_000_000)
ap.add_argument("--clients", type=int, default=8)
ap.add_argument("--block", type=int, default=1 << 25)  # 1 GB at N=8
ap.add_argument("--evil-scale", type=float, default=30.0)
cli = ap.parse_args()

N, D, C = cli.clients, cli.d, cli.block
n_blocks = (D + C - 1) // C
stacked_gb = 4.0 * N * D / 1e9
dev = jax.devices()[0]
print(f"device={dev.device_kind}  N={N} D={D/1e9:.2f}B  "
      f"stacked={stacked_gb:.1f} GB (> HBM)  blocks={n_blocks}x{C}",
      flush=True)


@jax.jit
def make_block(key, scales):
    # benign rows ~ N(0, 0.01); the byzantine row is scaled noise —
    # same structure as ByzantineAttack(attack_mode="random")
    x = jax.random.normal(key, (N, C), jnp.float32)
    return x * scales[:, None]


scales = jnp.asarray([cli.evil_scale] + [0.01] * (N - 1), jnp.float32)
root = jax.random.key(7)


def full_pass(g, salt):
    for b in range(n_blocks):
        g = _gram_update(g, make_block(jax.random.fold_in(root, salt + b),
                                       scales))
    return g


def run_chain(n_passes):
    t0 = time.perf_counter()
    g = jnp.zeros((N, N), jnp.float32)
    for p in range(n_passes):
        g = full_pass(g, p * n_blocks)
    float(jnp.sum(g))  # single readback forces the whole chain
    return time.perf_counter() - t0


run_chain(1)  # compile + warm
t_short = run_chain(1)
t_long = run_chain(4)
sec_per_pass = (t_long - t_short) / 3
gbps = 4.0 * N * D / sec_per_pass / 1e9

# correctness on the same synthesized cohort: krum must drop client 0
g = full_pass(jnp.zeros((N, N), jnp.float32), 0)
import numpy as np

gh = np.asarray(g)
sq = np.diag(gh)
dmat = np.maximum(sq[:, None] + sq[None, :] - 2 * gh, 0.0)
keep = select_krum(jnp.asarray(dmat), f=1, k=N - 2)
assert 0 not in keep, f"krum failed to drop the planted byzantine: {keep}"

print(json.dumps({
    "defense": "krum (blockwise gram)",
    "stacked_gb": round(stacked_gb, 1),
    "sec_per_defense_pass": round(sec_per_pass, 3),
    "effective_gb_per_s": round(gbps, 1),
    "survivors": keep,
    "byzantine_dropped": 0 not in keep,
    "timing": "chained gram carry, long-minus-short readback",
}), flush=True)
