#!/usr/bin/env python
"""graftcheck — semantic static analysis for the repo's own invariants.

Thin CLI over :mod:`fedml_tpu.analysis` (also reachable as
``fedml_tpu analyze``).  Runs the seven passes (jit-purity, donation,
host-sync, thread-safety, message-contract, span-names, lint) over the
repo and fails on any unsuppressed finding.

  python tools/graftcheck.py                 # repo-wide, exit 1 on findings
  python tools/graftcheck.py --changed main  # only findings in touched files
  python tools/graftcheck.py --json          # one JSON line (bench-style)
  python tools/graftcheck.py --list-passes

Suppression: ``# graft: allow(<pass-id>): <why>`` on the line, or a
``pass-id|path|message :: why`` entry in ``analysis_baseline.txt``.
See ``docs/static_analysis.md``.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
