#!/usr/bin/env python
"""In-tree linter — the offline stand-in for ruff.

Shim: the checks moved to ``fedml_tpu.analysis.passes.lint`` (the
``lint`` pass of ``tools/graftcheck.py``).  This entrypoint keeps the
historical CLI, exit codes, output and module API (``check_file`` /
``iter_py`` / ``main``) so CI (`.github/workflows/ci.yml`) and local
habits keep working.  Checks, unchanged:

  F401  unused module-level import (skipped in __init__.py re-exports)
  E722  bare except
  B006  mutable default argument
  W291  trailing whitespace
  E501  line longer than 100 chars
  T201  print() in library code (CLI/tools/tests exempt)

`# noqa` on the offending line suppresses any check.

The analysis package is stdlib-only, and the import below deliberately
bypasses ``fedml_tpu/__init__.py``: the linter must keep reporting E999
even when the package import chain itself is the thing that's broken.
"""
from __future__ import annotations

import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# import the subpackage WITHOUT executing fedml_tpu/__init__.py (which
# pulls numpy/arguments/runner): register a bare namespace stub, import
# what we need (analysis modules are stdlib-only), then drop the stub so
# a later real `import fedml_tpu` in this process is unaffected
_stubbed = False
if "fedml_tpu" not in sys.modules:
    _pkg = types.ModuleType("fedml_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "fedml_tpu")]
    sys.modules["fedml_tpu"] = _pkg
    _stubbed = True

from fedml_tpu.analysis.passes.lint import (  # noqa: E402,F401
    LIB_DIRS,
    MAX_LINE,
    PRINT_EXEMPT,
    check_file,
    imported_names,
    iter_py,
    main,
)

if _stubbed:
    for _name in [m for m in sys.modules
                  if m == "fedml_tpu" or m.startswith("fedml_tpu.")]:
        del sys.modules[_name]

if __name__ == "__main__":
    sys.exit(main())
