#!/usr/bin/env python
"""Staging micro-bench for the pipelined round engine.

Runs a short mesh-simulator federation on synthetic data and prints ONE
JSON line with the staging-path numbers the pipelined round engine is
judged by:

- ``staged_bytes`` / ``staged_bytes_per_sec`` — host staging throughput
  (poison + batch + assemble + device_put), cumulative over the run;
- ``assembly_ms`` — one vectorized ``assemble_slots`` gather of a full
  round (the np.stack path that replaced the per-slot copy loop);
- ``prefetch_overlap_ratio`` — from the telemetry report: how much of
  each round's staging ran while the previous round's program was in
  flight (chained-timing caveat: host spans cannot see the device queue
  drain — see docs/performance.md).

Usage: ``python tools/stage_bench.py [--rounds N] [--clients N]
[--no-prefetch]`` (also reachable as ``python bench.py --stage``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_stage_bench(rounds: int = 6, clients: int = 16,
                    prefetch: bool = True) -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data import load_federated
    from fedml_tpu.data.dataset import assemble_slots
    from fedml_tpu.simulation.parallel.mesh_simulator import MeshFedAvgAPI
    from fedml_tpu.telemetry.report import build_report

    run_dir = tempfile.mkdtemp(prefix="stage_bench_")
    cfg = {
        "common_args": {
            "training_type": "simulation",
            "random_seed": 0,
            "run_id": "stage_bench",
            "log_file_dir": run_dir,
        },
        "data_args": {
            "dataset": "synthetic",
            "partition_method": "hetero",
            "partition_alpha": 0.5,
            "train_size": 256 * clients,
            "test_size": 256,
            "class_num": 5,
            "feature_dim": 32,
        },
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds,
            "epochs": 1,
            "batch_size": 32,
            "learning_rate": 0.1,
            # eval only at the end: per-round eval would re-insert the
            # host sync the pipeline exists to remove
            "frequency_of_the_test": rounds,
            "enable_prefetch": prefetch,
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    api = MeshFedAvgAPI(args, None, ds, model)

    t0 = time.perf_counter()
    result = api.train()
    wall = time.perf_counter() - t0
    # snapshot BEFORE the assembly micro-bench below: its round-0
    # re-staging (the engine trimmed those entries rounds ago) would
    # otherwise add untimed bytes to the counters
    stats = api._data_cache.stats()

    # assembly micro-bench: re-gather round 0 through the
    # one-np.stack-per-tensor path (re-staged — not cache hits)
    from fedml_tpu.core.schedule.seq_train_scheduler import (
        schedule_clients_to_devices,
    )

    client_ids = list(range(clients))
    arrays_by_cid = {
        cid: api._client_arrays(cid, 0) for cid in client_ids
    }
    id_matrix = schedule_clients_to_devices(
        client_ids, ds.train_data_local_num_dict, api.n_devices)
    t1 = time.perf_counter()
    xs, ys, ms = assemble_slots(id_matrix, arrays_by_cid)
    assembly_ms = (time.perf_counter() - t1) * 1e3
    sink = os.path.join(run_dir, "run_stage_bench")
    report = build_report(sink)
    overlap = report.get("stage_overlap") or {}
    # staging work time, counted ONCE per round: the worker's prefetch
    # span when the round was prefetched (the main thread's stage span is
    # then just the get() wait, contained within it), the inline stage
    # span otherwise
    import re as _re

    from fedml_tpu.telemetry.report import load_spans

    per_round = {}
    for s in load_spans(sink):
        m = _re.match(r"^round/(\d+)/(prefetch|stage)$", s["name"])
        if not m:
            continue
        n, kind = int(m.group(1)), m.group(2)
        slot = per_round.setdefault(n, {})
        slot[kind] = slot.get(kind, 0.0) + s["duration_ms"]
    stage_ms = sum(
        slot.get("prefetch", slot.get("stage", 0.0))
        for slot in per_round.values()
    )
    return {
        "metric": "stage_bench",
        "rounds": rounds,
        "clients": clients,
        "n_devices": int(api.n_devices),
        "prefetch": bool(prefetch),
        "prefetched_rounds": int(result.get("prefetched_rounds", 0)),
        "wall_sec": round(wall, 4),
        "staged_bytes": int(stats["bytes_staged"]),
        "staged_bytes_per_sec": (
            round(stats["bytes_staged"] / (stage_ms / 1e3), 1)
            if stage_ms else None
        ),
        "assembly_ms": round(assembly_ms, 3),
        "assembled_bytes": int(xs.nbytes + ys.nbytes + ms.nbytes),
        "prefetch_overlap_ratio": round(float(overlap.get("ratio", 0.0)), 4),
        "cache": stats,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--no-prefetch", action="store_true")
    ns = ap.parse_args()
    print(json.dumps(run_stage_bench(
        rounds=ns.rounds, clients=ns.clients, prefetch=not ns.no_prefetch)))


if __name__ == "__main__":
    main()
