#!/usr/bin/env python
"""Live-serving SLO bench: sustained load across federation hot swaps.

Boots a real endpoint (ContinuousBatchingEngine + OpenAI protocol +
ThreadingHTTPServer) on a seeded model, wires a ServingPublisher →
FederatedServingBridge pair over the LOCAL transport, then drives
closed-loop concurrent HTTP load through ``/v1/completions`` while a
simulated federation publishes N rounds — each one int8-encoded, staged
into the shadow slot on the bridge thread, and atomically flipped under
traffic. Prints ONE JSON line (same contract as the other
``tools/*_bench.py``; also reachable as ``python bench.py --serve``):

- qps + p50/p95/p99 request latency, measured over ALTERNATING no-swap
  baseline and swap windows of one continuous load run (the SLO gate is
  the p99 ratio; interleaving keeps slow host-noise drift out of it);
- swap count, max swap-induced stall (the engine's own per-swap stall
  histogram), dropped/errored requests (MUST be 0), 429 rejections;
- the int8 staging proof: bytes that crossed host→device per swap
  (``serving/stage_wire_bytes``) vs the f32 tree size — the live path
  never materializes a host-side f32 tree.

Env knobs for the driver: ``FEDML_SERVE_REQUESTS`` / ``_SWAPS`` /
``_CONCURRENCY`` / ``_MAX_NEW`` / ``_SLOTS`` / ``_CODEC``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[i]


def _post(url: str, obj: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _env_int(name: str, default: int, override) -> int:
    return int(os.environ.get(name, default) if override is None
               else override)


def _obs_overhead(engine, max_new: int, prompt) -> tuple:
    """Request-observability overhead, measured two ways (the live_bench
    pattern): an interleaved on/off A/B over whole ``generate`` calls
    (reported — end-to-end context, but noisy on a busy host) and a
    deterministic micro measurement of the actual seam (gated): the
    per-token clock-read+append, the per-step saturation gauge sample,
    and the per-request span-tree build, amortized per token and
    expressed as a fraction of the measured inter-token latency."""
    on, off = [], []
    for trial in range(6):  # interleaved: both phases see the same host
        engine.request_obs = bool(trial % 2)
        t0 = time.perf_counter()
        engine.generate(prompt, max_new_tokens=max_new)
        (on if trial % 2 else off).append(time.perf_counter() - t0)
    engine.request_obs = True
    e2e_ratio = (sorted(on)[len(on) // 2] / sorted(off)[len(off) // 2]
                 if off and sorted(off)[len(off) // 2] > 0 else 0.0)

    # the decode cadence the seam rides on, from the instrumented trials
    tpot = engine._h_tpot.snapshot()
    tpot_s = (tpot["sum"] / tpot["count"] / 1e3) if tpot["count"] else 0.0

    # per-token: one perf_counter read + one list append (the WHOLE
    # per-token seam in _emit)
    buf = []
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        buf.append(time.perf_counter())
    per_token = (time.perf_counter() - t0) / n
    # per-step: the saturation gauge sample
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        engine._sample_saturation()
    per_step = (time.perf_counter() - t0) / n
    # per-request: the retirement span-tree build (root + 3 children via
    # the real tracer, explicit ended — same path as the engine) plus the
    # histogram observes retirement makes (engine + monitor twins).
    # Observes land on the engine's UNLABELED tpot hist, which is why the
    # reported percentiles read the monitor's labeled twin instead.
    from fedml_tpu.telemetry.spans import get_tracer

    tracer = get_tracer()
    n = 300
    t0 = time.perf_counter()
    for _ in range(n):
        now = time.time()
        root = tracer.begin("req/request", rid="obs_probe", round=0,
                            tokens=max_new, ttft_ms=1.0, tokens_per_s=1.0)
        root.started = now
        for name in ("req/queue", "req/prefill", "req/decode"):
            sp = tracer.begin(name, round=0)
            sp.trace_id = root.trace_id
            sp.parent_id = root.span_id
            sp.started = now
            tracer.end(sp, ended=now + 1e-4)
        tracer.end(root, ended=now + 1e-3)
    per_request = (time.perf_counter() - t0) / n
    n = 4000
    t0 = time.perf_counter()
    for _ in range(n):
        engine._h_tpot.observe(1.0)
    per_obs = (time.perf_counter() - t0) / n
    per_request += per_obs * (2 + 4 * max_new)  # ttft + tpot observes, x2 twins

    seam = per_token + per_step + per_request / max(max_new, 1)
    micro_ratio = seam / tpot_s if tpot_s > 0 else 0.0
    return round(e2e_ratio, 4), round(micro_ratio, 4)


def run_serve_bench(requests: int = None, swaps: int = None,
                    concurrency: int = None, max_new: int = None,
                    slots: int = None, codec: str = None, seed: int = 0,
                    slo_ratio: float = 1.5) -> dict:
    requests = _env_int("FEDML_SERVE_REQUESTS", 60, requests)
    swaps = _env_int("FEDML_SERVE_SWAPS", 5, swaps)
    # closed-loop workers sized to the host: oversubscribing a small CPU
    # box turns the p99 into a scheduler-convoy lottery for BOTH phases
    concurrency = _env_int("FEDML_SERVE_CONCURRENCY",
                           max(2, min(8, (os.cpu_count() or 4) - 1)),
                           concurrency)
    max_new = _env_int("FEDML_SERVE_MAX_NEW", 6, max_new)
    slots = _env_int("FEDML_SERVE_SLOTS", 4, slots)
    codec = str(os.environ.get("FEDML_SERVE_CODEC", "int8")
                if codec is None else codec)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.core.distributed.communication.local_comm import (
        LocalBroker,
    )
    from fedml_tpu.core.distributed.message import Message
    from fedml_tpu.models.llm.llama import LlamaConfig, LlamaForCausalLM
    from fedml_tpu.serving import (
        ContinuousBatchingEngine,
        FederatedServingBridge,
        FedMLInferenceRunner,
        LlamaPredictor,
        ServingPublisher,
    )
    from fedml_tpu.serving.openai_protocol import OpenAIServing
    from fedml_tpu.telemetry import get_registry
    from fedml_tpu.utils.serialization import tree_nbytes

    cfg = LlamaConfig.tiny(vocab_size=300, use_flash=False)
    model = LlamaForCausalLM(cfg)
    params = jax.jit(model.init)(
        jax.random.key(seed), jnp.zeros((1, 8), jnp.int32))
    f32_nbytes = tree_nbytes(params)

    engine = ContinuousBatchingEngine(
        model, params, batch_slots=slots, max_len=64, initial_round=0)
    runner = FedMLInferenceRunner(
        LlamaPredictor(engine),
        openai=OpenAIServing(engine, model_name="fedml-tpu"),
        max_inflight=max(2 * concurrency, 8),
    ).start()
    engine.model_slots.monitor = runner.monitor

    from fedml_tpu.serving.live import serve_namespace

    run_id = f"serve_bench_{seed}"
    ns = serve_namespace(run_id)  # the pair's own comm namespace
    LocalBroker.destroy(ns)
    publisher = ServingPublisher(run_id=run_id, codec=codec, seed=seed)
    bridge = FederatedServingBridge(engine.model_slots, run_id=run_id)
    publisher.run_async()
    bridge.run_async()
    LocalBroker.get(ns).post(1, Message(
        bridge.MSG_TYPE_CONNECTION_IS_READY, 1, 1))

    rng = np.random.default_rng(seed)
    url = f"http://127.0.0.1:{runner.port}/v1/completions"

    # warm every compiled path BEFORE timing: prompt buckets, the decode
    # program, and the swap-transition gather/scatter decode for every
    # group size (its first compile would otherwise land inside the swap
    # phase and be misread as a swap stall)
    for b in engine._buckets:
        plen = max(1, min(b - 1, engine.max_len - 3))
        engine.generate(rng.integers(3, 259, plen).tolist(),
                        max_new_tokens=2)
    engine.warm_swap_paths()  # the same pre-compile the serve CLI does
    # ... and the staging path (encode + device_put + on-device decode):
    # its first-call compiles must not land inside the measured swap
    # phase and read as swap-induced stalls
    from fedml_tpu.compression import derive_key, get_codec

    warm_codec = get_codec(codec)
    if warm_codec is not None:
        engine.model_slots.stage(
            warm_codec.encode(params, key=derive_key(seed, 0, 0)),
            warm_codec.spec)
    else:
        engine.model_slots.stage(params)

    # instrumentation-overhead check BEFORE the load run (the micro
    # probes pollute the engine's unlabeled histograms; the row's
    # TTFT/TPOT percentiles read the monitor's labeled twins, which only
    # real requests touch)
    obs_e2e_ratio, obs_overhead = _obs_overhead(
        engine, max_new, rng.integers(3, 259, 12).tolist())

    results = []  # (phase, latency_s, model_tag)
    dropped = []
    res_lock = threading.Lock()
    counter = {"next": 0}
    # the phase label workers stamp on each request at send time; the
    # timeline thread alternates it (baseline ↔ swap windows)
    phase_cell = {"phase": "probe"}
    stop_load = threading.Event()
    # per-request prompt lengths drawn once up front: np.Generator is not
    # thread-safe and the workers race
    plens = rng.integers(4, 24, size=requests).tolist()

    def worker():
        while not stop_load.is_set():
            with res_lock:
                i = counter["next"]
                counter["next"] += 1
            phase = phase_cell["phase"]
            prompt = "q" * plens[i % len(plens)]
            t0 = time.perf_counter()
            try:
                status, body = _post(url, {
                    "model": "fedml-tpu", "prompt": prompt,
                    "max_tokens": max_new, "seed": i})
                lat = time.perf_counter() - t0
                with res_lock:
                    if status == 200:
                        results.append((phase, lat, body.get("model", "")))
                    else:
                        dropped.append((phase, status))
            except Exception as e:  # noqa: BLE001 - any failure = dropped
                with res_lock:
                    dropped.append((phase, repr(e)))

    # One continuous closed-loop load with ALTERNATING windows:
    # baseline → (publish + swap window) → baseline → ... Host noise on a
    # small machine drifts on second scales, so a baseline block measured
    # minutes apart from the swap block gates on noise, not on the swap
    # machinery — interleaving samples both phases through the same
    # weather. Each swap window opens with its publish, so the staging +
    # transition episode lands inside it; the window is floored well
    # above one episode (~0.1-0.3 s), the deployment shape where rounds
    # are seconds-to-minutes apart.
    swap_wait = max(1.2, requests / max(swaps, 1) / 40.0)
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(swap_wait)  # discarded probe window (steady-state warm)
    base_wall = swap_wall = 0.0
    for r in range(1, swaps + 1):
        phase_cell["phase"] = "baseline"
        time.sleep(swap_wait)
        base_wall += swap_wait
        phase_cell["phase"] = "swap"
        # deterministic per-round weights: the round index is folded into
        # the perturbation so every published round is a distinct model
        publisher.publish(r, jax.tree.map(
            lambda x, _r=r: x + jnp.asarray(0.001 * _r, x.dtype), params))
        time.sleep(swap_wait)
        swap_wall += swap_wait
    phase_cell["phase"] = "baseline"  # closing window: balance the count
    time.sleep(swap_wait)
    base_wall += swap_wait
    stop_load.set()
    for t in threads:
        t.join()
    total_wall = time.perf_counter() - t_start

    # let the final swap land before reading freshness
    deadline = time.time() + 10
    while engine.model_slots.live_round < swaps and time.time() < deadline:
        time.sleep(0.05)

    snap = runner.monitor.snapshot()
    reg = get_registry()
    stage_wire = reg.gauge("serving/stage_wire_bytes").value
    stall_snap = reg.histogram("serving/swap_stall_ms").snapshot()
    # token-latency attribution: the monitor's endpoint-labeled twins
    # (the unlabeled engine hists carry the micro-probe pollution)
    ttft_snap = runner.monitor._h_ttft.snapshot()
    tpot_snap = runner.monitor._h_tpot.snapshot()
    queue_snap = runner.monitor._h_queue_wait.snapshot()

    base_lat = [l for p, l, _ in results if p == "baseline"]
    swap_lat = [l for p, l, _ in results if p == "swap"]
    swap_tags = {m for p, _, m in results if p == "swap"}
    base_p99 = _pct(base_lat, 0.99)
    swap_p99 = _pct(swap_lat, 0.99)

    publisher.finish()
    bridge.finish()
    runner.stop()
    engine.stop()
    LocalBroker.destroy(ns)

    row = {
        "bench": "serve",
        "requests": len(results) + len(dropped),
        "wall_s": round(total_wall, 2),
        "concurrency": concurrency,
        "codec": codec,
        "swaps_requested": swaps,
        "swaps_applied": engine.model_slots.swap_count,
        "round_current": engine.model_slots.live_round,
        "qps": round(len(swap_lat) / swap_wall, 2) if swap_wall else 0.0,
        "baseline_qps": round(len(base_lat) / base_wall, 2)
        if base_wall else 0.0,
        "p50_ms": round(_pct(swap_lat, 0.50) * 1e3, 2),
        "p95_ms": round(_pct(swap_lat, 0.95) * 1e3, 2),
        "p99_ms": round(swap_p99 * 1e3, 2),
        "baseline_p50_ms": round(_pct(base_lat, 0.50) * 1e3, 2),
        "baseline_p99_ms": round(base_p99 * 1e3, 2),
        "p99_vs_baseline": round(swap_p99 / base_p99, 3) if base_p99
        else 0.0,
        "max_swap_stall_ms": round(stall_snap["max"], 2)
        if stall_snap["count"] else 0.0,
        "ttft_p50_ms": round(ttft_snap["p50"], 2),
        "ttft_p95_ms": round(ttft_snap["p95"], 2),
        "ttft_p99_ms": round(ttft_snap["p99"], 2),
        "tpot_p50_ms": round(tpot_snap["p50"], 2),
        "tpot_p95_ms": round(tpot_snap["p95"], 2),
        "tpot_p99_ms": round(tpot_snap["p99"], 2),
        "tokens_per_s": snap.get("tokens_per_s", 0.0),
        "queue_wait_p95_ms": round(queue_snap["p95"], 2),
        # instrumentation overhead: the interleaved end-to-end A/B is
        # reported (host-noise context); the deterministic micro-measured
        # seam is what gates
        "obs_e2e_ratio": obs_e2e_ratio,
        "obs_overhead_ratio": obs_overhead,
        "dropped": len(dropped),
        "rejected": snap.get("rejected", 0),
        "served_rounds": sorted(swap_tags),
        "stage_wire_bytes": int(stage_wire),
        "f32_tree_nbytes": int(f32_nbytes),
        "ok_dropped": len(dropped) == 0,
        "ok_swaps": engine.model_slots.live_round >= swaps,
        # the SLO gate: sustained p99 under swaps within slo_ratio of the
        # no-swap baseline (compile paths pre-warmed, so this measures
        # the swap machinery, not XLA)
        "ok_p99": bool(base_p99 and swap_p99 <= slo_ratio * base_p99),
        # int8 staging proof: what crossed host→device per swap is the
        # compressed wire, a fraction of the f32 tree it decodes to
        "ok_no_host_f32": (codec in ("", "none", "identity")
                           or stage_wire < 0.5 * f32_nbytes),
        # the <2% gate on the deterministic seam (NOT folded into
        # `completed`: the smoke tier runs too few tokens to average the
        # micro probes fairly — bench.py --serve gates on it)
        "ok_obs_overhead": bool(obs_overhead <= 0.02),
    }
    row["completed"] = bool(row["ok_dropped"] and row["ok_swaps"]
                            and row["ok_no_host_f32"])
    return row


def write_artifact(row: dict, bench_dir: str = None):
    """Archive the emitted row as ``SERVE_r01.json`` (the compare_serve
    baseline). ``FEDML_SERVE_OUT=''`` disables."""
    name = os.environ.get("FEDML_SERVE_OUT", "SERVE_r01.json")
    if not name:
        return None
    path = os.path.join(bench_dir or REPO, name)
    with open(path, "w") as f:
        json.dump(row, f, indent=1)
        f.write("\n")
    return path


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--swaps", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=None)
    ap.add_argument("--max-new", type=int, default=None)
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--codec", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    row = run_serve_bench(requests=args.requests, swaps=args.swaps,
                          concurrency=args.concurrency,
                          max_new=args.max_new, slots=args.slots,
                          codec=args.codec, seed=args.seed)
    print(json.dumps(row))
    write_artifact(row)
    return 0 if (row["completed"] and row["ok_p99"]
                 and row["ok_obs_overhead"]) else 1


if __name__ == "__main__":
    sys.exit(main())
