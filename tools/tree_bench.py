#!/usr/bin/env python
"""Hierarchical-federation bench: the 100k-client claim, measured.

Runs a seeded N-tier aggregation tree (``fedml_tpu.hierarchy.TreeRunner``)
on this machine and prints ONE JSON line: clients simulated, tiers,
rounds/s, peak wire bytes per tier, peak compressed-buffer bytes per
tier, and peak host RSS — the numbers behind "a 3-tier, 100k+ virtual-
client federation runs on one machine without ever materializing a
per-client f32 tree".

Same contract as the other ``tools/*_bench.py`` (also reachable as
``python bench.py --tree``). Environment knobs for the driver:
``FEDML_TREE_CLIENTS`` / ``FEDML_TREE_TIERS`` / ``FEDML_TREE_ROUNDS`` /
``FEDML_TREE_PARAMS`` / ``FEDML_TREE_CODEC``.
"""
from __future__ import annotations

import argparse
import json
import os
import resource
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _peak_rss_bytes() -> int:
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # linux reports KiB, macOS bytes
    return int(ru) * (1 if sys.platform == "darwin" else 1024)


def run_tree_bench(clients: int = None, tiers: int = None, rounds: int = None,
                   n_params: int = None, codec: str = None, seed: int = 0,
                   quorum: float = 2.0 / 3.0, chunk: int = 4096) -> dict:
    # None -> the FEDML_TREE_* env knob (driver contract), then the
    # 100k-claim default — so `python bench.py --tree` honors the env
    clients = int(os.environ.get("FEDML_TREE_CLIENTS", 100_000)
                  if clients is None else clients)
    tiers = int(os.environ.get("FEDML_TREE_TIERS", 3)
                if tiers is None else tiers)
    rounds = int(os.environ.get("FEDML_TREE_ROUNDS", 2)
                 if rounds is None else rounds)
    n_params = int(os.environ.get("FEDML_TREE_PARAMS", 256)
                   if n_params is None else n_params)
    codec = str(os.environ.get("FEDML_TREE_CODEC", "int8")
                if codec is None else codec)
    from fedml_tpu.hierarchy import (
        TreeRunner,
        TreeTopology,
        default_template,
    )

    topo = TreeTopology.build(int(clients), tiers=int(tiers))
    runner = TreeRunner(topo, template=default_template(int(n_params)),
                        codec=codec, seed=int(seed), quorum=float(quorum),
                        chunk=int(chunk))
    stats = runner.run(int(rounds))
    per_tier = stats["per_tier"]
    peak_wire = {d: row["peak_round_upload_bytes"]
                 for d, row in per_tier.items()}
    peak_buffer = {d: row["peak_buffer_bytes"] for d, row in per_tier.items()}
    # the claim the gauge bound enforces: no tier ever buffers anything
    # near a per-client f32 tree set
    f32_worst = stats["f32_tree_nbytes"] * stats["clients"]
    peak_any = max(peak_buffer.values() or [0])
    return {
        "bench": "tree",
        "clients": stats["clients"],
        "tiers": stats["tiers"],
        "levels": stats["levels"],
        "rounds": stats["rounds"],
        "codec": stats["codec"],
        "seed": stats["seed"],
        "rounds_per_s": round(stats["rounds_per_s"], 4),
        "wall_s": round(stats["wall_s"], 3),
        "per_client_wire_bytes": stats["per_client_wire_bytes"],
        "f32_tree_nbytes": stats["f32_tree_nbytes"],
        "peak_wire_bytes_per_tier": peak_wire,
        "peak_buffer_bytes_per_tier": peak_buffer,
        "peak_buffer_vs_f32_trees": round(peak_any / max(f32_worst, 1), 6),
        "peak_host_rss_bytes": _peak_rss_bytes(),
        "final_digest": stats["final_digest"],
        "ok_no_f32_trees": peak_any < 0.5 * f32_worst,
        "completed": bool(stats["completed"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--tiers", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--params", type=int, default=None)
    ap.add_argument("--codec", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    row = run_tree_bench(clients=args.clients, tiers=args.tiers,
                         rounds=args.rounds, n_params=args.params,
                         codec=args.codec, seed=args.seed)
    print(json.dumps(row))
    return 0 if (row["completed"] and row["ok_no_f32_trees"]) else 1


if __name__ == "__main__":
    sys.exit(main())
