#!/usr/bin/env python
"""Preemptible-capacity job-plane bench — ONE JSON line (``bench.py --preempt``).

Two halves:

1. **Supervision micro** (always, the tier-1 smoke): a deterministic
   crasher under a real :class:`LocalAgent` must trip crash-loop
   containment after exactly ``crash_loop_threshold`` fast identical
   failures, with a bit-deterministic backoff schedule (the policy is
   un-jittered by design); plus a preempt quiesce micro — SIGTERM →
   whole-process-group drained — on a TERM-trapping run, reporting the
   quiesce wall.

2. **Drain scenario** (skipped in smoke mode): the cross-process
   preempt/resume acceptance from :mod:`fedml_tpu.scheduler.preempt` —
   two node-agent subprocesses, a durable cross-silo federation whose
   server node is drained mid-round (SIGTERM + grace, reschedule to the
   second agent), measuring **MTTR** (reclaim notice → the rescheduled
   server's journal-replay ``RESUMED`` marker), **salvaged uploads**
   (> 0, none retrained), and **bit-identity** of the final params
   against an undisturbed same-seed run (identity codec).

Env knobs: ``FEDML_PREEMPT_ROUNDS`` / ``FEDML_PREEMPT_CLIENTS`` /
``FEDML_PREEMPT_DRAIN_ROUND`` / ``FEDML_PREEMPT_MTTR_BUDGET_S`` /
``FEDML_PREEMPT_SMOKE``. The emitted line carries
``metric: preempt_mttr_s`` so archived ``PREEMPT_*.json`` files diff
through ``tools/bench_compare.py`` (``compare_preempt``).
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = ["run_preempt_bench", "main"]


def _supervision_micro(tmp: str) -> Dict:
    """Crash-loop containment + preempt quiesce, in-proc, deterministic."""
    from fedml_tpu.core.mlops.status import RunStatus
    from fedml_tpu.scheduler.agent import LocalAgent
    from fedml_tpu.scheduler.job_yaml import JobSpec
    from fedml_tpu.scheduler.supervision import RestartPolicy, RestartTracker

    policy = {"max_restarts": 5, "backoff_s": 0.05,
              "crash_loop_threshold": 3, "fast_fail_s": 10}
    agent = LocalAgent(workdir=os.path.join(tmp, "agent"),
                       poll_interval=0.02).start()
    try:
        rid = agent.start_run(JobSpec(
            job_name="crasher", job="exit 7", workspace=".",
            restart=dict(policy)))
        status = agent.wait(rid, timeout=60)
        rec = agent._runs[rid]
        contained = (status == RunStatus.FAILED
                     and "crash-loop contained" in rec.reason)
        # the backoff schedule must be bit-deterministic: what the run
        # actually slept matches a fresh tracker's arithmetic exactly
        ref = RestartTracker(RestartPolicy(**policy))
        expect = []
        for _ in range(2):  # threshold 3 → 2 relaunches before containment
            action, delay = ref.on_exit(7, 0.0)
            assert action == "restart"
            expect.append(delay)
        deterministic = rec.tracker.delays_s == expect

        rid2 = agent.start_run(JobSpec(
            job_name="quiesce",
            job='trap "exit 0" TERM; echo armed; sleep 30', workspace="."))
        deadline = time.time() + 10
        while "armed" not in agent.logs(rid2) and time.time() < deadline:
            time.sleep(0.01)  # wait for the shell to arm the trap
        t0 = time.perf_counter()
        agent.preempt(rid2, grace_s=10.0)
        quiesce_ms = (time.perf_counter() - t0) * 1e3
        preempted = agent.status(rid2) == RunStatus.PREEMPTED
        return {
            "crash_loop_contained": bool(contained),
            "crash_loop_attempts": rec.tracker.restarts + 1,
            "backoff_schedule_s": [round(d, 4) for d in rec.tracker.delays_s],
            "backoff_deterministic": bool(deterministic),
            "preempt_quiesce_ms": round(quiesce_ms, 2),
            "preempt_status_ok": bool(preempted),
            "ok_contained": bool(contained and deterministic and preempted),
        }
    finally:
        agent.shutdown()


def run_preempt_bench(full: Optional[bool] = None) -> Dict:
    import shutil
    import tempfile

    rounds = int(os.environ.get("FEDML_PREEMPT_ROUNDS", "4"))
    clients = int(os.environ.get("FEDML_PREEMPT_CLIENTS", "2"))
    drain_round = int(os.environ.get("FEDML_PREEMPT_DRAIN_ROUND", "2"))
    mttr_budget = float(os.environ.get("FEDML_PREEMPT_MTTR_BUDGET_S", "60"))
    if full is None:
        full = os.environ.get("FEDML_PREEMPT_SMOKE") != "1"

    tmp = tempfile.mkdtemp(prefix="fedml_preempt_bench_")
    try:
        return _run(tmp, rounds, clients, drain_round, mttr_budget, full)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run(tmp: str, rounds: int, clients: int, drain_round: int,
         mttr_budget: float, full: bool) -> Dict:
    row: Dict = {
        "metric": "preempt_mttr_s",
        "value": None,
        "unit": "s",
        "rounds": rounds, "clients": clients, "drain_round": drain_round,
        "smoke": not full,
    }
    row.update(_supervision_micro(tmp))
    if not full:
        row["ok"] = row["ok_contained"]
        return row

    from fedml_tpu.resilience.durability import run_recover_scenario
    from fedml_tpu.scheduler.preempt import run_preempt_scenario

    base = run_recover_scenario(seed=7, rounds=rounds, clients=clients,
                                kill=False, compression="identity")
    drained = run_preempt_scenario(
        seed=7, rounds=rounds, clients=clients, drain_round=drain_round,
        compression="identity", tmp_dir=os.path.join(tmp, "drain"))
    # no-retrain: a salvaged client's journaled round appears exactly
    # once in its TRAINED history across both server placements
    no_retrain = all(
        drained["trained"].get(str(c), []).count(drained["resumed_round"]) == 1
        for c in drained["salvaged_clients"])
    row.update({
        "value": drained["mttr_s"],
        "mttr_s": drained["mttr_s"],
        "salvaged_uploads": drained["salvaged_uploads"],
        "rescheduled_to": drained.get("rescheduled_to"),
        "bit_identical": (base["digest"] is not None
                          and base["digest"] == drained["digest"]),
        "no_retrain_of_salvaged": no_retrain,
        "scenario_wall_s": drained["wall_s"],
        "sched_counters": drained.get("counters"),
        "ok_mttr": (drained["mttr_s"] is not None
                    and drained["mttr_s"] < mttr_budget),
        "ok_salvaged": drained["salvaged_uploads"] > 0,
        "ok_completed": bool(drained["completed"]),
    })
    row["ok"] = bool(row["ok_contained"] and row["ok_completed"]
                     and row["ok_mttr"] and row["ok_salvaged"]
                     and row["bit_identical"]
                     and row["no_retrain_of_salvaged"])
    return row


def main() -> int:
    row = run_preempt_bench()
    print(json.dumps(row))  # noqa: T201 (CLI output)
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
