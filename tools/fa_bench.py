#!/usr/bin/env python
"""Federated-analytics bench: the sketch engine's claims, measured.

Two segments, one JSON line:

- **FSM segment** — real message-passing FA rounds (frequency sketch +
  multi-round TrieHH) over the in-proc transport: rounds completed,
  wall seconds, rounds/s.
- **Federation segment** — the 100k-client 3-tier heavy-hitter vote
  federation over the aggregation tree, secagg-masked with central DP
  at the root: rounds/s, per-client masked wire bytes vs the plain
  int32 sketch, and heavy-hitter recall/precision against the plaintext
  reference sketch replayed on the same seeded data.

Gates (exit 1 on failure, like every other ``tools/*_bench.py``):

- ``ok_wire`` — masked sketch wire ≤ 1.2× the plain int32 sketch bytes
- ``ok_recall`` — federated HH recall AND precision ≥ 0.95 vs the
  plaintext reference at the same width×depth
- ``ok_traced`` — the per-client sketch existed only as a tracer inside
  the leaf program (no host-side per-client plaintext in masked mode)

Also reachable as ``python bench.py --fa``; archived as ``FA_r01.json``
(the ``compare_fa`` baseline). Environment knobs for the driver:
``FEDML_FA_CLIENTS`` / ``FEDML_FA_TIERS`` / ``FEDML_FA_WIDTH`` /
``FEDML_FA_DEPTH`` / ``FEDML_FA_VOCAB`` / ``FEDML_FA_WORDS`` /
``FEDML_FA_COHORT`` / ``FEDML_FA_FSM_CLIENTS`` / ``FEDML_FA_OUT``.

The 3-tier topology pins leaf cohorts to ``FEDML_FA_COHORT`` clients
(default 128): secagg's pairwise-mask work grows with cohort size, so
a wide edge tier keeps the 100k default inside a few minutes without
changing what's measured.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_WIRE_OVERHEAD_GATE = 1.2
_RECALL_GATE = 0.95


def _fsm_segment(n_clients: int, seed: int) -> dict:
    """Real FSM rounds over the in-proc transport, sketch mode."""
    from fedml_tpu.fa.run_inproc import run_fa_inproc

    rng_words = ["sun", "moon", "star", "sky", "rain", "wind", "sea"]
    rounds = 0
    t0 = time.perf_counter()
    args = types.SimpleNamespace(
        run_id="fa_bench_freq", random_seed=seed, rank=0,
        fa_task="frequency_estimation", fa_sketch="auto",
        fa_query_items=rng_words[:3])
    data = {r: [rng_words[(r + i) % len(rng_words)] for i in range(32)]
            for r in range(1, n_clients + 1)}
    freq = run_fa_inproc(args, data)
    rounds += freq["rounds"]
    args = types.SimpleNamespace(
        run_id="fa_bench_hh", random_seed=seed, rank=0,
        fa_task="heavy_hitter_triehh", fa_sketch="auto",
        fa_theta=max(2, n_clients // 2), fa_max_word_len=4)
    data = {r: ["sun", "moon", "sun"] for r in range(1, n_clients + 1)}
    hh = run_fa_inproc(args, data)
    rounds += hh["rounds"]
    wall = time.perf_counter() - t0
    return {
        "fsm_clients": n_clients,
        "fsm_rounds": rounds,
        "fsm_wall_s": round(wall, 3),
        "fsm_rounds_per_s": round(rounds / wall, 3) if wall > 0 else 0.0,
        "fsm_heavy_hitters": hh.get("heavy_hitters"),
        "fsm_freq_spec": freq.get("spec"),
    }


def run_fa_bench(clients: int = None, tiers: int = None, width: int = None,
                 depth: int = None, vocab: int = None, words: int = None,
                 seed: int = 0, fsm_clients: int = None) -> dict:
    clients = int(os.environ.get("FEDML_FA_CLIENTS", 100_000)
                  if clients is None else clients)
    tiers = int(os.environ.get("FEDML_FA_TIERS", 3)
                if tiers is None else tiers)
    width = int(os.environ.get("FEDML_FA_WIDTH", 1024)
                if width is None else width)
    depth = int(os.environ.get("FEDML_FA_DEPTH", 3)
                if depth is None else depth)
    vocab = int(os.environ.get("FEDML_FA_VOCAB", 512)
                if vocab is None else vocab)
    words = int(os.environ.get("FEDML_FA_WORDS", 32)
                if words is None else words)
    fsm_clients = int(os.environ.get("FEDML_FA_FSM_CLIENTS", 6)
                      if fsm_clients is None else fsm_clients)
    cohort = int(os.environ.get("FEDML_FA_COHORT", 128))

    from fedml_tpu.fa.sketch.federation import (
        last_sketch_trace,
        run_sketch_federation,
    )

    fsm = _fsm_segment(fsm_clients, seed)

    levels = None
    if tiers == 3 and clients > cohort:
        levels = (1, -(-clients // cohort), clients)
    fed = run_sketch_federation(
        n_clients=clients, tiers=tiers, levels=levels,
        codec=f"votevec@{width}/{depth}", seed=seed, vocab=vocab,
        n_hot=12, p_hot=0.5, words_per_client=words,
        hh_threshold_frac=0.02, secagg=True, dp_sigma=2.0)
    traced = last_sketch_trace().get("client_sketch_traced") is True

    ok_wire = fed["wire_overhead"] <= _WIRE_OVERHEAD_GATE
    ok_recall = (fed["hh_recall"] >= _RECALL_GATE
                 and fed["hh_precision"] >= _RECALL_GATE)
    row = {
        "bench": "fa",
        "seed": seed,
        **fsm,
        "clients": fed["clients"],
        "tiers": tiers,
        "levels": fed["levels"],
        "spec": fed["spec"],
        "vocab": vocab,
        "words_per_client": words,
        "secagg": fed["secagg"],
        "dp_sigma": fed["dp_sigma"],
        "dp_epsilon": round(fed["dp_epsilon"], 3),
        "rounds_per_s": round(fed["rounds_per_s"], 4),
        "hh_recall": round(fed["hh_recall"], 4),
        "hh_precision": round(fed["hh_precision"], 4),
        "heavy_hitters_found": len(fed["heavy_hitters"]),
        "per_client_wire_bytes": fed["per_client_wire_bytes"],
        "plain_sketch_bytes": fed["plain_sketch_bytes"],
        "wire_overhead": round(fed["wire_overhead"], 4),
        "final_digest": fed["final_digest"],
        "ok_wire": ok_wire,
        "ok_recall": ok_recall,
        "ok_traced": traced,
        "completed": bool(fed["stats"].get("completed")),
    }
    row["ok"] = (row["completed"] and ok_wire and ok_recall and traced)
    return row


def write_artifact(row: dict, bench_dir: str = None):
    """Archive the emitted row as ``FA_r01.json`` (the compare_fa
    baseline). ``FEDML_FA_OUT=''`` disables."""
    name = os.environ.get("FEDML_FA_OUT", "FA_r01.json")
    if not name:
        return None
    path = os.path.join(bench_dir or REPO, name)
    with open(path, "w") as f:
        json.dump(row, f, indent=1)
        f.write("\n")
    return path


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--tiers", type=int, default=None)
    ap.add_argument("--width", type=int, default=None)
    ap.add_argument("--depth", type=int, default=None)
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--words", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    row = run_fa_bench(clients=args.clients, tiers=args.tiers,
                       width=args.width, depth=args.depth,
                       vocab=args.vocab, words=args.words, seed=args.seed)
    write_artifact(row)
    print(json.dumps(row))
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
