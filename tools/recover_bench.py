#!/usr/bin/env python
"""Kill-the-server recovery bench — ONE JSON line (``bench.py --recover``).

Two halves:

1. **Journal seam** — the durability tax on a healthy run. The same
   in-proc cross-silo federation runs with durability off and on
   (interleaved, best-of-N walls), plus a deterministic micro-measure of
   the journal's per-round cost: ``(cohort + 3)`` fsync'd appends of
   real wire-sized records. The gate is the micro seam as a fraction of
   the measured durable round wall (< 2% — the on/off wall ratio is
   also reported, but on a CPU toy model it is noise-dominated, same
   caveat as ``tools/live_bench.py``).

2. **Recovery scenario** (skipped in smoke mode) — the supervised
   kill-the-server run from
   :mod:`fedml_tpu.resilience.durability.recover`: SIGKILL mid-round,
   auto-restart with resume, measuring **MTTR** (kill → journal replay
   announced), **salvaged uploads** (must be > 0 — zero lost
   already-received uploads), a **no-retrain** check (no salvaged client
   trains its journaled round twice), and **bit-identity** of the final
   params against an uninterrupted same-seed run (identity codec).

Env knobs: ``FEDML_RECOVER_ROUNDS`` / ``FEDML_RECOVER_CLIENTS`` /
``FEDML_RECOVER_KILL_ROUND`` / ``FEDML_RECOVER_MTTR_BUDGET_S``.
The emitted line carries ``metric: recover_mttr_s`` so the archived
``RECOVER_*.json`` files diff through ``tools/bench_compare.py``.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = ["run_recover_bench", "main"]


def _inproc_wall(durability: bool, tmp: str, tag: str,
                 rounds: int, clients: int) -> float:
    """Wall seconds of one in-proc cross-silo run (rounds only start
    after construction, but compiles dominate the first call — callers
    interleave and take best-of)."""
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.cross_silo.run_inproc import run_cross_silo_inproc
    from fedml_tpu.data import load_federated

    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": 0,
                        "run_id": f"recover_seam_{tag}"},
        "data_args": {"dataset": "synthetic", "train_size": 60 * clients,
                      "test_size": 40, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 16,
            "learning_rate": 0.3,
            # BOTH runs checkpoint every round: per-round checkpointing
            # predates durability (checkpoint_frequency default 1), so
            # the on/off delta isolates the JOURNAL seam
            "checkpoint_dir": os.path.join(tmp, f"ck_{tag}"),
            "checkpoint_frequency": 1,
            **({"durability": True, "resume": True} if durability else {}),
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    t0 = time.perf_counter()
    result = run_cross_silo_inproc(args, ds, model, timeout=240)
    wall = time.perf_counter() - t0
    assert result is not None
    return wall


def _journal_round_ms(tmp: str, clients: int) -> float:
    """Deterministic per-round journal cost: (cohort + 3) fsync'd appends
    of records shaped like the real ones (lr-sized upload payload)."""
    import numpy as np

    from fedml_tpu.resilience.durability import RoundJournal

    j = RoundJournal(os.path.join(tmp, "seam.journal"))
    payload = {"w": np.zeros((10, 4), np.float32),
               "b": np.zeros((4,), np.float32)}
    trials = []
    for t in range(5):
        # EXACTLY the production record/durability pattern per round:
        # open + each upload are synced; the close/commit markers and the
        # reset are flush-only (replay re-derives them — see journal.py)
        t0 = time.perf_counter()
        j.append("round_open", round=t, cohort=list(range(1, clients + 1)),
                 silo_index={i: i - 1 for i in range(1, clients + 1)},
                 seed=0, codec=None, secagg=False)
        for c in range(1, clients + 1):
            j.append("upload_received", round=t, client=c,
                     msg_id="abcdef0123456789:0:42", n_samples=40,
                     local_steps=None, payload=payload)
        j.append("quorum_close", durable=False, round=t, missing=[])
        j.append("aggregate_committed", durable=False, round=t)
        j.reset()
        trials.append((time.perf_counter() - t0) * 1e3)
    j.close()
    return min(trials)


def run_recover_bench(full: Optional[bool] = None) -> Dict:
    import tempfile

    rounds = int(os.environ.get("FEDML_RECOVER_ROUNDS", "4"))
    clients = int(os.environ.get("FEDML_RECOVER_CLIENTS", "2"))
    kill_round = int(os.environ.get("FEDML_RECOVER_KILL_ROUND", "2"))
    mttr_budget = float(os.environ.get("FEDML_RECOVER_MTTR_BUDGET_S", "60"))
    if full is None:
        full = os.environ.get("FEDML_RECOVER_SMOKE") != "1"

    import shutil

    tmp = tempfile.mkdtemp(prefix="fedml_recover_bench_")
    try:
        return _run(tmp, rounds, clients, kill_round, mttr_budget, full)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run(tmp: str, rounds: int, clients: int, kill_round: int,
         mttr_budget: float, full: bool) -> Dict:
    # interleaved off/on walls: best-of cancels the cold-compile first run
    walls_off = []
    walls_on = []
    for i in range(2):
        walls_off.append(_inproc_wall(False, tmp, f"off{i}",
                                      rounds, clients))
        walls_on.append(_inproc_wall(True, tmp, f"on{i}",
                                     rounds, clients))
    wall_off, wall_on = min(walls_off), min(walls_on)
    round_ms_on = wall_on / rounds * 1e3
    seam_ms = _journal_round_ms(tmp, clients)
    seam_pct = seam_ms / round_ms_on * 100.0

    row: Dict = {
        "metric": "recover_mttr_s",
        "value": None,
        "unit": "s",
        "rounds": rounds, "clients": clients,
        "journal_round_ms": round(seam_ms, 3),
        "durable_round_ms": round(round_ms_on, 3),
        "seam_pct": round(seam_pct, 4),
        "rounds_per_s_on": round(rounds / wall_on, 4),
        "rounds_per_s_off": round(rounds / wall_off, 4),
        "on_off_ratio": round(wall_on / wall_off, 4),
        "ok_seam": seam_pct < 2.0,
        "smoke": not full,
    }
    if not full:
        row["ok"] = row["ok_seam"]
        return row

    from fedml_tpu.resilience.durability import run_recover_scenario

    base = run_recover_scenario(seed=7, rounds=rounds, clients=clients,
                                kill=False, compression="identity")
    killed = run_recover_scenario(seed=7, rounds=rounds, clients=clients,
                                  kill=True, kill_round=kill_round,
                                  compression="identity")
    # no-retrain: a salvaged client's journaled round appears exactly
    # once in its TRAINED history across both server lives
    no_retrain = all(
        killed["trained"].get(str(c), []).count(killed["resumed_round"]) == 1
        for c in killed["salvaged_clients"])
    row.update({
        "value": killed["mttr_s"],
        "mttr_s": killed["mttr_s"],
        "restarts": killed["restarts"],
        "salvaged_uploads": killed["salvaged_uploads"],
        "bit_identical": (base["digest"] is not None
                          and base["digest"] == killed["digest"]),
        "no_retrain_of_salvaged": no_retrain,
        "scenario_wall_s": killed["wall_s"],
        "ok_mttr": (killed["mttr_s"] is not None
                    and killed["mttr_s"] < mttr_budget),
        "ok_salvaged": killed["salvaged_uploads"] > 0,
    })
    row["ok"] = bool(row["ok_seam"] and row["ok_mttr"]
                     and row["ok_salvaged"] and row["bit_identical"]
                     and row["no_retrain_of_salvaged"])
    return row


def main() -> int:
    row = run_recover_bench()
    print(json.dumps(row))  # noqa: T201 (CLI output)
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
