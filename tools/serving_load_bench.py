"""Arrival-driven serving benchmark (VERDICT r4 task 4).

Drives the continuous-batching engine the way vLLM-class engines are
judged: Poisson arrivals at an offered load, mixed prompt lengths
(64-1024) and output lengths, reporting TTFT p50/p99, inter-token
latency, completed-token throughput, and the measured prefill stall
decode streams suffer per admission. Reference capability this maps to:
the hf/vLLM serving template (`device_model_deployment.py:528`).

Run (1.1B bf16 on the chip):
  python tools/serving_load_bench.py --model 1b --loads 0.5,1,2,4
Run (dev-scale CPU sanity):
  JAX_PLATFORMS=cpu python tools/serving_load_bench.py --model tiny

Each load level runs `--requests` requests; arrivals are pre-scheduled
from a seeded RNG so runs are reproducible.
"""
import argparse
import json
import sys
import threading
import time

sys.path.insert(0, "/root/repo")

import numpy as np

ap = argparse.ArgumentParser()
ap.add_argument("--model", default="tiny", choices=["tiny", "1b", "7b"])
ap.add_argument("--loads", default="0.5,1,2",
                help="offered loads, requests/second, comma-separated")
ap.add_argument("--requests", type=int, default=32)
ap.add_argument("--slots", type=int, default=8)
ap.add_argument("--quantize", default=None)
ap.add_argument("--seed", type=int, default=0)
ap.add_argument("--platform", default=None,
                help="force a jax platform (e.g. cpu) — the axon "
                     "sitecustomize overrides JAX_PLATFORMS env")
cli = ap.parse_args()

import jax

if cli.platform:
    jax.config.update("jax_platforms", cli.platform)
import jax.numpy as jnp

from fedml_tpu.models.llm.llama import LlamaConfig, LlamaForCausalLM
from fedml_tpu.serving.llm_engine import ContinuousBatchingEngine

if cli.model == "7b":
    # int8-only on one v5e: bf16 weights + KV cannot fit (PERF_NOTES r4)
    cli.quantize = cli.quantize or "int8"
    cfg = LlamaConfig.llama2_7b(
        dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
        remat=False, remat_policy="none", use_flash=False,
    )
    max_len, prompt_hi = 768, 512
    cli.slots = min(cli.slots, 4)  # KV is ~1.07 GB/slot at max_len 768
elif cli.model == "1b":
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=22, num_attention_heads=32,
        num_key_value_heads=8, max_position_embeddings=2048,
        dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
        remat=False, remat_policy="none", use_flash=False,
    )
    max_len, prompt_hi = 1536, 1024
else:
    cfg = LlamaConfig.tiny(use_flash=False)
    max_len, prompt_hi = 128, 64

model = LlamaForCausalLM(cfg)
rng = np.random.default_rng(cli.seed)
toks = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 8)))
params = jax.jit(model.init)(jax.random.key(0), toks)
n_params = sum(x.size for x in jax.tree.leaves(params))
print(f"model={cli.model} ({n_params/1e9:.2f}B) slots={cli.slots} "
      f"max_len={max_len} quantize={cli.quantize}", flush=True)

engine = ContinuousBatchingEngine(
    model, params, batch_slots=cli.slots, max_len=max_len,
    quantize=cli.quantize, quantize_donate=bool(cli.quantize),
).start()


def one_level(offered_rps: float) -> dict:
    n_req = cli.requests
    r = np.random.default_rng(cli.seed + int(offered_rps * 1000))
    # mixed prompts: log-uniform in [64, prompt_hi]; outputs geometric-ish
    lo = min(64, prompt_hi)
    plens = np.exp(r.uniform(np.log(lo), np.log(prompt_hi), n_req)).astype(int)
    olens = np.clip(r.geometric(1 / 24.0, n_req), 4, 96)
    olens = np.minimum(olens, max_len - plens - 4)  # engine hard cap
    gaps = r.exponential(1.0 / offered_rps, n_req)
    arrivals = np.cumsum(gaps)

    results = [None] * n_req
    lock = threading.Lock()

    def consume(i, q, t_submit):
        first, last, count = None, None, 0
        while True:
            tok = q.get()
            now = time.perf_counter()
            if tok is None:
                break
            if first is None:
                first = now
            last = now
            count += 1
        with lock:
            results[i] = (t_submit, first, last, count)

    # warm the compile caches (every prompt bucket + decode) before timing
    for b in engine._buckets:
        if b <= prompt_hi:
            engine.generate(
                rng.integers(0, cfg.vocab_size, max(b - 1, 1)).tolist(),
                max_new_tokens=2)

    threads = []
    t0 = time.perf_counter()
    for i in range(n_req):
        delay = t0 + arrivals[i] - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        prompt = r.integers(0, cfg.vocab_size, plens[i]).tolist()
        t_submit = time.perf_counter()
        q = engine.submit(prompt, max_new_tokens=int(olens[i]))
        th = threading.Thread(target=consume, args=(i, q, t_submit))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0

    ttft = np.asarray([f - s for s, f, _, c in results if f])
    itl = np.asarray([(e - f) / max(c - 1, 1)
                      for _, f, e, c in results if f and c > 1])
    total_tokens = sum(c for *_, c in results)
    return {
        "offered_rps": offered_rps,
        "achieved_rps": round(len(results) / wall, 2),
        "ttft_p50_ms": round(float(np.percentile(ttft, 50)) * 1e3, 1),
        "ttft_p99_ms": round(float(np.percentile(ttft, 99)) * 1e3, 1),
        "itl_p50_ms": round(float(np.percentile(itl, 50)) * 1e3, 1),
        "itl_p99_ms": round(float(np.percentile(itl, 99)) * 1e3, 1),
        "tok_per_s": round(total_tokens / wall, 1),
        "mean_prompt": int(plens.mean()),
        "mean_output": float(olens.mean()),
    }


# direct prefill-stall measurement: decode inter-token gap when an
# admission intervenes = one bucketed-prefill forward
def prefill_stall() -> dict:
    out = {}
    for p in (64, 512, 1024):
        if p > max_len - 8:
            continue
        prompt = rng.integers(0, cfg.vocab_size, p).tolist()
        t0 = time.perf_counter()
        engine.generate(prompt, max_new_tokens=1)
        out[f"prefill_ms_p{p}"] = round((time.perf_counter() - t0) * 1e3, 1)
    return out


levels = [one_level(float(x)) for x in cli.loads.split(",")]
stall = prefill_stall()
print(json.dumps({"levels": levels, "prefill_stall": stall,
                  "admit_per_step": engine.admit_per_step}, indent=1),
      flush=True)
engine.stop()
