"""Live-telemetry overhead bench — proves streaming is (nearly) free.

Runs the SAME in-proc cross-silo federation twice — live plane off, then
on (collector + online doctor + /metrics endpoint + per-round loopback
frames) — and reports:

- ``rounds_per_s_off`` / ``rounds_per_s_on`` (best of ``trials`` each,
  interleaved so host noise drifts cancel) and their ratio, gated at
  ``tolerance`` (default 2%);
- the micro-measured streaming seam: wall cost of one snapshot→frame→
  ingest pump over the run's real populated registry, times pumps per
  round, as a fraction of the measured round wall (``overhead_ratio``,
  gated < ``tolerance``) — this is the deterministic gate; the end-to-end
  rounds/s ratio is the honest-but-noisy one;
- steady-state telemetry wire bytes per node per round (from the
  ``live/frame_bytes`` histogram), gated under ``max_bytes_per_round``.

Env knobs: ``FEDML_LIVE_ROUNDS`` / ``FEDML_LIVE_CLIENTS`` /
``FEDML_LIVE_TRIALS`` / ``FEDML_LIVE_TOL`` / ``FEDML_LIVE_MAX_BYTES``.
One JSON line via ``bench.py --live``.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional


def _run_once(seed: int, rounds: int, clients: int, live: bool,
              run_id: str, log_dir: Optional[str] = None) -> float:
    """One in-proc cross-silo run; returns wall seconds."""
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu import telemetry
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.cross_silo.run_inproc import run_cross_silo_inproc
    from fedml_tpu.data import load_federated

    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": seed,
                        "run_id": run_id,
                        **({"log_file_dir": log_dir} if log_dir else {})},
        "data_args": {"dataset": "synthetic", "train_size": 60 * clients,
                      "test_size": 60, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 32,
            "learning_rate": 0.3,
            **({"live_telemetry": True, "metrics_port": 0} if live else {}),
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    t0 = time.perf_counter()
    result = run_cross_silo_inproc(args, ds, model, timeout=300)
    wall = time.perf_counter() - t0
    if result is None:
        raise RuntimeError("federation run did not complete")
    telemetry.reset_live_plane()
    return wall


def _frame_stats():
    """(frames_emitted, frame_bytes_sum) from the process registry."""
    from fedml_tpu.telemetry import get_registry

    frames = bytes_sum = 0.0
    for rec in get_registry().snapshot():
        if rec["name"] == "live/frames_emitted":
            frames += rec.get("value", 0.0)
        elif rec["name"] == "live/frame_bytes":
            bytes_sum += rec.get("sum", 0.0)
    return frames, bytes_sum


def _micro_pump_seconds(n: int = 50) -> float:
    """Wall seconds of ONE snapshot→frame→ingest pump over the registry
    this process just populated with a real run (deterministic seam
    measurement — the counterpart of chaos_bench's send-seam gate)."""
    from fedml_tpu.telemetry import get_registry
    from fedml_tpu.telemetry.live import LiveCollector, MetricStreamer

    reg = get_registry()
    streamer = MetricStreamer("bench", job="live_bench", registry=reg,
                              interval_s=3600.0)
    collector = LiveCollector(job="live_bench")
    tick = reg.counter("comm/messages_sent")  # something changes per pump
    streamer.pump(collector, force=True)  # absorb the first full build
    t0 = time.perf_counter()
    for _ in range(n):
        tick.inc()
        streamer.pump(collector, force=True)
    return (time.perf_counter() - t0) / n


def run_live_bench(rounds: Optional[int] = None,
                   clients: Optional[int] = None,
                   trials: Optional[int] = None,
                   tolerance: Optional[float] = None,
                   max_bytes_per_round: Optional[float] = None
                   ) -> Dict[str, Any]:
    rounds = int(rounds or os.environ.get("FEDML_LIVE_ROUNDS", 5))
    clients = int(clients or os.environ.get("FEDML_LIVE_CLIENTS", 3))
    trials = int(trials or os.environ.get("FEDML_LIVE_TRIALS", 3))
    tolerance = float(tolerance or os.environ.get("FEDML_LIVE_TOL", 0.02))
    max_bytes = float(max_bytes_per_round
                      or os.environ.get("FEDML_LIVE_MAX_BYTES", 256 * 1024))

    walls_off, walls_on = [], []
    frames0, bytes0 = _frame_stats()
    for t in range(trials):
        # interleaved A/B so slow host-noise drift cancels out of the
        # ratio (same methodology as serve_bench's swap windows)
        walls_off.append(_run_once(t, rounds, clients, live=False,
                                   run_id=f"livebench_off_{t}"))
        walls_on.append(_run_once(t, rounds, clients, live=True,
                                  run_id=f"livebench_on_{t}"))
    frames1, bytes1 = _frame_stats()
    wall_off = min(walls_off)
    wall_on = min(walls_on)
    rps_off = rounds / wall_off
    rps_on = rounds / wall_on
    ratio = rps_on / rps_off if rps_off else 0.0

    # steady-state wire cost: every emitted frame, averaged over the live
    # runs' (nodes × rounds). In-proc there is ONE streaming node (the
    # server loopback); multiprocess deployments add one per rank.
    n_frames = frames1 - frames0
    frame_bytes = bytes1 - bytes0
    bytes_per_node_per_round = (frame_bytes / (trials * rounds)
                                if trials * rounds else 0.0)

    pump_s = _micro_pump_seconds()
    round_wall_s = wall_on / rounds
    overhead_ratio = (pump_s / round_wall_s) if round_wall_s > 0 else 0.0

    return {
        "metric": "live_telemetry_overhead",
        "rounds": rounds,
        "clients": clients,
        "trials": trials,
        "rounds_per_s_off": round(rps_off, 3),
        "rounds_per_s_on": round(rps_on, 3),
        "on_off_ratio": round(ratio, 4),
        "pump_ms": round(pump_s * 1e3, 3),
        "overhead_ratio": round(overhead_ratio, 5),
        "frames": int(n_frames),
        "frame_bytes": int(frame_bytes),
        "bytes_per_node_per_round": round(bytes_per_node_per_round, 1),
        "tolerance": tolerance,
        "max_bytes_per_round": max_bytes,
        "ok_overhead": overhead_ratio <= tolerance,
        "ok_bytes": bytes_per_node_per_round <= max_bytes,
        "ok_rounds": ratio >= 1.0 - tolerance,
        "completed": True,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_live_bench()))
