"""Secure-profile FHE round cost on a realistic LoRA payload
(VERDICT r4 task 5).

Simulates one `fhe_profile: secure` federated round end to end:
N_CLIENTS clients encrypt a D-param adapter payload (r=16 7B LoRA is
~10M params ≈ 40 MB fp32), the server computes the weighted ciphertext
aggregate WITHOUT decrypting (fhe_fedavg), one client decrypts the
aggregate. RNS-CKKS N=8192 → D/4096 ciphertexts per payload.

Run:  python tools/fhe_bench.py [--d 10000000] [--clients 8]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

from fedml_tpu.core.fhe.ckks import RNSCKKSContext, _load_ntt_native

ap = argparse.ArgumentParser()
ap.add_argument("--d", type=int, default=10_000_000)
ap.add_argument("--clients", type=int, default=8)
cli = ap.parse_args()

ctx = RNSCKKSContext(seed=0).keygen()
D, N = cli.d, cli.clients
n_ct = -(-D // ctx.slots)
mb = 4.0 * D / 1e6
print(f"payload D={D/1e6:.1f}M params ({mb:.0f} MB fp32) -> {n_ct} cts "
      f"(N={ctx.n}, {ctx.slots} slots); native ntt: "
      f"{_load_ntt_native() is not None}", flush=True)

rng = np.random.default_rng(1)
vec = rng.normal(0, 0.02, D)

t0 = time.perf_counter()
cts = ctx.encrypt_vector(vec)
t_enc = time.perf_counter() - t0
print(f"encrypt (1 client): {t_enc:.1f}s  ({mb/t_enc:.1f} MB/s)", flush=True)

# server: weighted ciphertext aggregation over N clients. Every client's
# payload has identical shape/size, so aggregating N references to this
# one is compute-identical to N distinct uploads (values don't change the
# mod-arithmetic cost) without paying N× encrypt time in the harness.
q = ctx.q
weights = np.maximum(1, np.rint(
    (np.arange(N) + 1.0) / (N * (N + 1) / 2) * 256)).astype(np.int64)
t0 = time.perf_counter()
acc0 = [np.mod(ct.c0 * int(weights[0]), q) for ct in cts]
acc1 = [np.mod(ct.c1 * int(weights[0]), q) for ct in cts]
for w in weights[1:]:
    for j, ct in enumerate(cts):
        acc0[j] = np.mod(acc0[j] + ct.c0 * int(w), q)
        acc1[j] = np.mod(acc1[j] + ct.c1 * int(w), q)
t_agg = time.perf_counter() - t0
print(f"aggregate ({N} clients, ciphertext-only): {t_agg:.1f}s", flush=True)

from fedml_tpu.core.fhe.ckks import CKKSCiphertext

agg = [CKKSCiphertext(a0, a1) for a0, a1 in zip(acc0, acc1)]
save = ctx.delta
ctx.delta = save * float(weights.sum())
t0 = time.perf_counter()
out = ctx.decrypt_vector(agg, D)
t_dec = time.perf_counter() - t0
ctx.delta = save
print(f"decrypt (aggregate): {t_dec:.1f}s  ({mb/t_dec:.1f} MB/s)", flush=True)

# correctness: all clients sent the same vec, so the weighted mean is vec
err = float(np.abs(out - vec).max())
assert err < 5e-3, f"aggregate decrypt error {err}"

round_sec = t_enc + t_agg + t_dec
print(json.dumps({
    "profile": "secure RNS-CKKS N=8192",
    "payload_mb": round(mb, 1),
    "n_ciphertexts": n_ct,
    "clients": N,
    "encrypt_s": round(t_enc, 1),
    "aggregate_s": round(t_agg, 1),
    "decrypt_s": round(t_dec, 1),
    "round_s": round(round_sec, 1),
    "max_err": err,
    "native_ntt": _load_ntt_native() is not None,
}), flush=True)
