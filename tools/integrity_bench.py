#!/usr/bin/env python
"""Update-integrity containment gates — one JSON line.

Three gates, matching the containment layer's cost/benefit contract
(``fedml_tpu/integrity``, docs/integrity.md):

- ``ok_seam``  — ring 1's admission screen costs < 2% of a round:
  the per-upload jitted screen program is micro-measured on a
  resnet-sized int8 delta, multiplied by the uploads per round, and
  compared against a measured clean federation round;
- ``ok_acc``   — a poisoned federation (NaN injection + magnitude
  poison at the comm seam) finishes within tolerance of the clean
  same-seed run: every corrupt upload screened or rolled back, the
  model unharmed;
- ``ok_mttr``  — a round rollback (reject → restore → re-run) lands
  inside its wall-clock budget, measured on a loss-spike scenario the
  screen deliberately admits.

Archived as ``INTEGRITY_r0N.json``; ``tools/bench_compare.py``'s
``compare_integrity`` fails any gate that goes false between archives
(and seam/MTTR regressions past 50%). Env knobs: ``FEDML_INTEGRITY_*``
(see ``_env`` below). Also reachable as ``python bench.py --integrity``.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _env(name: str, default, cast=float):
    raw = os.environ.get(f"FEDML_INTEGRITY_{name}")
    return cast(raw) if raw else default


def _screen_us(tree) -> float:
    """Steady-state per-upload cost of the jitted screen program."""
    from fedml_tpu.compression import derive_key, get_codec
    from fedml_tpu.integrity import screen_stats

    ct = get_codec("int8").encode(tree, key=derive_key(0, 0, 1),
                                  is_delta=True)
    screen_stats(ct)  # compile
    trials = 20
    t0 = time.perf_counter()
    for _ in range(trials):
        screen_stats(ct)
    return (time.perf_counter() - t0) / trials * 1e6


def measure_screen_seam(n_params: int, uploads_per_round: int,
                        round_wall_s: float, model_tree) -> dict:
    """The admission screen's cost against the round it protects.

    The GATED seam is honest about scale: it screens an upload of the
    MEASURED federation's own model shape against that federation's own
    round wall (a seam measured on an 11M-param tree against a tiny-lr
    round would compare two different workloads). The resnet-sized
    per-upload cost is reported alongside as the large-model data point
    — its round would be dominated by training, not screening.
    """
    from tools.wire_bench import make_resnet_sized_tree

    per_upload_us = _screen_us(model_tree)
    seam_pct = (per_upload_us * 1e-6 * uploads_per_round) / max(
        round_wall_s, 1e-9) * 100.0
    return {
        "screen_us_per_upload": round(per_upload_us, 1),
        "screen_us_per_upload_resnet": round(
            _screen_us(make_resnet_sized_tree(n_params)), 1),
        "screen_seam_pct": round(seam_pct, 3),
    }


def run_integrity_bench() -> dict:
    """Clean vs poisoned same-seed federations + screen seam + MTTR."""
    from fedml_tpu.resilience import run_chaos_scenario

    seed = _env("SEED", 11, int)
    rounds = _env("ROUNDS", 5, int)
    clients = _env("CLIENTS", 4, int)
    n_params = _env("PARAMS", 400_000, int)
    acc_tol = _env("ACC_TOL", 0.1)
    mttr_budget_s = _env("MTTR_BUDGET_S", 20.0)
    seam_budget_pct = _env("SEAM_BUDGET_PCT", 2.0)

    common = dict(seed=seed, rounds=rounds, clients=clients,
                  compression="int8", round_deadline_s=30.0,
                  round_quorum=0.5, timeout=180.0)

    t0 = time.perf_counter()
    clean = run_chaos_scenario(integrity=True, **common)
    clean_wall = time.perf_counter() - t0
    round_wall_s = clean_wall / max(rounds, 1)

    # the poisoned twin: NaN blocks at round 1, magnitude poison at
    # round 3 — both from the comm seam, both must be screened
    t0 = time.perf_counter()
    poisoned = run_chaos_scenario(
        integrity=True, corrupt_rank=2, corrupt_round=1,
        corrupt_mode="nan", **common)
    poisoned_wall = time.perf_counter() - t0
    scaled = run_chaos_scenario(
        integrity=True, corrupt_rank=min(3, clients), corrupt_round=3,
        corrupt_mode="scale", corrupt_factor=200.0, **common)

    # the measured federation's model shape (run_chaos_scenario's lr on
    # synthetic(feature_dim=10, class_num=4)) — what its uploads carry
    import numpy as np

    model_tree = {"w": np.zeros((10, 4), np.float32),
                  "b": np.zeros((4,), np.float32)}

    acc_clean = float((clean.get("result") or {}).get("test_acc") or 0.0)
    acc_nan = float((poisoned.get("result") or {}).get("test_acc") or 0.0)
    acc_scaled = float((scaled.get("result") or {}).get("test_acc") or 0.0)
    acc_poisoned = min(acc_nan, acc_scaled)
    screened = (poisoned["counters"].get("screened_uploads", 0)
                + scaled["counters"].get("screened_uploads", 0))
    rollbacks = (poisoned["counters"].get("rollbacks", 0)
                 + scaled["counters"].get("rollbacks", 0))

    seam = measure_screen_seam(n_params, clients, round_wall_s,
                               model_tree)

    # rollback MTTR: reject → restore → re-run, measured on an sp
    # loss-spike run the screen deliberately admits (huge thresholds);
    # the poisoned run's extra wall over its clean twin, per rollback
    mttr = measure_rollback_mttr(seed)

    ok_seam = seam["screen_seam_pct"] < seam_budget_pct
    ok_acc = (clean.get("completed") and poisoned.get("completed")
              and scaled.get("completed")
              and screened + rollbacks >= 1
              and abs(acc_clean - acc_poisoned) <= acc_tol)
    ok_mttr = (mttr["rollbacks"] >= 1
               and mttr["mttr_s"] <= mttr_budget_s)
    return {
        "metric": "integrity_screen_seam_pct",
        "value": seam["screen_seam_pct"],
        "unit": "%",
        "ok": bool(ok_seam and ok_acc and ok_mttr),
        "ok_seam": bool(ok_seam),
        "ok_acc": bool(ok_acc),
        "ok_mttr": bool(ok_mttr),
        **seam,
        "seam_budget_pct": seam_budget_pct,
        "round_wall_s": round(round_wall_s, 3),
        "acc_clean": round(acc_clean, 4),
        "acc_poisoned_nan": round(acc_nan, 4),
        "acc_poisoned_scale": round(acc_scaled, 4),
        "acc_tol": acc_tol,
        "screened_uploads": screened,
        "quarantined": (poisoned["counters"].get("quarantined", 0)
                        + scaled["counters"].get("quarantined", 0)),
        "mttr_s": mttr["mttr_s"],
        "rollbacks": mttr["rollbacks"],
        "mttr_budget_s": mttr_budget_s,
        "clean_wall_s": round(clean_wall, 3),
        "poisoned_wall_s": round(poisoned_wall, 3),
    }


def measure_rollback_mttr(seed: int) -> dict:
    """Time one full ring-3 rollback: reject → restore → re-run round.

    An sp federation with screen thresholds opened wide (the poison must
    reach the aggregate) and a loss-spiking client at round 2; MTTR is
    the wall from the rejection to the re-run round's acceptance,
    measured around the guarded section itself.
    """
    import jax

    import fedml_tpu
    from fedml_tpu import device as device_mod
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data import load_federated
    from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI

    cfg = {
        "common_args": {"training_type": "simulation",
                        "random_seed": int(seed),
                        "run_id": f"integrity_bench_{seed}"},
        "data_args": {"dataset": "synthetic", "partition_method": "hetero",
                      "partition_alpha": 0.5, "train_size": 400,
                      "test_size": 100, "class_num": 4, "feature_dim": 16},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg", "client_num_in_total": 5,
            "client_num_per_round": 5, "comm_round": 4, "epochs": 1,
            "batch_size": 32, "learning_rate": 0.3,
            "compression": "identity", "integrity": True,
            "integrity_norm_mult": 1e9, "integrity_z_threshold": 1e9,
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    device = device_mod.get_device(args)
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    api = FedAvgAPI(args, device, ds, model)

    inner = api.trainer

    class _Poison:
        """Client 3 runs gradient ascent at round 2 — finite, admitted
        by the wide-open screen, rejected by the loss-spike guard."""

        def __init__(self):
            self.cid = None
            self.rnd = None

        def __getattr__(self, k):
            return getattr(inner, k)

        def set_id(self, cid):
            self.cid = cid
            inner.set_id(cid)

        def set_round(self, r):
            self.rnd = r
            inner.set_round(r)

        def run_local_training(self, params, data, device, args):
            w, m = inner.run_local_training(params, data, device, args)
            if self.cid == 3 and self.rnd == 2:
                w = jax.tree.map(lambda g, x: g + 50.0 * (g - x),
                                 params, w)
            return w, m

    api.trainer = _Poison()
    marks = {}
    orig_rollback = api._rollback_round

    def timed_rollback(round_idx, reason, client_ids):
        marks["rejected_at"] = time.perf_counter()
        return orig_rollback(round_idx, reason, client_ids)

    api._rollback_round = timed_rollback
    orig_accept = api._guard.accept

    def timed_accept(loss=None):
        if "rejected_at" in marks and "resumed_at" not in marks:
            marks["resumed_at"] = time.perf_counter()
        return orig_accept(loss)

    api._guard.accept = timed_accept
    api.train()
    rollbacks = api._guard.total_rollbacks
    mttr_s = (marks["resumed_at"] - marks["rejected_at"]
              if "resumed_at" in marks and "rejected_at" in marks
              else float("inf"))
    return {"mttr_s": round(mttr_s, 3), "rollbacks": int(rollbacks)}


def main() -> int:
    row = run_integrity_bench()
    print(json.dumps(row))
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
