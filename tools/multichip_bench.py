#!/usr/bin/env python
"""Multi-chip scale-out bench — ONE JSON line (``bench.py --multichip``).

Sweeps the fused federated LLM round over mesh sizes N = 1, 2, 4, …
(power-of-two prefixes of the available devices) and reports **scaling
efficiency** plus the **per-shard HBM plan** of the sharded round:

- N = 1 runs the sequential fused round (``llm/fused_round``) — the
  single-chip reference every larger mesh is judged against;
- N > 1 runs the client-parallel round (``llm/fused_round_cp``): client
  slots ride the mesh's ``dp`` axis, the frozen base is fsdp-sharded,
  and the adapter FedAvg is the round's one cross-lane all-reduce (see
  ``LLMTrainer.compile_federated_round_cp``). The mesh shape per N comes
  from :func:`fedml_tpu.parallel.multichip.plan_multichip` — the same
  planner that depth-reduces on a single-core virtual mesh instead of
  letting XLA:CPU's 40 s collective-rendezvous timer abort the run.

Efficiency basis (recorded as ``efficiency_basis``): on real multi-chip
hardware, ``wall_1 / (N * wall_N)`` — the classic fraction of linear
speedup. On a single-core VIRTUAL mesh (CI, this box) N devices
time-share one core, so N-fold speedup is physically impossible and the
honest basis is ``wall_1 / wall_N`` (**serialized-virtual-mesh**): a
perfect partition costs the same total compute as one device, so 1.0 is
ideal and the ratio measures pure partition overhead — the collectives,
layout shuffles and lane bookkeeping the sharding added.

Gates: efficiency at the largest measured N ≥ ``FEDML_MULTICHIP_MIN_EFF``
(default 0.7), and the catalog's per-shard peak-HBM plan of the sharded
round under the per-device limit (nominal-pass when the backend reports
no limit, e.g. XLA:CPU — the *planned* bytes still ride the record).

The emitted row (``metric: multichip_scaling_efficiency``) is archived
as ``MULTICHIP_r06.json`` and diffed by ``tools/bench_compare.py
compare_multichip``; seed-era ``MULTICHIP_r0*.json`` files are rc-only
dry-run wrappers with no headline metric and skip naturally.

Env knobs: ``FEDML_MULTICHIP_DEVICES`` (sweep ceiling, default 4),
``FEDML_MULTICHIP_STEPS`` / ``FEDML_MULTICHIP_CLIENTS`` (round shape),
``FEDML_MULTICHIP_MIN_EFF``, ``FEDML_MULTICHIP_OUT`` (artifact path;
empty string disables the write).
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = ["run_multichip_bench", "main"]


def _ensure_devices(n: int):
    """At least ``n`` devices, provisioning XLA:CPU virtual devices when
    possible. XLA parses ``XLA_FLAGS`` exactly ONCE, at the first backend
    init — so the count flag is planted before the first device query
    ever happens in this process (harmless on real accelerators: it only
    affects the host CPU platform). If a backend is already live with
    fewer devices (e.g. called from a test harness), the sweep simply
    adapts to what exists — never hangs, never aborts."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    return jax.devices()


def _round_wall(fed, trainer, data, n_short: int = 1, n_long: int = 5,
                trials: int = 3) -> float:
    """Seconds/round via the long-minus-short chained-readback method
    (same rationale as ``bench.chain_time``: the fixed dispatch+readback
    round-trip cancels in the difference; donated buffers chain rounds
    by construction)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.train.llm.trainer import extract_lora

    xs, ys, ms, w, opt0 = data

    def chain(n: int) -> float:
        p = jax.tree.map(jnp.copy, trainer.params)
        o = jax.tree.map(jnp.copy, opt0)
        g = jax.tree.map(jnp.copy, extract_lora(trainer.params))
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            p, o, g, loss = fed(p, o, g, xs, ys, ms, w)
        float(loss)
        return time.perf_counter() - t0

    chain(n_short)  # throwaway: absorbs the compile
    best = float("inf")
    for _ in range(trials):
        t_short = chain(n_short)
        t_long = chain(n_long)
        est = (t_long - t_short) / (n_long - n_short)
        if est > 0:
            best = min(best, est)
    if best == float("inf"):  # noise swamped the difference; fall back
        best = chain(n_long) / n_long
    return best


def run_multichip_bench() -> Dict:
    max_devices = int(os.environ.get("FEDML_MULTICHIP_DEVICES", "4"))
    n_clients = int(os.environ.get("FEDML_MULTICHIP_CLIENTS", "8"))
    local_steps = int(os.environ.get("FEDML_MULTICHIP_STEPS", "1"))
    min_eff = float(os.environ.get("FEDML_MULTICHIP_MIN_EFF", "0.7"))

    devices = _ensure_devices(max_devices)
    import jax
    import numpy as np

    from fedml_tpu.models.llm.llama import LlamaConfig
    from fedml_tpu.parallel.multichip import (
        is_single_core_virtual_mesh,
        plan_multichip,
    )
    from fedml_tpu.telemetry.profiling import get_catalog
    from fedml_tpu.train.llm.sharding import make_mesh
    from fedml_tpu.train.llm.trainer import LLMTrainer

    try:
        hbm_limit = float(devices[0].memory_stats()["bytes_limit"])
    except Exception:
        hbm_limit = 16e9 if devices[0].platform == "tpu" else 0.0

    sweep: List[int] = []
    n = 1
    while n <= min(max_devices, len(devices)):
        sweep.append(n)
        n *= 2
    if len(sweep) < 2:
        # a 1-device environment cannot measure scaling — skip with a
        # pointed message rather than emit a meaningless gate failure
        return {
            "metric": "multichip_scaling_efficiency",
            "value": None, "unit": "ratio", "ok": True, "skipped": True,
            "note": (f"only {len(devices)} device(s) visible and the "
                     "backend was initialized before the virtual-device "
                     "flag could land — run bench.py --multichip in a "
                     "fresh process (or on a multi-chip host) to measure "
                     "scaling"),
            "n_devices": len(devices),
        }

    cfg = LlamaConfig.tiny(lora_rank=4, use_flash=False)
    batch, seq = 4, 32
    virtual = is_single_core_virtual_mesh(sweep[-1])
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size,
                        size=(n_clients, local_steps, batch, seq),
                        dtype=np.int32)

    walls: Dict[int, float] = {}
    plans: Dict[int, Dict] = {}
    param_bytes = 0.0
    for nd in sweep:
        plan = plan_multichip(nd, n_layers=cfg.num_hidden_layers,
                              param_bytes=param_bytes,
                              hbm_limit_bytes=hbm_limit)
        mesh = make_mesh(dp=plan.dp, fsdp=plan.fsdp,
                         devices=list(devices[:nd]))

        class _A:
            max_seq_length = seq
            per_device_batch_size = batch
            gradient_accumulation_steps = 1
            learning_rate = 1e-3
            random_seed = 0

        tr = LLMTrainer(cfg, _A(), mesh=mesh)
        tr.init(seed=0)
        if param_bytes == 0.0:
            param_bytes = float(sum(
                v.size * v.dtype.itemsize for v in jax.tree.leaves(tr.params)))
        cp = plan.dp
        xs = toks.reshape(n_clients // cp, cp, local_steps, batch, seq)
        ys = (xs + 1) % cfg.vocab_size
        ms = np.ones((n_clients // cp, cp, local_steps, batch), np.float32)
        w = np.ones((n_clients // cp, cp), np.float32)
        if cp > 1:
            fed = tr.compile_federated_round_cp(n_clients, local_steps, cp)
            opt0, _ = tr.lane_opt_state(cp)
        else:
            fed = tr.compile_federated_round(n_clients, local_steps)
            xs, ys = xs[:, 0], ys[:, 0]
            ms, w = ms[:, 0], w[:, 0]
            opt0 = tr.opt_state
        walls[nd] = _round_wall(fed, tr, (xs, ys, ms, w, opt0))
        plans[nd] = {"dp": plan.dp, "fsdp": plan.fsdp,
                     "n_layers": plan.n_layers,
                     "depth_reduced": plan.depth_reduced}
        del tr, fed

    # efficiency per N against the 1-device reference (see module
    # docstring for the virtual-mesh basis)
    basis = "serialized-virtual-mesh" if virtual else "wall-clock"
    eff = {
        nd: (walls[1] / walls[nd] if virtual
             else walls[1] / (nd * walls[nd]))
        for nd in sweep if nd > 1
    }
    top_n = sweep[-1]
    top_eff = eff.get(top_n)

    programs = get_catalog().programs_summary()
    cp_rec = programs.get("llm/fused_round_cp") or {}
    per_shard_hbm = float(cp_rec.get("peak_hbm_bytes") or 0.0)
    mesh_spec = cp_rec.get("mesh_spec")
    ok_hbm = (per_shard_hbm < hbm_limit) if hbm_limit else True
    ok_scaling = top_eff is not None and top_eff >= min_eff

    return {
        "metric": "multichip_scaling_efficiency",
        "value": round(top_eff, 4) if top_eff is not None else None,
        "unit": "ratio",
        "ok": bool(ok_scaling and ok_hbm),
        "ok_scaling": bool(ok_scaling),
        "ok_hbm": bool(ok_hbm),
        "efficiency_basis": basis,
        "min_efficiency": min_eff,
        "n_devices": top_n,
        "virtual_mesh": bool(virtual),
        "n_clients": n_clients,
        "local_steps": local_steps,
        "extra": {
            "rounds_per_sec": {
                str(nd): round(1.0 / walls[nd], 4) for nd in sweep},
            "round_wall_s": {str(nd): round(walls[nd], 4) for nd in sweep},
            "efficiency": {str(nd): round(v, 4) for nd, v in eff.items()},
            "mesh_plans": {str(nd): plans[nd] for nd in sweep},
            "per_shard_peak_hbm_bytes": per_shard_hbm,
            "hbm_limit_bytes": hbm_limit,
            "mesh_spec": mesh_spec,
            "param_bytes": param_bytes,
        },
    }


def write_artifact(row: Dict, bench_dir: Optional[str] = None) -> Optional[str]:
    """Archive the emitted row as ``MULTICHIP_r06.json`` (measured
    headline schema — retires the seed-era rc-only dry-run wrappers as
    the compare baseline). ``FEDML_MULTICHIP_OUT=''`` disables."""
    name = os.environ.get("FEDML_MULTICHIP_OUT", "MULTICHIP_r06.json")
    if not name:
        return None
    bench_dir = bench_dir or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    path = os.path.join(bench_dir, name)
    with open(path, "w") as f:
        json.dump(row, f, indent=1)
        f.write("\n")
    return path


def main() -> int:
    row = run_multichip_bench()
    write_artifact(row)
    print(json.dumps(row))  # noqa: T201 (CLI output)
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
