#!/usr/bin/env python
"""Compare the newest two ``BENCH_*.json`` headline metrics.

The bench driver emits ONE JSON line (``{"metric", "value", ...}``) and
the round harness archives it — either as that raw object or wrapped in a
``{"n", "cmd", "rc", "tail"}`` record whose ``tail`` holds the emitted
line among log noise. This tool accepts both shapes, diffs the newest
two files (natural name order — ``BENCH_r99`` < ``BENCH_r100``), and
fails when the headline metric regressed by more than ``threshold``
(10% default).

Exit codes: 0 = ok / nothing to compare, 1 = regression. Wired as
``bench.py --compare`` so CI can gate a perf PR with one invocation.
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Dict, Optional, Tuple

__all__ = ["load_headline", "run_compare", "main"]


def _natural_key(path: str):
    """Numeric-aware sort key: BENCH_r100 comes after BENCH_r99, not
    between r10 and r11 as a plain lexicographic sort would put it."""
    name = os.path.basename(path)
    return [int(p) if p.isdigit() else p
            for p in re.split(r"(\d+)", name)]


def load_headline(path: str) -> Optional[Tuple[str, float]]:
    """(metric, value) from a BENCH file, or None if unrecognizable."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(obj, dict) and "metric" in obj and "value" in obj:
        return str(obj["metric"]), float(obj["value"])
    # harness-wrapped shape: the emitted line is the LAST parseable JSON
    # object in the captured tail
    tail = obj.get("tail") if isinstance(obj, dict) else None
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec and "value" in rec:
                return str(rec["metric"]), float(rec["value"])
    return None


def run_compare(bench_dir: str = ".", threshold: float = 0.10,
                pattern: str = "BENCH_*.json") -> Dict:
    """Diff the newest two BENCH files; ``ok`` is False only on a real,
    same-metric regression past the threshold."""
    files = sorted(glob.glob(os.path.join(bench_dir, pattern)),
                   key=_natural_key)
    if len(files) < 2:
        return {"ok": True,
                "note": f"need at least two {pattern} files to compare "
                        f"(found {len(files)})"}
    prev_path, new_path = files[-2], files[-1]
    prev = load_headline(prev_path)
    new = load_headline(new_path)
    if prev is None or new is None:
        bad = prev_path if prev is None else new_path
        return {"ok": True,
                "note": f"no headline metric parseable from {bad}"}
    (prev_metric, prev_value), (new_metric, new_value) = prev, new
    if prev_metric != new_metric:
        return {"ok": True,
                "note": f"metric changed ({prev_metric} -> {new_metric}); "
                        "not comparable",
                "prev_file": prev_path, "new_file": new_path}
    delta = ((new_value - prev_value) / prev_value if prev_value
             else 0.0)
    return {
        "ok": delta >= -threshold,
        "metric": new_metric,
        "prev_file": os.path.basename(prev_path),
        "new_file": os.path.basename(new_path),
        "prev_value": prev_value,
        "new_value": new_value,
        "delta_pct": round(delta * 100.0, 2),
        "threshold_pct": round(threshold * 100.0, 2),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    bench_dir = "."
    threshold = 0.10
    i = 0
    while i < len(argv):
        if argv[i] == "--dir" and i + 1 < len(argv):
            bench_dir = argv[i + 1]
            i += 2
        elif argv[i] == "--threshold" and i + 1 < len(argv):
            threshold = float(argv[i + 1])
            i += 2
        else:
            i += 1
    row = run_compare(bench_dir, threshold)
    print(json.dumps(row))
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
