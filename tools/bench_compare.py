#!/usr/bin/env python
"""Compare the newest two ``BENCH_*.json`` headline metrics.

The bench driver emits ONE JSON line (``{"metric", "value", ...}``) and
the round harness archives it — either as that raw object or wrapped in a
``{"n", "cmd", "rc", "tail"}`` record whose ``tail`` holds the emitted
line among log noise. This tool accepts both shapes, diffs the newest
two files (natural name order — ``BENCH_r99`` < ``BENCH_r100``), and
fails when the headline metric regressed by more than ``threshold``
(10% default).

Exit codes: 0 = ok / nothing to compare, 1 = regression. Wired as
``bench.py --compare`` so CI can gate a perf PR with one invocation.
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["compare_fa", "compare_integrity", "compare_multichip",
           "compare_preempt", "compare_recover", "compare_serve",
           "compare_wire", "load_headline", "run_compare", "main"]


def _natural_key(path: str):
    """Numeric-aware sort key: BENCH_r100 comes after BENCH_r99, not
    between r10 and r11 as a plain lexicographic sort would put it."""
    name = os.path.basename(path)
    return [int(p) if p.isdigit() else p
            for p in re.split(r"(\d+)", name)]


def _load_record(path: str) -> Optional[Dict]:
    """The full emitted bench record from a BENCH file (raw or
    harness-wrapped), or None if unrecognizable."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(obj, dict) and "metric" in obj and "value" in obj:
        return obj
    # harness-wrapped shape: the emitted line is the LAST parseable JSON
    # object in the captured tail
    tail = obj.get("tail") if isinstance(obj, dict) else None
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec and "value" in rec:
                return rec
    return None


def load_headline(path: str) -> Optional[Tuple[str, float]]:
    """(metric, value) from a BENCH file, or None if unrecognizable."""
    rec = _load_record(path)
    if rec is None:
        return None
    return str(rec["metric"]), float(rec["value"])


def compare_programs(prev_rec: Optional[Dict], new_rec: Optional[Dict],
                     threshold: float) -> List[Dict]:
    """Per-program regressions between two bench records' program
    catalogs (``extra.programs``: name → flops/bytes/peak-HBM).

    Flags, per program present in BOTH records: peak-HBM growth past the
    threshold (the multichip headroom eroding), program FLOPs growth past
    the threshold (the compiled program itself got more expensive — an
    MFU regression at fixed wall), and new recompiles (treedef churn
    landing where there was none). Whole-run MFU is diffed by the caller
    off ``extra.mfu``."""
    out: List[Dict] = []
    prev_p = ((prev_rec or {}).get("extra") or {}).get("programs") or {}
    new_p = ((new_rec or {}).get("extra") or {}).get("programs") or {}
    for name in sorted(set(prev_p) & set(new_p)):
        a, b = prev_p[name], new_p[name]
        for field, label in (("peak_hbm_bytes", "peak HBM"),
                             ("flops", "flops")):
            pa = float(a.get(field) or 0.0)
            pb = float(b.get(field) or 0.0)
            if pa > 0 and pb > pa * (1.0 + threshold):
                out.append({
                    "program": name, "field": field,
                    "prev": pa, "new": pb,
                    "delta_pct": round((pb - pa) / pa * 100.0, 2),
                    "note": f"{label} grew {((pb - pa) / pa) * 100:.1f}%",
                })
        ra = int(a.get("recompiles") or 0)
        rb = int(b.get("recompiles") or 0)
        # multi_shape programs (serve/decode_group's per-group-size
        # variants, eval over several test shapes) legitimately grow
        # variants — same exemption the doctor's churn verdict applies
        if rb > ra and not (a.get("multi_shape") or b.get("multi_shape")):
            out.append({
                "program": name, "field": "recompiles",
                "prev": ra, "new": rb, "delta_pct": None,
                "note": f"recompiles {ra} -> {rb} (treedef churn)",
            })
    return out


def compare_recover(bench_dir: str = ".",
                    mttr_threshold: float = 0.50) -> Optional[Dict]:
    """Diff the newest two ``RECOVER_*.json`` recovery-bench records.

    Fails on an MTTR regression past ``mttr_threshold`` (lower is
    better; the default is loose — restart time includes a process spawn
    and is noisier than a throughput metric) and on any recovery GATE
    going false where it was true: lost salvaged uploads or broken
    bit-identity are correctness regressions at ANY magnitude, not a
    threshold call. None when fewer than two files exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "RECOVER_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None
    prev_rec = _load_record(files[-2])
    new_rec = _load_record(files[-1])
    if prev_rec is None or new_rec is None:
        return {"ok": True,
                "note": "no parseable recover record in "
                        f"{files[-2] if prev_rec is None else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    prev_mttr = prev_rec.get("mttr_s")
    new_mttr = new_rec.get("mttr_s")
    if prev_mttr and new_mttr is not None:
        delta = (float(new_mttr) - float(prev_mttr)) / float(prev_mttr)
        out["mttr_prev_s"] = prev_mttr
        out["mttr_new_s"] = new_mttr
        out["mttr_delta_pct"] = round(delta * 100.0, 2)
        if delta > mttr_threshold:
            out["regressions"].append(
                f"MTTR regressed {delta * 100:.1f}% "
                f"({prev_mttr}s -> {new_mttr}s)")
    for gate in ("bit_identical", "no_retrain_of_salvaged",
                 "ok_salvaged", "ok_seam"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"recovery gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_preempt(bench_dir: str = ".",
                    mttr_threshold: float = 0.50) -> Optional[Dict]:
    """Diff the newest two ``PREEMPT_*.json`` job-plane bench records.

    Same contract as :func:`compare_recover`: an MTTR regression past
    ``mttr_threshold`` fails, and ANY gate going false where it was true
    — lost salvage, broken bit-identity, a crasher no longer contained —
    is a correctness regression at any magnitude. None when fewer than
    two files exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "PREEMPT_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None
    prev_rec = _load_record(files[-2])
    new_rec = _load_record(files[-1])
    if prev_rec is None or new_rec is None:
        return {"ok": True,
                "note": "no parseable preempt record in "
                        f"{files[-2] if prev_rec is None else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    prev_mttr = prev_rec.get("mttr_s")
    new_mttr = new_rec.get("mttr_s")
    if prev_mttr and new_mttr is not None:
        delta = (float(new_mttr) - float(prev_mttr)) / float(prev_mttr)
        out["mttr_prev_s"] = prev_mttr
        out["mttr_new_s"] = new_mttr
        out["mttr_delta_pct"] = round(delta * 100.0, 2)
        if delta > mttr_threshold:
            out["regressions"].append(
                f"preempt MTTR regressed {delta * 100:.1f}% "
                f"({prev_mttr}s -> {new_mttr}s)")
    for gate in ("bit_identical", "no_retrain_of_salvaged", "ok_salvaged",
                 "ok_contained", "ok_completed"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"preempt gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_integrity(bench_dir: str = ".",
                      regression_threshold: float = 0.50) -> Optional[Dict]:
    """Diff the newest two ``INTEGRITY_*.json`` containment records.

    Same contract as :func:`compare_recover`: any GATE going false where
    it was true (screen seam blown, poisoned accuracy out of tolerance,
    rollback MTTR over budget) is a regression at any magnitude, and the
    seam/MTTR numbers themselves fail past ``regression_threshold`` —
    a screen that got 50% slower is eating the round it protects. None
    when fewer than two files exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "INTEGRITY_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None
    prev_rec = _load_record(files[-2])
    new_rec = _load_record(files[-1])
    if prev_rec is None or new_rec is None:
        return {"ok": True,
                "note": "no parseable integrity record in "
                        f"{files[-2] if prev_rec is None else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    for field, label in (("screen_seam_pct", "screen seam"),
                         ("mttr_s", "rollback MTTR")):
        prev_v = prev_rec.get(field)
        new_v = new_rec.get(field)
        if prev_v and new_v is not None:
            delta = (float(new_v) - float(prev_v)) / float(prev_v)
            out[f"{field}_prev"] = prev_v
            out[f"{field}_new"] = new_v
            if delta > regression_threshold:
                out["regressions"].append(
                    f"{label} regressed {delta * 100:.1f}% "
                    f"({prev_v} -> {new_v})")
    for gate in ("ok_seam", "ok_acc", "ok_mttr"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"integrity gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_wire(bench_dir: str = ".",
                 regression_threshold: float = 0.10) -> Optional[Dict]:
    """Diff the newest two ``WIRE_*.json`` transport-bench archives.

    Each archive holds ``{"metric": "wire_bytes_per_codec", "rows":
    [...]}`` (the ``bench.py --wire`` rows). Flags, per codec present in
    BOTH archives, a compression-ratio drop past
    ``regression_threshold`` — the wire got fatter for the same tree —
    and any 4-bit ratio GATE (``ok_ratio_f32``/``ok_ratio_int8``) going
    false where it was true, at any magnitude. None when fewer than two
    archives exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "WIRE_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None

    def _rows(path: str) -> Dict[str, Dict]:
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError):
            return {}
        rows = obj.get("rows") if isinstance(obj, dict) else obj
        if not isinstance(rows, list):
            return {}
        return {str(r.get("codec")): r for r in rows
                if isinstance(r, dict) and r.get("ratio")}

    prev_rows = _rows(files[-2])
    new_rows = _rows(files[-1])
    if not prev_rows or not new_rows:
        return {"ok": True,
                "note": "no parseable wire rows in "
                        f"{files[-2] if not prev_rows else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    for codec in sorted(set(prev_rows) & set(new_rows)):
        pa = float(prev_rows[codec]["ratio"])
        pb = float(new_rows[codec]["ratio"])
        if pa > 0 and (pa - pb) / pa > regression_threshold:
            out["regressions"].append(
                f"codec {codec} wire ratio regressed "
                f"{(pa - pb) / pa * 100:.1f}% ({pa} -> {pb})")
        for gate in ("ok_ratio_f32", "ok_ratio_int8"):
            if (prev_rows[codec].get(gate) is True
                    and new_rows[codec].get(gate) is False):
                out["regressions"].append(
                    f"codec {codec} gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_serve(bench_dir: str = ".",
                  regression_threshold: float = 0.25) -> Optional[Dict]:
    """Diff the newest two ``SERVE_*.json`` serving-bench records.

    Same contract as :func:`compare_recover`: any GATE going false where
    it was true (dropped requests, missed swaps, a host-side f32 tree on
    the staging path, the p99-vs-baseline SLO, the request-observability
    overhead seam) is a regression at any magnitude, and the latency/
    throughput numbers themselves fail past ``regression_threshold`` —
    loose by default, serving percentiles on a shared CPU box are
    noisier than throughput metrics. None when fewer than two files
    exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "SERVE_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None
    prev_rec = _load_record(files[-2])
    new_rec = _load_record(files[-1])
    if prev_rec is None or new_rec is None:
        return {"ok": True,
                "note": "no parseable serve record in "
                        f"{files[-2] if prev_rec is None else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    # higher-is-worse latency fields + lower-is-worse qps
    for field, label in (("p95_ms", "swap-window p95"),
                         ("p99_ms", "swap-window p99"),
                         ("ttft_p95_ms", "TTFT p95"),
                         ("tpot_p95_ms", "TPOT p95")):
        prev_v = prev_rec.get(field)
        new_v = new_rec.get(field)
        if prev_v and new_v is not None:
            delta = (float(new_v) - float(prev_v)) / float(prev_v)
            out[f"{field}_prev"] = prev_v
            out[f"{field}_new"] = new_v
            if delta > regression_threshold:
                out["regressions"].append(
                    f"{label} regressed {delta * 100:.1f}% "
                    f"({prev_v} -> {new_v} ms)")
    prev_qps, new_qps = prev_rec.get("qps"), new_rec.get("qps")
    if prev_qps and new_qps is not None:
        delta = (float(new_qps) - float(prev_qps)) / float(prev_qps)
        out["qps_prev"] = prev_qps
        out["qps_new"] = new_qps
        if delta < -regression_threshold:
            out["regressions"].append(
                f"swap-window qps regressed {-delta * 100:.1f}% "
                f"({prev_qps} -> {new_qps})")
    for gate in ("ok_dropped", "ok_swaps", "ok_no_host_f32", "ok_p99",
                 "ok_obs_overhead"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"serve gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_multichip(bench_dir: str = ".",
                      regression_threshold: float = 0.10) -> Optional[Dict]:
    """Diff the newest two parseable ``MULTICHIP_*.json`` scale-out
    records.

    Seed-era MULTICHIP files are rc-only dry-run wrappers with no
    headline metric — they are SKIPPED (not compared against, not
    crashed on); only measured rows (``tools/multichip_bench.py``
    schema) participate. Fails on a scaling-efficiency regression past
    ``regression_threshold`` or on any gate (``ok_scaling``/``ok_hbm``)
    going false where it was true. Efficiency values are only
    comparable on the same basis — a basis change (virtual mesh ↔ real
    chips) skips the threshold check and diffs gates alone. None when
    fewer than two parseable records exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "MULTICHIP_*.json")),
                   key=_natural_key)
    parseable = [(f, rec) for f in files
                 if (rec := _load_record(f)) is not None]
    if len(parseable) < 2:
        return None
    (prev_path, prev_rec), (new_path, new_rec) = parseable[-2:]
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(prev_path),
        "new_file": os.path.basename(new_path),
        "skipped_files": len(files) - len(parseable),
        "regressions": [],
    }
    prev_eff, new_eff = prev_rec.get("value"), new_rec.get("value")
    same_basis = (prev_rec.get("efficiency_basis")
                  == new_rec.get("efficiency_basis"))
    if prev_eff and new_eff is not None and same_basis:
        delta = (float(new_eff) - float(prev_eff)) / float(prev_eff)
        out["efficiency_prev"] = prev_eff
        out["efficiency_new"] = new_eff
        out["efficiency_delta_pct"] = round(delta * 100.0, 2)
        if delta < -regression_threshold:
            out["regressions"].append(
                f"scaling efficiency regressed {-delta * 100:.1f}% "
                f"({prev_eff} -> {new_eff})")
    elif not same_basis:
        out["note"] = (
            f"efficiency basis changed "
            f"({prev_rec.get('efficiency_basis')} -> "
            f"{new_rec.get('efficiency_basis')}); gates only")
    for gate in ("ok_scaling", "ok_hbm"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"multichip gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def compare_fa(bench_dir: str = ".",
               regression_threshold: float = 0.10) -> Optional[Dict]:
    """Diff the newest two ``FA_*.json`` federated-analytics records.

    Same contract as :func:`compare_recover`: a gate going false where
    it was true (wire overhead, HH recall/precision vs the plaintext
    reference, the traced-client-sketch proof) is a regression at any
    magnitude; the tree federation's rounds/s and the recall number
    itself fail past ``regression_threshold``. None when fewer than two
    files exist."""
    files = sorted(glob.glob(os.path.join(bench_dir, "FA_*.json")),
                   key=_natural_key)
    if len(files) < 2:
        return None
    prev_rec = _load_record(files[-2])
    new_rec = _load_record(files[-1])
    if prev_rec is None or new_rec is None:
        return {"ok": True,
                "note": "no parseable fa record in "
                        f"{files[-2] if prev_rec is None else files[-1]}"}
    out: Dict = {
        "ok": True,
        "prev_file": os.path.basename(files[-2]),
        "new_file": os.path.basename(files[-1]),
        "regressions": [],
    }
    for field, label in (("rounds_per_s", "tree federation rounds/s"),
                         ("hh_recall", "heavy-hitter recall"),
                         ("hh_precision", "heavy-hitter precision"),
                         ("fsm_rounds_per_s", "FSM rounds/s")):
        prev_v = prev_rec.get(field)
        new_v = new_rec.get(field)
        if prev_v and new_v is not None:
            delta = (float(new_v) - float(prev_v)) / float(prev_v)
            out[f"{field}_prev"] = prev_v
            out[f"{field}_new"] = new_v
            if delta < -regression_threshold:
                out["regressions"].append(
                    f"{label} regressed {-delta * 100:.1f}% "
                    f"({prev_v} -> {new_v})")
    prev_w, new_w = prev_rec.get("wire_overhead"), \
        new_rec.get("wire_overhead")
    if prev_w and new_w is not None:
        out["wire_overhead_prev"] = prev_w
        out["wire_overhead_new"] = new_w
        if (float(new_w) - float(prev_w)) / float(prev_w) \
                > regression_threshold:
            out["regressions"].append(
                f"masked wire overhead grew ({prev_w} -> {new_w})")
    for gate in ("ok_wire", "ok_recall", "ok_traced", "completed"):
        if prev_rec.get(gate) is True and new_rec.get(gate) is False:
            out["regressions"].append(f"fa gate {gate} went false")
    out["ok"] = not out["regressions"]
    return out


def run_compare(bench_dir: str = ".", threshold: float = 0.10,
                pattern: str = "BENCH_*.json") -> Dict:
    """Diff the newest two BENCH files; ``ok`` is False only on a real,
    same-metric regression past the threshold."""
    files = sorted(glob.glob(os.path.join(bench_dir, pattern)),
                   key=_natural_key)
    if len(files) < 2:
        return {"ok": True,
                "note": f"need at least two {pattern} files to compare "
                        f"(found {len(files)})"}
    prev_path, new_path = files[-2], files[-1]
    prev = load_headline(prev_path)
    new = load_headline(new_path)
    if prev is None or new is None:
        bad = prev_path if prev is None else new_path
        return {"ok": True,
                "note": f"no headline metric parseable from {bad}"}
    (prev_metric, prev_value), (new_metric, new_value) = prev, new
    if prev_metric != new_metric:
        return {"ok": True,
                "note": f"metric changed ({prev_metric} -> {new_metric}); "
                        "not comparable",
                "prev_file": prev_path, "new_file": new_path}
    delta = ((new_value - prev_value) / prev_value if prev_value
             else 0.0)
    # per-program attribution diff: regressions named by PROGRAM, not
    # just whole-run rounds/s (the program catalog rides extra.programs)
    prev_rec = _load_record(prev_path)
    new_rec = _load_record(new_path)
    program_regressions = compare_programs(prev_rec, new_rec, threshold)
    mfu_prev = ((prev_rec or {}).get("extra") or {}).get("mfu")
    mfu_new = ((new_rec or {}).get("extra") or {}).get("mfu")
    mfu_delta = None
    if mfu_prev and mfu_new is not None:
        mfu_delta = (float(mfu_new) - float(mfu_prev)) / float(mfu_prev)
        if mfu_delta < -threshold:
            program_regressions.append({
                "program": "<whole-run>", "field": "mfu",
                "prev": mfu_prev, "new": mfu_new,
                "delta_pct": round(mfu_delta * 100.0, 2),
                "note": f"whole-run MFU dropped {-mfu_delta * 100:.1f}%",
            })
    # recovery/preempt-bench gates ride the same invocation: an MTTR
    # regression or a lost-salvage/bit-identity/containment break between
    # archived RECOVER_*/PREEMPT_* runs fails the compare exactly like a
    # rounds/s drop
    recover = compare_recover(bench_dir)
    preempt = compare_preempt(bench_dir)
    integrity = compare_integrity(bench_dir)
    multichip = compare_multichip(bench_dir)
    wire = compare_wire(bench_dir, threshold)
    serve = compare_serve(bench_dir)
    fa = compare_fa(bench_dir, threshold)
    return {
        "ok": (delta >= -threshold and not program_regressions
               and (recover is None or recover["ok"])
               and (preempt is None or preempt["ok"])
               and (integrity is None or integrity["ok"])
               and (multichip is None or multichip["ok"])
               and (wire is None or wire["ok"])
               and (serve is None or serve["ok"])
               and (fa is None or fa["ok"])),
        "metric": new_metric,
        "prev_file": os.path.basename(prev_path),
        "new_file": os.path.basename(new_path),
        "prev_value": prev_value,
        "new_value": new_value,
        "delta_pct": round(delta * 100.0, 2),
        "threshold_pct": round(threshold * 100.0, 2),
        "mfu_delta_pct": (round(mfu_delta * 100.0, 2)
                          if mfu_delta is not None else None),
        "program_regressions": program_regressions,
        **({"recover": recover} if recover is not None else {}),
        **({"preempt": preempt} if preempt is not None else {}),
        **({"integrity": integrity} if integrity is not None else {}),
        **({"multichip": multichip} if multichip is not None else {}),
        **({"wire": wire} if wire is not None else {}),
        **({"serve": serve} if serve is not None else {}),
        **({"fa": fa} if fa is not None else {}),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    bench_dir = "."
    threshold = 0.10
    i = 0
    while i < len(argv):
        if argv[i] == "--dir" and i + 1 < len(argv):
            bench_dir = argv[i + 1]
            i += 2
        elif argv[i] == "--threshold" and i + 1 < len(argv):
            threshold = float(argv[i + 1])
            i += 2
        else:
            i += 1
    row = run_compare(bench_dir, threshold)
    print(json.dumps(row))
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
