"""Measured baseline: the ACTUAL reference (torch FedML @ /root/reference)
vs fedml_tpu on identical data, config, and seeds — BASELINE.md config #1
shape (FedAvg + logistic regression, 10 clients, sp simulation).

The reference is imported read-only from /root/reference/python with its
cloud/edge dependencies (MQTT, S3, docker, wandb, triton, ...) auto-stubbed
— only the training path runs, which needs none of them. No reference code
is copied; it is *executed* to produce the baseline numbers BASELINE.md
calls for ("baselines must be measured, not copied").

Usage:
    python tools/reference_baseline.py [--rounds 10] [--out BASELINE_MEASURED.md]
"""
from __future__ import annotations

import argparse
import importlib.abc
import importlib.machinery
import json
import sys
import time
import types
from types import SimpleNamespace

import numpy as np

N_CLIENTS, PER_ROUND, EPOCHS, BATCH, LR = 10, 10, 2, 32, 0.1
N_TRAIN, N_TEST, DIM, CLASSES = 2000, 400, 60, 10


# --------------------------------------------------------------------------
# shared synthetic data — one generator feeds both frameworks
# --------------------------------------------------------------------------

def make_data(seed: int = 0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(DIM, CLASSES))
    x = rng.normal(size=(N_TRAIN + N_TEST, DIM)).astype(np.float32)
    y = np.argmax(x @ w + 0.5 * rng.normal(size=(N_TRAIN + N_TEST, CLASSES)),
                  axis=1).astype(np.int64)
    xs, ys = x[:N_TRAIN], y[:N_TRAIN]
    xt, yt = x[N_TRAIN:], y[N_TRAIN:]
    # uniform client split (reference config #1 uses homogeneous partition)
    idx = np.array_split(np.arange(N_TRAIN), N_CLIENTS)
    tidx = np.array_split(np.arange(N_TEST), N_CLIENTS)
    return xs, ys, xt, yt, idx, tidx


# --------------------------------------------------------------------------
# reference side
# --------------------------------------------------------------------------

STUB_ROOTS = {
    "GPUtil", "paho", "boto3", "botocore", "wandb", "MNN", "httpx", "redis",
    "chardet", "fastapi", "uvicorn", "prettytable", "click_spinner",
    "torchvision", "matplotlib", "sqlalchemy", "docker", "pkg_resources",
    "tritonclient", "multiprocess", "setproctitle", "networkx", "gevent",
    "geventhttpclient", "wget", "h5py", "spacy", "gensim", "sklearn",
    "pandas", "PIL", "cv2", "pympler",
}


class _Dummy:
    def __init__(self, *a, **k):
        pass

    def __call__(self, *a, **k):
        return _Dummy()

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return _Dummy()

    def __mro_entries__(self, bases):
        return (object,)

    def __iter__(self):
        return iter(())


class _StubModule(types.ModuleType):
    __path__: list = []

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        if name == "parse_version":
            return lambda v: tuple(str(v).split("."))
        if name == "declarative_base":
            return lambda **k: type("Base", (), {})
        if name in ("APIError", "NotFound", "DockerException"):
            return type(name, (Exception,), {})
        return _Dummy()


class _StubFinder(importlib.abc.MetaPathFinder, importlib.abc.Loader):
    def find_spec(self, fullname, path=None, target=None):
        if fullname.split(".", 1)[0] in STUB_ROOTS:
            return importlib.machinery.ModuleSpec(fullname, self,
                                                  is_package=True)
        return None

    def create_module(self, spec):
        return _StubModule(spec.name)

    def exec_module(self, module):
        pass


def _setup_reference():
    """Install the stub finder, import the reference, silence its mlops."""
    import requests  # noqa: F401 — bind real chardet handling before stubs

    sys.meta_path.insert(0, _StubFinder())
    sys.path.insert(0, "/root/reference/python")
    import fedml

    # the harness never calls fedml.init() (needs yaml/CLI); silence the
    # mlops control-plane hooks the train loop fires
    for name in dir(fedml.mlops):
        if name.startswith(("log", "event")):
            setattr(fedml.mlops, name, lambda *a, **k: None)
    return fedml


def _reference_args(rounds, *, n_clients, per_round, epochs, batch, lr,
                    model):
    return SimpleNamespace(
        batch_size=batch, client_num_in_total=n_clients,
        client_num_per_round=per_round, comm_round=rounds,
        dataset="synthetic", enable_wandb=False, frequency_of_the_test=1000,
        client_optimizer="sgd", epochs=epochs, learning_rate=lr,
        weight_decay=0.0, federated_optimizer="FedAvg", model=model,
        run_id=0, using_mlops=False,
    )


def _run_reference_fedavg(args, model_fn, data, label, to_input=None,
                          classes=CLASSES):
    """Shared reference-side scaffold: loaders → FedAvgAPI → timing → acc."""
    import torch
    from torch.utils.data import DataLoader, TensorDataset

    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    xs, ys, xt, yt, idx, tidx = data
    to_input = to_input or (lambda a: a)
    n_clients = args.client_num_in_total

    def loader(x, y):
        return DataLoader(
            TensorDataset(torch.from_numpy(to_input(x)), torch.from_numpy(y)),
            batch_size=args.batch_size, shuffle=False,
        )

    train_local = {i: loader(xs[idx[i]], ys[idx[i]]) for i in range(n_clients)}
    test_local = {i: loader(xt[tidx[i]], yt[tidx[i]]) for i in range(n_clients)}
    nums = {i: len(idx[i]) for i in range(n_clients)}
    dataset = [len(xs), len(xt), loader(xs, ys), loader(xt, yt),
               nums, train_local, test_local, classes]

    torch.manual_seed(0)  # seed BEFORE construction so init is seeded
    api = FedAvgAPI(args, torch.device("cpu"), dataset, model_fn())
    t0 = time.perf_counter()
    api.train()
    wall = time.perf_counter() - t0

    api.model_trainer.model.eval()
    with torch.no_grad():
        logits = api.model_trainer.model(torch.from_numpy(to_input(xt)))
        acc = float((logits.argmax(1).numpy() == yt).mean())
    return {"framework": label, "rounds": args.comm_round,
            "wall_sec": round(wall, 2),
            "sec_per_round": round(wall / args.comm_round, 3),
            "final_test_acc": round(acc, 4)}


def run_reference(rounds: int):
    _setup_reference()
    from fedml.model.linear.lr import LogisticRegression

    args = _reference_args(rounds, n_clients=N_CLIENTS, per_round=PER_ROUND,
                           epochs=EPOCHS, batch=BATCH, lr=LR, model="lr")
    return _run_reference_fedavg(
        args, lambda: LogisticRegression(DIM, CLASSES), make_data(),
        "reference (torch, CPU)")


# --------------------------------------------------------------------------
# fedml_tpu side
# --------------------------------------------------------------------------

def _run_ours_fedavg(rounds, platform, data, data_args, model_name, label,
                     *, n_clients, per_round, epochs, batch, lr,
                     classes=CLASSES):
    """Shared fedml_tpu-side scaffold: dataset -> FedAvgAPI -> timing -> acc."""
    sys.path.insert(0, "/root/repo")
    import jax

    if platform:
        # sitecustomize may pin the hardware plugin; the config API wins
        jax.config.update("jax_platforms", platform)
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data.dataset import FederatedDataset
    from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI

    xs, ys, xt, yt, idx, tidx = data
    ds = FederatedDataset(
        train_data_num=len(xs), test_data_num=len(xt),
        train_data_global=(xs, ys), test_data_global=(xt, yt),
        train_data_local_num_dict={i: len(idx[i]) for i in range(n_clients)},
        train_data_local_dict={i: (xs[idx[i]], ys[idx[i]])
                               for i in range(n_clients)},
        test_data_local_dict={i: (xt[tidx[i]], yt[tidx[i]])
                              for i in range(n_clients)},
        class_num=classes,
    )
    args = fedml_tpu.init(load_arguments_from_dict({
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": data_args,
        "model_args": {"model": model_name},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": n_clients,
                       "client_num_per_round": per_round,
                       "comm_round": rounds, "epochs": epochs,
                       "batch_size": batch, "learning_rate": lr,
                       # same eval work as the reference side: test only at
                       # the end, not every round
                       "frequency_of_the_test": 1000},
    }))
    model = (model_name(args) if callable(model_name)
             else models_mod.create(args, output_dim=classes))
    api = FedAvgAPI(args, None, ds, model)
    t0 = time.perf_counter()
    res = api.train()
    wall = time.perf_counter() - t0
    return {"framework": f"{label} (jax, {jax.default_backend()})",
            "rounds": rounds, "wall_sec": round(wall, 2),
            "sec_per_round": round(wall / rounds, 3),
            "first_compile_included": True,
            "final_test_acc": round(float(res["test_acc"]), 4)}


def run_ours(rounds: int, platform: str = ""):
    return _run_ours_fedavg(
        rounds, platform, make_data(), {"dataset": "synthetic"}, "lr",
        "fedml_tpu", n_clients=N_CLIENTS, per_round=PER_ROUND,
        epochs=EPOCHS, batch=BATCH, lr=LR)


# --------------------------------------------------------------------------
# config #2 flavor: CNN (resnet20) image classification — both frameworks'
# own CIFAR-style resnet20 on identical synthetic 32×32×3 data
# --------------------------------------------------------------------------

CNN_TRAIN, CNN_TEST, CNN_CLIENTS, CNN_BATCH, CNN_LR, CNN_EPOCHS = (
    640, 160, 4, 32, 0.05, 1)


def make_image_data(seed: int = 1):
    rng = np.random.default_rng(seed)
    n = CNN_TRAIN + CNN_TEST
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    # class = sign pattern of 10 fixed random filters → learnable, not trivial
    w = rng.normal(size=(32 * 32 * 3, 10))
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int64)
    xs, ys, xt, yt = x[:CNN_TRAIN], y[:CNN_TRAIN], x[CNN_TRAIN:], y[CNN_TRAIN:]
    idx = np.array_split(np.arange(CNN_TRAIN), CNN_CLIENTS)
    tidx = np.array_split(np.arange(CNN_TEST), CNN_CLIENTS)
    return xs, ys, xt, yt, idx, tidx


def run_reference_cnn(rounds: int):
    _setup_reference()
    from fedml.model.cv.resnet import resnet20

    args = _reference_args(rounds, n_clients=CNN_CLIENTS,
                           per_round=CNN_CLIENTS, epochs=CNN_EPOCHS,
                           batch=CNN_BATCH, lr=CNN_LR, model="resnet20")
    return _run_reference_fedavg(
        args, lambda: resnet20(10), make_image_data(),
        "reference resnet20 (torch, CPU)",
        to_input=lambda a: np.transpose(a, (0, 3, 1, 2)).copy())


def run_ours_cnn(rounds: int, platform: str = ""):
    return _run_ours_fedavg(
        rounds, platform, make_image_data(),
        {"dataset": "synthetic_image", "image_size": 32}, "resnet20",
        "fedml_tpu resnet20", n_clients=CNN_CLIENTS, per_round=CNN_CLIENTS,
        epochs=CNN_EPOCHS, batch=CNN_BATCH, lr=CNN_LR)


# --------------------------------------------------------------------------
# config #3 flavor: Shakespeare-style LSTM next-character prediction —
# both frameworks' own McMahan-RNN (Embed(8) → LSTM(256)×2 → Dense(vocab),
# final-position classification head) on identical synthetic char streams
# --------------------------------------------------------------------------

RNN_TRAIN, RNN_TEST, RNN_CLIENTS, RNN_BATCH, RNN_LR, RNN_EPOCHS = (
    600, 150, 4, 32, 0.5, 1)
RNN_SEQ, RNN_VOCAB = 20, 90


def make_char_data(seed: int = 2):
    """Markov-chain character streams: next-char is genuinely learnable
    (each char has 3 likely successors), not memorizable noise."""
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, RNN_VOCAB, size=(RNN_VOCAB, 3))
    n = RNN_TRAIN + RNN_TEST
    x = np.zeros((n, RNN_SEQ), np.int64)
    y = np.zeros((n,), np.int64)
    state = rng.integers(0, RNN_VOCAB, size=n)
    for t in range(RNN_SEQ + 1):
        choice = succ[state, rng.integers(0, 3, size=n)]
        # 10% uniform noise keeps the chain ergodic
        noise = rng.integers(0, RNN_VOCAB, size=n)
        nxt = np.where(rng.random(n) < 0.1, noise, choice)
        if t < RNN_SEQ:
            x[:, t] = state
        else:
            y = state
        state = nxt
    xs, ys, xt, yt = x[:RNN_TRAIN], y[:RNN_TRAIN], x[RNN_TRAIN:], y[RNN_TRAIN:]
    idx = np.array_split(np.arange(RNN_TRAIN), RNN_CLIENTS)
    tidx = np.array_split(np.arange(RNN_TEST), RNN_CLIENTS)
    return xs, ys, xt, yt, idx, tidx


def run_reference_rnn(rounds: int):
    _setup_reference()
    from fedml.model.nlp.rnn import RNN_OriginalFedAvg

    args = _reference_args(rounds, n_clients=RNN_CLIENTS,
                           per_round=RNN_CLIENTS, epochs=RNN_EPOCHS,
                           batch=RNN_BATCH, lr=RNN_LR, model="rnn")
    return _run_reference_fedavg(
        args, lambda: RNN_OriginalFedAvg(vocab_size=RNN_VOCAB),
        make_char_data(), "reference shakespeare-LSTM (torch, CPU)",
        classes=RNN_VOCAB)


def run_ours_rnn(rounds: int, platform: str = ""):
    def final_char_rnn(args):
        # our zoo RNN emits per-position LM logits (the fed_shakespeare
        # objective); the reference model here classifies the FINAL
        # position only — wrap for identical work
        import flax.linen as nn

        from fedml_tpu.models.nlp.rnn import RNNOriginalFedAvg

        class FinalCharRNN(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                logits = RNNOriginalFedAvg(vocab_size=RNN_VOCAB)(x)
                return logits[:, -1] if logits.ndim == 3 else logits

        return FinalCharRNN()

    return _run_ours_fedavg(
        rounds, platform, make_char_data(),
        {"dataset": "shakespeare", "seq_len": RNN_SEQ}, final_char_rnn,
        "fedml_tpu shakespeare-LSTM", n_clients=RNN_CLIENTS,
        per_round=RNN_CLIENTS, epochs=RNN_EPOCHS, batch=RNN_BATCH,
        lr=RNN_LR, classes=RNN_VOCAB)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--config", choices=["lr", "cnn", "rnn"], default="lr")
    ap.add_argument("--side", choices=["reference", "ours", "both"],
                    default="both")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for the fedml_tpu side (cpu|tpu); "
                         "cpu by default so the CPU-vs-CPU table reproduces")
    args = ap.parse_args()
    ref_fn = {"lr": run_reference, "cnn": run_reference_cnn,
              "rnn": run_reference_rnn}[args.config]
    ours_fn = {"lr": run_ours, "cnn": run_ours_cnn,
               "rnn": run_ours_rnn}[args.config]
    results = []
    if args.side in ("reference", "both"):
        results.append(ref_fn(args.rounds))
        print(json.dumps(results[-1]))
    if args.side in ("ours", "both"):
        # run ours in a subprocess when both: the stub finder must not leak
        if args.side == "both":
            import subprocess

            out = subprocess.run(
                [sys.executable, __file__, "--side", "ours",
                 "--rounds", str(args.rounds), "--config", args.config,
                 "--platform", args.platform],
                capture_output=True, text=True,
            )
            lines = out.stdout.strip().splitlines()
            if out.returncode != 0 or not lines:
                sys.stderr.write(out.stderr)
                raise SystemExit(
                    f"ours-side subprocess failed (rc={out.returncode})")
            results.append(json.loads(lines[-1]))
            print(lines[-1])
        else:
            results.append(ours_fn(args.rounds, args.platform))
            print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    main()
