"""Measured baseline: the ACTUAL reference (torch FedML @ /root/reference)
vs fedml_tpu on identical data, config, and seeds — BASELINE.md config #1
shape (FedAvg + logistic regression, 10 clients, sp simulation).

The reference is imported read-only from /root/reference/python with its
cloud/edge dependencies (MQTT, S3, docker, wandb, triton, ...) auto-stubbed
— only the training path runs, which needs none of them. No reference code
is copied; it is *executed* to produce the baseline numbers BASELINE.md
calls for ("baselines must be measured, not copied").

Usage:
    python tools/reference_baseline.py [--rounds 10] [--out BASELINE_MEASURED.md]
"""
from __future__ import annotations

import argparse
import importlib.abc
import importlib.machinery
import json
import sys
import time
import types
from types import SimpleNamespace

import numpy as np

N_CLIENTS, PER_ROUND, EPOCHS, BATCH, LR = 10, 10, 2, 32, 0.1
N_TRAIN, N_TEST, DIM, CLASSES = 2000, 400, 60, 10


# --------------------------------------------------------------------------
# shared synthetic data — one generator feeds both frameworks
# --------------------------------------------------------------------------

def make_data(seed: int = 0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(DIM, CLASSES))
    x = rng.normal(size=(N_TRAIN + N_TEST, DIM)).astype(np.float32)
    y = np.argmax(x @ w + 0.5 * rng.normal(size=(N_TRAIN + N_TEST, CLASSES)),
                  axis=1).astype(np.int64)
    xs, ys = x[:N_TRAIN], y[:N_TRAIN]
    xt, yt = x[N_TRAIN:], y[N_TRAIN:]
    # uniform client split (reference config #1 uses homogeneous partition)
    idx = np.array_split(np.arange(N_TRAIN), N_CLIENTS)
    tidx = np.array_split(np.arange(N_TEST), N_CLIENTS)
    return xs, ys, xt, yt, idx, tidx


# --------------------------------------------------------------------------
# reference side
# --------------------------------------------------------------------------

STUB_ROOTS = {
    "GPUtil", "paho", "boto3", "botocore", "wandb", "MNN", "httpx", "redis",
    "chardet", "fastapi", "uvicorn", "prettytable", "click_spinner",
    "torchvision", "matplotlib", "sqlalchemy", "docker", "pkg_resources",
    "tritonclient", "multiprocess", "setproctitle", "networkx", "gevent",
    "geventhttpclient", "wget", "h5py", "spacy", "gensim", "sklearn",
    "pandas", "PIL", "cv2", "pympler",
}


class _Dummy:
    def __init__(self, *a, **k):
        pass

    def __call__(self, *a, **k):
        return _Dummy()

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return _Dummy()

    def __mro_entries__(self, bases):
        return (object,)

    def __iter__(self):
        return iter(())


class _StubModule(types.ModuleType):
    __path__: list = []

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        if name == "parse_version":
            return lambda v: tuple(str(v).split("."))
        if name == "declarative_base":
            return lambda **k: type("Base", (), {})
        if name in ("APIError", "NotFound", "DockerException"):
            return type(name, (Exception,), {})
        return _Dummy()


class _StubFinder(importlib.abc.MetaPathFinder, importlib.abc.Loader):
    def find_spec(self, fullname, path=None, target=None):
        if fullname.split(".", 1)[0] in STUB_ROOTS:
            return importlib.machinery.ModuleSpec(fullname, self,
                                                  is_package=True)
        return None

    def create_module(self, spec):
        return _StubModule(spec.name)

    def exec_module(self, module):
        pass


def run_reference(rounds: int):
    import requests  # noqa: F401 — bind real chardet handling before stubs

    sys.meta_path.insert(0, _StubFinder())
    sys.path.insert(0, "/root/reference/python")

    import torch
    from torch.utils.data import DataLoader, TensorDataset

    import fedml
    from fedml.model.linear.lr import LogisticRegression
    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    # the harness never calls fedml.init() (needs yaml/CLI); silence the
    # mlops control-plane hooks the train loop fires
    for name in dir(fedml.mlops):
        if name.startswith(("log", "event")):
            setattr(fedml.mlops, name, lambda *a, **k: None)

    xs, ys, xt, yt, idx, tidx = make_data()

    def loader(x, y):
        return DataLoader(
            TensorDataset(torch.from_numpy(x), torch.from_numpy(y)),
            batch_size=BATCH, shuffle=False,
        )

    train_local = {i: loader(xs[idx[i]], ys[idx[i]]) for i in range(N_CLIENTS)}
    test_local = {i: loader(xt[tidx[i]], yt[tidx[i]]) for i in range(N_CLIENTS)}
    nums = {i: len(idx[i]) for i in range(N_CLIENTS)}
    dataset = [N_TRAIN, N_TEST, loader(xs, ys), loader(xt, yt),
               nums, train_local, test_local, CLASSES]

    args = SimpleNamespace(
        batch_size=BATCH, client_num_in_total=N_CLIENTS,
        client_num_per_round=PER_ROUND, comm_round=rounds,
        dataset="synthetic", enable_wandb=False, frequency_of_the_test=1000,
        client_optimizer="sgd", epochs=EPOCHS, learning_rate=LR,
        weight_decay=0.0, federated_optimizer="FedAvg", model="lr",
        run_id=0, using_mlops=False,
    )
    torch.manual_seed(0)
    model = LogisticRegression(DIM, CLASSES)
    api = FedAvgAPI(args, torch.device("cpu"), dataset, model)

    t0 = time.perf_counter()
    api.train()
    wall = time.perf_counter() - t0

    with torch.no_grad():
        logits = api.model_trainer.model(torch.from_numpy(xt))
        acc = float((logits.argmax(1).numpy() == yt).mean())
    return {"framework": "reference (torch, CPU)", "rounds": rounds,
            "wall_sec": round(wall, 2),
            "sec_per_round": round(wall / rounds, 3),
            "final_test_acc": round(acc, 4)}


# --------------------------------------------------------------------------
# fedml_tpu side
# --------------------------------------------------------------------------

def run_ours(rounds: int, platform: str = ""):
    sys.path.insert(0, "/root/repo")
    import jax

    if platform:
        # sitecustomize may pin the hardware plugin; the config API wins
        jax.config.update("jax_platforms", platform)

    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data.dataset import FederatedDataset
    from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI
    import fedml_tpu

    xs, ys, xt, yt, idx, tidx = make_data()
    ds = FederatedDataset(
        train_data_num=N_TRAIN, test_data_num=N_TEST,
        train_data_global=(xs, ys), test_data_global=(xt, yt),
        train_data_local_num_dict={i: len(idx[i]) for i in range(N_CLIENTS)},
        train_data_local_dict={i: (xs[idx[i]], ys[idx[i]])
                               for i in range(N_CLIENTS)},
        test_data_local_dict={i: (xt[tidx[i]], yt[tidx[i]])
                              for i in range(N_CLIENTS)},
        class_num=CLASSES, feature_dim=DIM,
    )
    args = fedml_tpu.init(load_arguments_from_dict({
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": {"dataset": "synthetic"},
        "model_args": {"model": "lr"},
        "train_args": {"federated_optimizer": "FedAvg",
                       "client_num_in_total": N_CLIENTS,
                       "client_num_per_round": PER_ROUND,
                       "comm_round": rounds, "epochs": EPOCHS,
                       "batch_size": BATCH, "learning_rate": LR,
                       # same eval work as the reference side: test only at
                       # the end, not every round
                       "frequency_of_the_test": 1000},
    }))
    from fedml_tpu import models as models_mod

    model = models_mod.create(args, output_dim=CLASSES)
    api = FedAvgAPI(args, None, ds, model)
    t0 = time.perf_counter()
    res = api.train()
    wall = time.perf_counter() - t0
    return {"framework": f"fedml_tpu (jax, {jax.default_backend()})",
            "rounds": rounds, "wall_sec": round(wall, 2),
            "sec_per_round": round(wall / rounds, 3),
            "first_compile_included": True,
            "final_test_acc": round(float(res["test_acc"]), 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--side", choices=["reference", "ours", "both"],
                    default="both")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for the fedml_tpu side (cpu|tpu); "
                         "cpu by default so the CPU-vs-CPU table reproduces")
    args = ap.parse_args()
    results = []
    if args.side in ("reference", "both"):
        results.append(run_reference(args.rounds))
        print(json.dumps(results[-1]))
    if args.side in ("ours", "both"):
        # run ours in a subprocess when both: the stub finder must not leak
        if args.side == "both":
            import subprocess

            out = subprocess.run(
                [sys.executable, __file__, "--side", "ours",
                 "--rounds", str(args.rounds),
                 "--platform", args.platform],
                capture_output=True, text=True,
            )
            lines = out.stdout.strip().splitlines()
            if out.returncode != 0 or not lines:
                sys.stderr.write(out.stderr)
                raise SystemExit(
                    f"ours-side subprocess failed (rc={out.returncode})")
            results.append(json.loads(lines[-1]))
            print(lines[-1])
        else:
            results.append(run_ours(args.rounds, args.platform))
            print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    main()
