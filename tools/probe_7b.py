"""Single-chip Llama-2-7B LoRA step probe (VERDICT r3 task 1).

Attempts the real thing on the v5e: bf16 frozen base (~13.5 GB of HBM),
LoRA-only fp32 masters, B=1/T=512. Prints step time + memory stats, or
the OOM evidence. Run variants:

  python tools/probe_7b.py            # remat off
  python tools/probe_7b.py --remat    # full remat
  python tools/probe_7b.py --t 1024   # longer sequence
"""
import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

ap = argparse.ArgumentParser()
ap.add_argument("--remat", action="store_true")
ap.add_argument("--policy", default=None, choices=["none", "dots", "full"])
ap.add_argument("--t", type=int, default=512)
ap.add_argument("--b", type=int, default=1)
ap.add_argument("--layers", type=int, default=32)
ap.add_argument("--steps", type=int, default=8)
ap.add_argument("--quant", action="store_true",
                help="QLoRA: int8-quantize the frozen base (frees ~6.6 GB "
                     "at 7B -> bigger B/T fit)")
cli = ap.parse_args()
if cli.policy:
    cli.remat = cli.policy != "none"

from fedml_tpu.models.llm.llama import LlamaConfig
from fedml_tpu.train.llm.trainer import LLMTrainer

cfg = LlamaConfig.llama2_7b(
    num_hidden_layers=cli.layers,
    lora_rank=16, remat=cli.remat,
    remat_policy=cli.policy or ("full" if cli.remat else "none"),
    param_dtype=jnp.bfloat16,
)


class Args:
    max_seq_length = cli.t
    per_device_batch_size = cli.b
    gradient_accumulation_steps = 1
    learning_rate = 1e-4
    mesh_dp = 1
    mesh_fsdp = -1
    mesh_tp = 1
    mesh_sp = 1
    base_quantize = "int8" if cli.quant else ""


dev = jax.devices()[0]
print(f"device: {dev.device_kind}, platform {dev.platform}", flush=True)

t0 = time.perf_counter()
tr = LLMTrainer(cfg, Args())
tr.init(seed=0)
n_params = sum(x.size for x in jax.tree.leaves(tr.params))
print(f"init ok: {n_params/1e9:.2f}B params, {time.perf_counter()-t0:.1f}s",
      flush=True)

rng = np.random.default_rng(0)
x = rng.integers(0, 32000, size=(cli.b, cli.t), dtype=np.int32)
y = (x + 1) % 32000
m = np.ones((cli.b,), np.float32)

t0 = time.perf_counter()
loss = tr.step(x, y, m)
print(f"first step (compile): {time.perf_counter()-t0:.1f}s loss={loss:.3f}",
      flush=True)

# chained timing: steps donate params/opt_state -> data-dependent
def chain(n):
    t0 = time.perf_counter()
    p, o = tr.params, tr.opt_state
    loss = None
    for _ in range(n):
        p, o, loss = tr._train_step(
            p, o, tr._put(x[None], tr._micro_spec),
            tr._put(y[None], tr._micro_spec),
            tr._put(m[None], tr._micro_spec, np.float32))
    tr.params, tr.opt_state = p, o
    float(loss)
    return time.perf_counter() - t0

chain(2)
best = 1e9
for _ in range(3):
    ts, tl = chain(2), chain(2 + cli.steps)
    best = min(best, (tl - ts) / cli.steps)
toks = cli.b * cli.t
flops = 4.0 * n_params * toks + 6.0 * cfg.num_hidden_layers * \
    cfg.hidden_size * cli.t * toks * 0.5
stats = {}
try:
    ms = dev.memory_stats()
    stats = {k: round(ms[k] / 1e9, 2) for k in
             ("bytes_in_use", "peak_bytes_in_use", "bytes_limit") if k in ms}
except Exception:
    pass
print(json.dumps({
    "sec_per_step": round(best, 4),
    "tokens_per_sec": round(toks / best, 1),
    "mfu": round(flops / best / 197e12, 4),
    "B": cli.b, "T": cli.t, "layers": cli.layers, "remat": cli.policy or cli.remat,
    "quant_base": bool(cli.quant),
    "memory_gb": stats,
}), flush=True)
