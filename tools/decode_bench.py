import time, numpy as np, jax, jax.numpy as jnp
import sys; sys.path.insert(0, "/root/repo")
from fedml_tpu.models.llm.llama import LlamaConfig, LlamaForCausalLM
from fedml_tpu.serving.llm_engine import ContinuousBatchingEngine
import argparse
ap = argparse.ArgumentParser()
ap.add_argument("--quantize", default=None)
ap.add_argument("--model", default="1b", choices=["1b", "7b"])
ap.add_argument("--batch", type=int, default=8)
ap.add_argument("--ctx", type=int, default=1024)
cli = ap.parse_args()

if cli.model == "7b":
    # BASELINE config #5: Llama-2-7B inference endpoint on TPU. bf16
    # weights alone are 13.5 GB — int8 (6.8 GB) is what makes a B=8
    # single-v5e 7B endpoint fit at all (KV cache ~0.5 GB/slot @1024).
    cfg = LlamaConfig.llama2_7b(remat=False, remat_policy="none",
                                dtype=jnp.bfloat16,
                                param_dtype=jnp.bfloat16, use_flash=False)
else:
    cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                      intermediate_size=5632, num_hidden_layers=22,
                      num_attention_heads=32, num_key_value_heads=8,
                      max_position_embeddings=2048, remat=False,
                      remat_policy="none", dtype=jnp.bfloat16,
                      param_dtype=jnp.bfloat16, use_flash=False)
model = LlamaForCausalLM(cfg)
rng = np.random.default_rng(0)
toks = jnp.asarray(rng.integers(0, 32000, size=(1, 8)))
params = jax.jit(model.init)(jax.random.key(0), toks)
n_params = sum(x.size for x in jax.tree.leaves(params))
B, CTX = cli.batch, cli.ctx
eng = ContinuousBatchingEngine(model, params, batch_slots=B, max_len=CTX,
                               quantize=cli.quantize, quantize_donate=True)
params = eng.params  # quantized if requested
caches = model.init_kv_caches(B, CTX)
caches = [(jnp.asarray(k), jnp.asarray(v)) for k, v, _ in caches]
last = jnp.asarray(rng.integers(0, 32000, size=(B,)))
lengths = jnp.full((B,), CTX // 2, jnp.int32)

def chain(n):
    global caches
    t0 = time.perf_counter()
    c = caches
    logits = None
    for _ in range(n):
        c, logits = eng._decode(params, c, last, lengths)
    caches = c
    float(jnp.sum(logits.astype(jnp.float32)))
    return time.perf_counter() - t0

chain(2)
best = 1e9
for _ in range(3):
    ts = chain(2); tl = chain(34)
    best = min(best, (tl - ts) / 32)
tok_s = B / best
print(f"model={cli.model} params={n_params/1e9:.2f}B quantize={cli.quantize} decode step "
      f"{best*1e3:.2f} ms @B{B} ctx{CTX//2} -> {tok_s:.0f} tok/s device-side")
# memory-bound roofline from the ACTUAL (possibly quantized) weight bytes
from fedml_tpu.ops.quant import tree_bytes
wbytes = tree_bytes(params)
print(f"weight bytes {wbytes/1e9:.2f} GB -> "
      f"weight-read roofline: {wbytes/best/1e9:.0f} GB/s effective")
