#!/usr/bin/env python
"""Resilience-layer micro-bench: hot-path overhead + broker recovery.

Two claims the resilience subsystem makes, measured:

1. **Injection-disabled overhead** — production (no ``chaos`` config)
   pays only a msg-id stamp, a ``None`` check, and a try/except around
   the transport send. Measured against the CHEAPEST transport (LOCAL:
   enqueue-only, no serialization) so the reported percentage is a
   conservative upper bound; the acceptance gate is < 1%.
2. **Broker recovery** — kill the pub/sub broker mid-run, restart it on
   the same port, and time how long until a reconnect-enabled client
   delivers a message end-to-end again.

Prints ONE JSON line (same contract as the other ``tools/*_bench.py``;
also reachable as ``python bench.py --chaos``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _seam_s(mgr, make_msg, n: int) -> float:
    """Time the resilience seam in isolation: exactly what send_message
    gained over the pre-resilience path — the msg-id presence check +
    stamp, the chaos None check, and the retry try/except wrapping an
    (here: no-op) transport call."""
    from fedml_tpu.core.distributed.message import Message

    msgs = [make_msg() for _ in range(n)]
    noop = lambda: None
    retry_on = mgr._retry_on
    t0 = time.perf_counter()
    for m in msgs:
        if m.get(Message.MSG_ARG_KEY_MSG_ID) is None:
            m.add_params(Message.MSG_ARG_KEY_MSG_ID,
                         mgr._msg_id_prefix + str(next(mgr._send_seq)))
        if mgr._chaos is not None:  # pragma: no cover - production: None
            mgr._chaos.on_send(m)
        try:
            noop()
        except retry_on:  # pragma: no cover - noop never raises
            pass
    return time.perf_counter() - t0


def bench_send_overhead(n: int = 20_000) -> dict:
    """Seam cost vs two hot paths: the deployment transport (BROKER over
    loopback TCP — the gated number) and the cheapest possible transport
    (LOCAL enqueue-only — the reported worst case)."""
    import numpy as np

    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.core.distributed.communication.broker import PubSubBroker
    from fedml_tpu.core.distributed.communication.broker_comm import (
        BrokerCommManager,
    )
    from fedml_tpu.core.distributed.communication.local_comm import (
        LocalBroker,
    )
    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager
    from fedml_tpu.core.distributed.message import Message

    run_id = "chaos_bench"
    LocalBroker.destroy(run_id)
    args = load_arguments_from_dict(
        {"train_args": {"run_id": run_id}}, training_type="cross_silo")
    payload = {"w": np.zeros(64, np.float32)}

    def make_msg() -> Message:
        m = Message("MSG_BENCH", 0, 1)
        m.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
        return m

    def timed_sends(mgr, count: int) -> float:
        for _ in range(200):  # warm registry handles + code paths
            mgr.send_message(make_msg())
        msgs = [make_msg() for _ in range(count)]
        t0 = time.perf_counter()
        for m in msgs:
            mgr.send_message(m)
        return time.perf_counter() - t0

    local_mgr = FedMLCommManager(args, rank=0, size=2)
    local_s = timed_sends(local_mgr, n)
    seam_s = _seam_s(local_mgr, make_msg, n)

    broker = PubSubBroker(port=0).start()
    host, port = broker.address
    import tempfile

    from fedml_tpu.core.distributed.communication.object_store import (
        LocalDirObjectStore,
    )

    with tempfile.TemporaryDirectory() as tmp:
        comm = BrokerCommManager(run_id, 0, host, port,
                                 LocalDirObjectStore(tmp))
        broker_mgr = FedMLCommManager(args, comm=comm, rank=0, size=2)
        n_broker = max(1000, n // 10)
        broker_s = timed_sends(broker_mgr, n_broker)
        comm.client.close()
    broker.stop()
    LocalBroker.destroy(run_id)

    local_us = local_s / n * 1e6
    seam_us = seam_s / n * 1e6
    broker_us = broker_s / n_broker * 1e6
    overhead_pct = 100.0 * seam_us / broker_us if broker_us else 0.0
    return {
        "send_us_per_msg_broker": round(broker_us, 3),
        "send_us_per_msg_local": round(local_us, 3),
        "seam_us_per_msg": round(seam_us, 3),
        # the gate: seam cost relative to the deployment (BROKER) send
        "overhead_pct": round(overhead_pct, 3),
        "overhead_pct_local_worst_case": round(
            100.0 * seam_us / local_us if local_us else 0.0, 3),
        "ok_overhead": overhead_pct < 1.0,
    }


def bench_broker_recovery(deadline_s: float = 30.0) -> dict:
    """Kill + restart the broker; time until delivery resumes."""
    from fedml_tpu.core.distributed.communication.broker import (
        BrokerClient,
        PubSubBroker,
    )

    broker = PubSubBroker(port=0).start()
    host, port = broker.address
    got = []
    sub = BrokerClient(host, port, reconnect=True)
    pub = BrokerClient(host, port, reconnect=True)
    sub.subscribe("bench/recovery", got.append)
    time.sleep(0.1)
    pub.publish("bench/recovery", b"pre")
    t_end = time.time() + 5
    while not got and time.time() < t_end:
        time.sleep(0.005)
    assert got, "baseline delivery failed"

    broker.stop()
    time.sleep(0.2)  # let both clients observe the dead socket
    restart_t0 = time.time()
    broker2 = PubSubBroker(host=host, port=port).start()
    # publish-until-delivered: each attempt rides the reconnect logic
    n_pre = len(got)
    recovery_ms = None
    t_end = time.time() + deadline_s
    while time.time() < t_end:
        try:
            pub.publish("bench/recovery", b"post")
        except (ConnectionError, OSError):
            time.sleep(0.02)
            continue
        if len(got) > n_pre:
            recovery_ms = (time.time() - restart_t0) * 1e3
            break
        time.sleep(0.01)
    if recovery_ms is None and len(got) > n_pre:  # pragma: no cover
        recovery_ms = (time.time() - restart_t0) * 1e3
    sub.close()
    pub.close()
    broker2.stop()
    return {
        "recovered": recovery_ms is not None,
        "broker_recovery_ms": round(recovery_ms, 1) if recovery_ms else None,
    }


def run_chaos_bench(n: int = 20_000) -> dict:
    row = {"bench": "chaos", **bench_send_overhead(n)}
    row.update(bench_broker_recovery())
    return row


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=20_000,
                   help="messages for the send-overhead loop")
    ns = p.parse_args()
    row = run_chaos_bench(ns.n)
    print(json.dumps(row))
    return 0 if (row["ok_overhead"] and row["recovered"]) else 1


if __name__ == "__main__":
    sys.exit(main())
