#!/usr/bin/env python
"""Span/metric name lint — keeps the telemetry taxonomy from drifting.

Shim: the rules moved to ``fedml_tpu.analysis.passes.span_names`` (the
``span-names`` pass of ``tools/graftcheck.py``).  This entrypoint keeps
the historical CLI, exit codes, output and module API
(``collect``/``check``/``normalize``) so the existing tier-1 wiring and
``tests/test_telemetry.py`` run unmodified.

Like ``tools/lint.py``, the import bypasses ``fedml_tpu/__init__.py``
so the lint stays usable when the package import chain is broken.
"""
from __future__ import annotations

import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_stubbed = False
if "fedml_tpu" not in sys.modules:
    _pkg = types.ModuleType("fedml_tpu")
    _pkg.__path__ = [os.path.join(_REPO, "fedml_tpu")]
    sys.modules["fedml_tpu"] = _pkg
    _stubbed = True

from fedml_tpu.analysis.passes.span_names import (  # noqa: E402,F401
    REPO,
    ROOTS,
    check,
    collect,
    iter_py,
    main,
    normalize,
)

if _stubbed:
    for _name in [m for m in sys.modules
                  if m == "fedml_tpu" or m.startswith("fedml_tpu.")]:
        del sys.modules[_name]

if __name__ == "__main__":
    sys.exit(main())
