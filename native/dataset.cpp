// Native dataset readers — MNIST idx and CIFAR-10 binary formats.
//
// TPU-era equivalent of the reference's MobileNN dataset readers
// (android/fedmlsdk/MobileNN/src/MNN/{mnist,cifar10}.cpp and
// src/torch/{mnist,cifar10}.cpp — C++ parsers feeding the on-device
// trainer). Here they feed the cross-device client runtime / data
// registry: same raw file formats (big-endian idx, 3073-byte CIFAR
// records), parsed without Python-loop overhead. The numpy twin lives
// in fedml_tpu/data/native_reader.py; parity is enforced by
// tests/test_native_reader.py.
//
// Build:  make -C native        (produces native/libdataset.so)
// Bind:   ctypes, no pybind11 needed.

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

uint32_t be32(const unsigned char* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

}  // namespace

extern "C" {

// Parse an idx3 image file (magic 0x00000803). Returns the number of
// images written to `out` (float32, scaled to [0,1], row-major
// n*rows*cols), or -1 on format error. `max_n` caps the count
// (max_n <= 0 means "probe": returns the file's image count and writes
// only the header values).
long long mnist_read_images(const char* path, float* out, long long max_n,
                            long long* rows, long long* cols) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char hdr[16];
    if (fread(hdr, 1, 16, f) != 16 || be32(hdr) != 0x803) {
        fclose(f);
        return -1;
    }
    long long n = be32(hdr + 4), r = be32(hdr + 8), c = be32(hdr + 12);
    *rows = r;
    *cols = c;
    if (max_n <= 0) {
        fclose(f);
        return n;
    }
    if (n > max_n) n = max_n;
    const long long px = r * c;
    unsigned char* buf = new unsigned char[px];
    for (long long i = 0; i < n; ++i) {
        if ((long long)fread(buf, 1, px, f) != px) {
            delete[] buf;
            fclose(f);
            return i;  // truncated file: return what parsed cleanly
        }
        float* o = out + i * px;
        for (long long j = 0; j < px; ++j) o[j] = buf[j] / 255.0f;
    }
    delete[] buf;
    fclose(f);
    return n;
}

// Parse an idx1 label file (magic 0x00000801) into int32 labels.
long long mnist_read_labels(const char* path, int32_t* out,
                            long long max_n) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char hdr[8];
    if (fread(hdr, 1, 8, f) != 8 || be32(hdr) != 0x801) {
        fclose(f);
        return -1;
    }
    long long n = be32(hdr + 4);
    if (max_n <= 0) {
        fclose(f);
        return n;
    }
    if (n > max_n) n = max_n;
    for (long long i = 0; i < n; ++i) {
        int ch = fgetc(f);
        if (ch == EOF) {
            fclose(f);
            return i;
        }
        out[i] = (int32_t)ch;
    }
    fclose(f);
    return n;
}

// Parse a CIFAR-10 binary batch (3073-byte records: label + 3x32x32
// CHW uint8). Writes images as float32 [0,1] in HWC order (the TPU/XLA
// native conv layout) and int32 labels; returns record count or -1.
long long cifar10_read_batch(const char* path, float* images,
                             int32_t* labels, long long max_n) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    const long long rec = 1 + 3 * 32 * 32;
    unsigned char buf[1 + 3 * 32 * 32];
    long long i = 0;
    while (max_n <= 0 || i < max_n) {
        size_t got = fread(buf, 1, rec, f);
        if (got == 0) break;
        if ((long long)got != rec) {
            fclose(f);
            return i;  // truncated tail record dropped
        }
        if (max_n > 0) {
            labels[i] = (int32_t)buf[0];
            float* o = images + i * 3 * 32 * 32;
            // CHW -> HWC
            for (int h = 0; h < 32; ++h)
                for (int w = 0; w < 32; ++w)
                    for (int ch = 0; ch < 3; ++ch)
                        o[(h * 32 + w) * 3 + ch] =
                            buf[1 + ch * 1024 + h * 32 + w] / 255.0f;
        }
        ++i;
    }
    fclose(f);
    return i;
}

}  // extern "C"
