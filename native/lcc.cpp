// Lagrange-coded-computing kernels for SecAgg/LightSecAgg.
//
// TPU-era equivalent of the reference's native trust-stack component
// (android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp — finite-field
// Lagrange coefficients, modular inverse, encode/decode mask matmuls).
// The Python twin lives in fedml_tpu/core/mpc/lcc.py; parity is enforced
// by tests/test_mpc.py.
//
// Build:  make -C native        (produces native/liblcc.so)
// Bind:   ctypes (fedml_tpu/core/mpc/lcc.py), no pybind11 needed.
//
// All arithmetic is mod a prime p < 2^31, so products fit in 64 bits and
// sums of products are reduced incrementally — no __int128 required, but
// we use it where available for fewer reductions.

#include <cstdint>
#include <cstddef>

extern "C" {

static inline uint64_t mulmod(uint64_t a, uint64_t b, uint64_t p) {
#ifdef __SIZEOF_INT128__
    return (uint64_t)(((__uint128_t)a * b) % p);
#else
    return (a * b) % p;  // safe for p < 2^31
#endif
}

static inline uint64_t powmod(uint64_t a, uint64_t e, uint64_t p) {
    uint64_t r = 1 % p;
    a %= p;
    while (e) {
        if (e & 1) r = mulmod(r, a, p);
        a = mulmod(a, a, p);
        e >>= 1;
    }
    return r;
}

// Fermat inverse (p prime).
uint64_t lcc_modinv(uint64_t a, uint64_t p) { return powmod(a % p, p - 2, p); }

// Lagrange coefficient matrix U[n_target x n_eval]:
//   U[i][j] = prod_{l != j} (target_i - eval_l) / (eval_j - eval_l)   (mod p)
// eval points must be pairwise distinct mod p.
// Returns 0 on success, -1 if a zero denominator is hit.
int lcc_lagrange_coeffs(const int64_t* eval_pts, int64_t n_eval,
                        const int64_t* target_pts, int64_t n_target,
                        int64_t p_, int64_t* out /* n_target*n_eval */) {
    const uint64_t p = (uint64_t)p_;
    for (int64_t i = 0; i < n_target; ++i) {
        const uint64_t t = (uint64_t)(((target_pts[i] % p_) + p_) % p_);
        for (int64_t j = 0; j < n_eval; ++j) {
            uint64_t num = 1, den = 1;
            const uint64_t ej = (uint64_t)(((eval_pts[j] % p_) + p_) % p_);
            for (int64_t l = 0; l < n_eval; ++l) {
                if (l == j) continue;
                const uint64_t el = (uint64_t)(((eval_pts[l] % p_) + p_) % p_);
                num = mulmod(num, (t + p - el) % p, p);
                den = mulmod(den, (ej + p - el) % p, p);
            }
            if (den == 0) return -1;
            out[i * n_eval + j] = (int64_t)mulmod(num, lcc_modinv(den, p), p);
        }
    }
    return 0;
}

// Field "matmul": out[n_out x dim] = coeffs[n_out x n_in] * X[n_in x dim] mod p.
// This is both LCC encode (X = data+noise rows, coeffs from beta->alpha) and
// decode (X = surviving evaluations, coeffs from alpha->beta).
void lcc_field_matmul(const int64_t* coeffs, const int64_t* X,
                      int64_t n_out, int64_t n_in, int64_t dim,
                      int64_t p_, int64_t* out) {
    const uint64_t p = (uint64_t)p_;
    for (int64_t i = 0; i < n_out; ++i) {
        for (int64_t d = 0; d < dim; ++d) out[i * dim + d] = 0;
        for (int64_t j = 0; j < n_in; ++j) {
            const uint64_t c = (uint64_t)(((coeffs[i * n_in + j] % p_) + p_) % p_);
            if (c == 0) continue;
            const int64_t* xrow = X + j * dim;
            int64_t* orow = out + i * dim;
            for (int64_t d = 0; d < dim; ++d) {
                const uint64_t x = (uint64_t)(((xrow[d] % p_) + p_) % p_);
                orow[d] = (int64_t)(((uint64_t)orow[d] + mulmod(c, x, p)) % p);
            }
        }
    }
}

}  // extern "C"
