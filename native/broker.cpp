// Native pub/sub broker — the runtime's federation control plane in C++.
//
// Speaks EXACTLY the wire protocol of the Python PubSubBroker
// (fedml_tpu/core/distributed/communication/broker.py):
//
//   frame   := u32_be len || payload
//   payload := op (1 byte: 'S' subscribe | 'P' publish)
//              || u16_be topic_len || topic || body
//
// with MQTT QoS0 semantics: a publish fans out to every connection
// subscribed to the topic. Single-threaded epoll event loop; per-
// connection buffered reads and non-blocking buffered writes (a slow
// subscriber backlogs its own queue, never the loop). This is the
// deployment-grade stand-in for the reference's hosted MQTT broker
// (mqtt_s3/mqtt_s3_multi_clients_comm_manager.py) — the Python broker
// stays as the in-process test twin, and parity is enforced by running
// the same client test suite against both.
//
// Usage: broker [port]            (0 = ephemeral; prints "LISTENING <port>")
//
// Build: make -C native broker

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <string>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint32_t kMaxFrame = 1u << 30;
constexpr size_t kMaxWriteBacklog = 1u << 31;  // drop conn beyond 2 GB queued

struct Conn {
  int fd = -1;
  std::string rbuf;                       // unparsed inbound bytes
  std::string wbuf;                       // unflushed outbound bytes
  size_t woff = 0;                        // flushed prefix of wbuf
  std::unordered_set<std::string> topics; // for cleanup on close
};

std::unordered_map<int, Conn> conns;                       // fd -> conn
std::unordered_map<std::string, std::unordered_set<int>> subs; // topic -> fds
// Connections that hit a fatal error are doomed, not closed inline:
// closing frees the Conn, and callers (drain_frames parsing c.rbuf, the
// event loop holding a Conn&) may still be using it. The loop reaps the
// doomed set at a safe point after each epoll batch.
std::unordered_set<int> doomed;
int epfd = -1;

void doom(int fd) { doomed.insert(fd); }

void set_nonblock(int fd) { fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) | O_NONBLOCK); }

void watch(int fd, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0);
  ev.data.fd = fd;
  epoll_ctl(epfd, EPOLL_CTL_MOD, fd, &ev);
}

void close_conn(int fd) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  for (const auto& t : it->second.topics) {
    auto s = subs.find(t);
    if (s != subs.end()) {
      s->second.erase(fd);
      if (s->second.empty()) subs.erase(s);
    }
  }
  epoll_ctl(epfd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  conns.erase(it);
}

// Queue bytes on a connection; flush greedily, arm EPOLLOUT on backlog.
void send_bytes(Conn& c, const char* data, size_t n) {
  if (doomed.count(c.fd)) return;
  if (c.wbuf.size() - c.woff == 0) {
    // fast path: try a direct write first
    ssize_t w = ::send(c.fd, data, n, MSG_NOSIGNAL);
    if (w == (ssize_t)n) return;
    if (w < 0) {
      if (errno != EAGAIN && errno != EWOULDBLOCK) { doom(c.fd); return; }
      w = 0;
    }
    data += w;
    n -= (size_t)w;
  }
  // backlog = bytes actually pending, not the already-flushed prefix
  if (c.wbuf.size() - c.woff + n > kMaxWriteBacklog) { doom(c.fd); return; }
  c.wbuf.append(data, n);
  watch(c.fd, true);
}

void flush(Conn& c) {
  if (doomed.count(c.fd)) return;
  while (c.woff < c.wbuf.size()) {
    ssize_t w = ::send(c.fd, c.wbuf.data() + c.woff, c.wbuf.size() - c.woff,
                       MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      doom(c.fd);
      return;
    }
    c.woff += (size_t)w;
  }
  if (c.woff == c.wbuf.size()) {
    c.wbuf.clear();
    c.woff = 0;
    watch(c.fd, false);
    return;
  }
  // partial drain: compact once the dead prefix dominates, so a slow
  // subscriber doesn't pin flushed bytes in memory indefinitely
  if (c.woff >= (64u << 10) && c.woff > c.wbuf.size() / 2) {
    c.wbuf.erase(0, c.woff);
    c.woff = 0;
  }
}

void route(const std::string& topic, const char* frame, size_t frame_len) {
  auto s = subs.find(topic);
  if (s == subs.end()) return;
  // copy: send_bytes may close (and erase) subscribers mid-iteration
  std::vector<int> targets(s->second.begin(), s->second.end());
  for (int fd : targets) {
    auto it = conns.find(fd);
    if (it != conns.end()) send_bytes(it->second, frame, frame_len);
  }
}

// Parse complete frames out of c.rbuf. Returns false on protocol error.
bool drain_frames(Conn& c) {
  size_t off = 0;
  while (true) {
    if (c.rbuf.size() - off < 4) break;
    uint32_t len;
    memcpy(&len, c.rbuf.data() + off, 4);
    len = ntohl(len);
    if (len > kMaxFrame || len < 3) return false;
    if (c.rbuf.size() - off < 4 + (size_t)len) break;
    const char* p = c.rbuf.data() + off + 4;
    char op = p[0];
    uint16_t tlen;
    memcpy(&tlen, p + 1, 2);
    tlen = ntohs(tlen);
    if ((size_t)3 + tlen > len) return false;
    std::string topic(p + 3, tlen);
    if (op == 'S') {
      subs[topic].insert(c.fd);
      c.topics.insert(topic);
    } else if (op == 'P') {
      // forward the whole original frame (header included) verbatim
      route(topic, c.rbuf.data() + off, 4 + (size_t)len);
    } else {
      return false;
    }
    off += 4 + (size_t)len;
  }
  c.rbuf.erase(0, off);
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  signal(SIGPIPE, SIG_IGN);
  int port = argc > 1 ? atoi(argv[1]) : 0;
  const char* host = argc > 2 ? argv[2] : "127.0.0.1";

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    fprintf(stderr, "bad host %s\n", host);
    return 1;
  }
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0 || listen(lfd, 128) != 0) {
    perror("bind/listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  getsockname(lfd, (sockaddr*)&addr, &alen);
  printf("LISTENING %d\n", ntohs(addr.sin_port));
  fflush(stdout);
  set_nonblock(lfd);

  epfd = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = lfd;
  epoll_ctl(epfd, EPOLL_CTL_ADD, lfd, &ev);

  std::vector<epoll_event> events(256);
  char buf[1 << 16];
  while (true) {
    int n = epoll_wait(epfd, events.data(), (int)events.size(), -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      perror("epoll_wait");
      return 1;
    }
    for (int i = 0; i < n; ++i) {
      int fd = events[i].data.fd;
      if (fd == lfd) {
        while (true) {
          int cfd = accept(lfd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = cfd;
          epoll_ctl(epfd, EPOLL_CTL_ADD, cfd, &cev);
          conns[cfd].fd = cfd;
        }
        continue;
      }
      auto it = conns.find(fd);
      if (it == conns.end() || doomed.count(fd)) continue;
      Conn& c = it->second;
      if (events[i].events & (EPOLLHUP | EPOLLERR)) {
        doom(fd);
        continue;
      }
      if (events[i].events & EPOLLOUT) flush(c);
      if (events[i].events & EPOLLIN) {
        if (doomed.count(fd)) continue;  // flush may have doomed it
        bool dead = false;
        while (true) {
          ssize_t r = recv(fd, buf, sizeof buf, 0);
          if (r > 0) {
            c.rbuf.append(buf, (size_t)r);
            continue;
          }
          if (r == 0) { dead = true; }
          else if (errno != EAGAIN && errno != EWOULDBLOCK) { dead = true; }
          break;
        }
        // drain_frames may route to (and doom) any conn, including this
        // one — it never frees, so parsing c.rbuf stays safe
        if (!drain_frames(c)) dead = true;  // protocol violation
        if (dead) doom(fd);
      }
    }
    // safe point: no Conn& is live across this batch boundary
    for (int fd : doomed) close_conn(fd);
    doomed.clear();
  }
}
