// Negacyclic NTT kernels for the RNS-CKKS secure profile.
//
// TPU-era equivalent of the reference's native trust-stack components
// (the reference delegates CKKS entirely to TenSEAL's C++; here the
// scheme is in-tree — fedml_tpu/core/fhe/ckks.py — and this kernel
// replaces its numpy NTT butterfly on the hot path: encrypt/decrypt of
// LoRA-sized payloads is thousands of N=8192 polynomial products).
// Parity with the numpy twin is exact (modular arithmetic), enforced by
// tests/test_trust_round3.py.
//
// Build:  make -C native        (produces native/libntt.so)
// Bind:   ctypes (fedml_tpu/core/fhe/ckks.py), no pybind11 needed.
//
// Moduli are NTT-friendly primes q < 2^31 (q ≡ 1 mod 2N), so products
// fit __int128-free in 64 bits only via (a*b)%q with a,b < 2^31 — we use
// __int128 where available anyway for clarity and safety.

#include <cstdint>
#include <cstddef>
#include <cstring>
#include <map>
#include <mutex>
#include <utility>
#include <vector>

namespace {

inline uint64_t mulmod(uint64_t a, uint64_t b, uint64_t q) {
#ifdef __SIZEOF_INT128__
    return (uint64_t)(((__uint128_t)a * b) % q);
#else
    return (a * b) % q;  // safe for q < 2^32 operands
#endif
}

inline uint64_t powmod(uint64_t a, uint64_t e, uint64_t q) {
    uint64_t r = 1 % q;
    a %= q;
    while (e) {
        if (e & 1) r = mulmod(r, a, q);
        a = mulmod(a, a, q);
        e >>= 1;
    }
    return r;
}

// Precomputed tables for one (q, psi, N): bit-reversal permutation,
// stage twiddles for the cyclic core (w = psi^2), and the psi twists.
struct Plan {
    uint64_t q, n, n_inv;
    std::vector<uint32_t> bitrev;
    std::vector<uint64_t> w_fwd, w_inv;      // stage-major twiddles
    std::vector<uint64_t> psi_pow, psi_inv_pow;
};

std::map<std::pair<uint64_t, uint64_t>, Plan> g_plans;
std::mutex g_mu;

const Plan& get_plan(uint64_t q, uint64_t psi, uint64_t n) {
    std::lock_guard<std::mutex> lk(g_mu);
    auto key = std::make_pair(q, psi);
    auto it = g_plans.find(key);
    if (it != g_plans.end()) return it->second;
    Plan p;
    p.q = q;
    p.n = n;
    p.n_inv = powmod(n, q - 2, q);
    p.bitrev.resize(n);
    uint32_t bits = 0;
    while ((1ull << bits) < n) ++bits;
    for (uint64_t i = 0; i < n; ++i) {
        uint64_t r = 0;
        for (uint32_t b = 0; b < bits; ++b)
            if (i & (1ull << b)) r |= 1ull << (bits - 1 - b);
        p.bitrev[i] = (uint32_t)r;
    }
    uint64_t w = mulmod(psi, psi, q);            // primitive n-th root
    uint64_t psi_inv = powmod(psi, q - 2, q);
    p.psi_pow.resize(n);
    p.psi_inv_pow.resize(n);
    uint64_t acc = 1, acc_i = 1;
    for (uint64_t i = 0; i < n; ++i) {
        p.psi_pow[i] = acc;
        p.psi_inv_pow[i] = acc_i;
        acc = mulmod(acc, psi, q);
        acc_i = mulmod(acc_i, psi_inv, q);
    }
    // stage-major twiddles: for len = 2,4,...,n store len/2 powers of
    // base = w^(n/len) — total n-1 values per direction
    p.w_fwd.reserve(n);
    p.w_inv.reserve(n);
    for (uint64_t len = 2; len <= n; len <<= 1) {
        uint64_t base = powmod(w, n / len, q);
        uint64_t base_inv = powmod(base, q - 2, q);
        uint64_t t = 1, ti = 1;
        for (uint64_t j = 0; j < len / 2; ++j) {
            p.w_fwd.push_back(t);
            p.w_inv.push_back(ti);
            t = mulmod(t, base, q);
            ti = mulmod(ti, base_inv, q);
        }
    }
    return g_plans.emplace(key, std::move(p)).first->second;
}

// In-place cyclic NTT core on one row (already bit-rev permuted input?
// no — permutes internally), matching the numpy twin's math exactly.
void core(uint64_t* a, const Plan& p, bool inverse) {
    const uint64_t q = p.q, n = p.n;
    // bit-reversal permutation
    for (uint64_t i = 0; i < n; ++i) {
        uint32_t j = p.bitrev[i];
        if (i < j) std::swap(a[i], a[j]);
    }
    const std::vector<uint64_t>& tw = inverse ? p.w_inv : p.w_fwd;
    size_t toff = 0;
    for (uint64_t len = 2; len <= n; len <<= 1) {
        uint64_t half = len >> 1;
        for (uint64_t blk = 0; blk < n; blk += len) {
            for (uint64_t j = 0; j < half; ++j) {
                uint64_t u = a[blk + j];
                uint64_t t = mulmod(a[blk + j + half], tw[toff + j], q);
                a[blk + j] = u + t < q ? u + t : u + t - q;
                a[blk + j + half] = u >= t ? u - t : u + q - t;
            }
        }
        toff += half;
    }
}

void polymul_rows(const uint64_t* fa,   // NTT(pretwist(a)) [N], shared
                  const int64_t* u,     // [B, N] second operands
                  int64_t* out,         // [B, N]
                  int64_t n_rows, const Plan& p) {
    const uint64_t q = p.q, n = p.n;
    std::vector<uint64_t> buf(n);
    for (int64_t r = 0; r < n_rows; ++r) {
        const int64_t* row = u + r * n;
        for (uint64_t i = 0; i < n; ++i) {
            uint64_t v = (uint64_t)(row[i] % (int64_t)q + (int64_t)q) % q;
            buf[i] = mulmod(v, p.psi_pow[i], q);
        }
        core(buf.data(), p, false);
        for (uint64_t i = 0; i < n; ++i) buf[i] = mulmod(buf[i], fa[i], q);
        core(buf.data(), p, true);
        int64_t* orow = out + r * n;
        for (uint64_t i = 0; i < n; ++i)
            orow[i] = (int64_t)mulmod(mulmod(buf[i], p.n_inv, q),
                                      p.psi_inv_pow[i], q);
    }
}

}  // namespace

extern "C" {

// out[B,N] = a[N] (*) u[B,N] mod (X^N+1, q) — one fixed operand
// (public key / secret key poly) against a batch. psi is a primitive
// 2N-th root of unity mod q (the caller's _NTTPlan already found one).
void ntt_polymul_bcast(const int64_t* a, const int64_t* u, int64_t* out,
                       int64_t n_rows, int64_t n, int64_t q, int64_t psi) {
    const Plan& p = get_plan((uint64_t)q, (uint64_t)psi, (uint64_t)n);
    std::vector<uint64_t> fa(n);
    for (int64_t i = 0; i < n; ++i) {
        uint64_t v = (uint64_t)(a[i] % q + q) % q;
        fa[i] = mulmod(v, p.psi_pow[i], (uint64_t)q);
    }
    core(fa.data(), p, false);
    polymul_rows(fa.data(), u, out, n_rows, p);
}

// Pairwise batch variant: out[r] = a[r] (*) u[r]. Used where both
// operands vary (none on the current hot path, provided for parity).
void ntt_polymul_batch(const int64_t* a, const int64_t* u, int64_t* out,
                       int64_t n_rows, int64_t n, int64_t q, int64_t psi) {
    for (int64_t r = 0; r < n_rows; ++r)
        ntt_polymul_bcast(a + r * n, u + r * n, out + r * n, 1, n, q, psi);
}

}  // extern "C"
