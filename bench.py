#!/usr/bin/env python
"""Benchmark driver entry — prints ONE JSON line.

Metric (BASELINE.json): FedAvg rounds/sec/chip. The reference publishes no
numbers (BASELINE.md), so vs_baseline is measured against the reference's
canonical SP config shape executed by our own SP engine on the same
hardware (sequential host loop == what FedML's sp backend does), i.e.
vs_baseline = mesh-parallel rounds/sec ÷ sequential rounds/sec.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data import load_federated

    # canonical config #1 shape (reference simulation_sp/fedml_config.yaml):
    # LR on MNIST-shaped data, 1000 clients total, 10 per round
    def cfg(backend):
        return {
            "common_args": {"training_type": "simulation", "random_seed": 0},
            "data_args": {
                "dataset": "mnist",
                "partition_method": "hetero",
                "partition_alpha": 0.5,
                "train_size": 60000,
                "test_size": 10000,
            },
            "model_args": {"model": "lr"},
            "train_args": {
                "backend": backend,
                "federated_optimizer": "FedAvg",
                "client_num_in_total": 1000,
                "client_num_per_round": 10,
                "comm_round": 20,
                "epochs": 1,
                "batch_size": 10,
                "learning_rate": 0.03,
                "frequency_of_the_test": 100,
            },
        }

    import jax

    n_chips = jax.device_count()

    def run(backend):
        args = fedml_tpu.init(load_arguments_from_dict(cfg(backend)))
        ds = load_federated(args)
        model = models_mod.create(args, ds.class_num)
        if backend == "mesh":
            from fedml_tpu.simulation.parallel.mesh_simulator import MeshFedAvgAPI

            api = MeshFedAvgAPI(args, None, ds, model)
        else:
            from fedml_tpu.simulation.sp.fedavg_api import FedAvgAPI

            api = FedAvgAPI(args, None, ds, model)
        api.train_one_round(0)  # warm-up: compile outside the timed region
        t0 = time.time()
        rounds = int(args.comm_round)
        for r in range(1, rounds + 1):
            api.train_one_round(r)
        return rounds / (time.time() - t0)

    sp_rps = run("sp")
    mesh_rps = run("mesh")
    value = mesh_rps / n_chips
    print(
        json.dumps(
            {
                "metric": "fedavg_rounds_per_sec_per_chip",
                "value": round(value, 4),
                "unit": "rounds/s/chip",
                "vs_baseline": round(mesh_rps / sp_rps, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
