#!/usr/bin/env python
"""Benchmark driver entry — prints ONE JSON line.

Flagship metric (BASELINE.json): **FedAvg rounds/sec/chip on the LLM path
(Llama-2-7B LoRA fine-tune, 8 clients)** — the federated round is 8
clients' compiled local steps + LoRA-dict FedAvg on the real chip, at the
TRUE 7B config (6.76B params, bf16 frozen base in 13.5 of 15.75 GB HBM).
FEDML_BENCH_MODEL=1b reruns the round-2/3 1.1B comparison shape.

vs_baseline: the reference (FedML, torch eager) cannot run on TPU at all —
its achievable throughput on this host is a torch-CPU step of the *same*
architecture/shape (transformers LlamaForCausalLM, fp32 eager, measured, then
scaled by tokens). vs_baseline = our measured round throughput ÷ the
reference engine's measured token throughput on identical work.

Timing methodology (important on this platform): the TPU is reached through
a tunnel whose ``block_until_ready`` acknowledges *dispatch*, not execution —
so every measurement here (a) chains real data dependencies between
iterations, (b) forces one device→host scalar readback at the end, and
(c) reports the *difference* between a long and a short chain so the fixed
readback round-trip cancels. Validated against a known-FLOPs 8192³ matmul
(≈95 TFLOP/s ≈ 48% of v5e peak — sane; the naive method reported 70 PFLOP/s).

The JSON line also carries (in "extra"):
  - llm_tokens_per_sec and mfu — model-FLOPs utilization vs chip peak bf16.
    With LoRA, frozen-weight grads are dead-code-eliminated by XLA, so the
    model-FLOPs basis is 4N·tokens (fwd 2N + activation-grad 2N) + 6N_lora +
    causal attention term — NOT the dense-training 6N.
  - flash_vs_xla_speedup (Pallas flash attention vs plain-XLA attention,
    fwd+bwd, same shapes) — proves the kernel earns its keep.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# chip peak bf16 FLOP/s by device kind — owned by the profiling layer now
# (telemetry/profiling/roofline.py) so bench, report, doctor and the live
# watch all read ONE table; re-exported here for external callers
from fedml_tpu.telemetry.profiling.roofline import PEAK_BF16  # noqa: E402


def chain_time(run_chain, n_short: int, n_long: int, trials: int = 2) -> float:
    """Seconds/iteration via the long-minus-short chained-readback method.

    ``run_chain(n)`` must execute n *data-dependent* iterations ending in a
    device→host scalar readback, and return elapsed wall seconds.

    Non-positive estimates are discarded: a late compile (e.g. the first
    donated-buffer re-entry of a fused program recompiles for the new
    input layout) can inflate one t_short and make (long−short) negative
    — measured round 5; min() must never crown that artifact.
    """
    run_chain(n_short)  # throwaway: absorbs compile/transfer transients
    best = float("inf")
    last_long = None
    for _ in range(trials):
        t_short = run_chain(n_short)
        t_long = run_chain(n_long)
        last_long = t_long
        est = (t_long - t_short) / (n_long - n_short)
        if est > 0:
            best = min(best, est)
    if best == float("inf"):  # every trial polluted: report the upper bound
        best = last_long / n_long
    return best


def llm_shape(hbm_bytes: float):
    """Pick a Llama shape sized to the chip's HBM (fp32 masters + grads)."""
    from fedml_tpu.models.llm.llama import LlamaConfig

    which = os.environ.get("FEDML_BENCH_MODEL", "auto").lower()
    if which not in ("auto", "7b", "7b_qlora", "1b"):
        raise SystemExit(
            f"FEDML_BENCH_MODEL={which!r}: expected auto|7b|7b_qlora|1b — "
            "refusing to silently bench the tiny-dev model as the flagship")
    if hbm_bytes >= 12e9 and which == "7b_qlora":
        # QLoRA variant (opt-in): int8 frozen base frees ~6.6 GB → B=4
        # fits; measured MFU 0.786 vs 0.664 bf16 (PERF_NOTES r5 add. 6).
        # Not the default flagship so the metric stays comparable across
        # rounds (bf16 base, B1/T512).
        import jax.numpy as jnp

        cfg = LlamaConfig.llama2_7b(
            lora_rank=16, remat=False, remat_policy="none",
            param_dtype=jnp.bfloat16,
        )
        return cfg, 4, 512
    if hbm_bytes >= 12e9 and which in ("auto", "7b"):
        # The NORTH-STAR model (BASELINE.json: Llama-2-7B LoRA): true
        # 7B config — hidden 4096, inter 11008, 32 layers, 32 MHA heads,
        # 6.76B params. bf16 frozen base = 13.5 GB of the v5e's 15.75 GB
        # HBM; fits with LoRA-only fp32 masters at B=1/T=512, remat OFF
        # (honest step 105-107 ms / MFU 0.66-0.67 — short probe chains
        # read up to 8% fast, PERF_NOTES r5 addendum 5; B1/T1024
        # remat-off OOMs by 435 MB; base_quantize int8 [QLoRA] fits
        # B4/T512 at MFU 0.786 — tools/probe_7b.py reproduces all).
        import jax.numpy as jnp

        cfg = LlamaConfig.llama2_7b(
            lora_rank=16, remat=False, remat_policy="none",
            param_dtype=jnp.bfloat16,
        )
        return cfg, 1, 512  # batch, seq
    if hbm_bytes >= 12e9 and which == "1b":
        # ~1.1B (TinyLlama-class) comparison shape — the round-2/3
        # flagship, kept for cross-round regression tracking
        import jax.numpy as jnp

        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=22, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=2048,
            lora_rank=16, remat=False, remat_policy="none",
            param_dtype=jnp.bfloat16,
        )
        return cfg, 8, 1024
    # CPU / tiny-dev fallback so the bench always completes
    cfg = LlamaConfig.tiny(lora_rank=8)
    return cfg, 4, 128


def catalog_flops(name: str):
    """XLA-cost FLOPs of a cataloged program, or None.

    The per-program ``cost_analysis()`` extraction that used to live here
    as a private ``xla_cost_flops`` helper moved into the program catalog
    (``telemetry/profiling/catalog.py``): the hot-path programs register
    there at first compile, the AOT executable is reused for the
    measurement chain (no second compile), and every consumer — this
    bench, ``telemetry report``, the doctor, ``tools/bench_compare`` —
    reads the SAME record. None where cost analysis was unavailable on
    this backend; callers fall back to the analytic model and stamp
    ``mfu_source: "analytic"``.
    """
    from fedml_tpu.telemetry.profiling import get_catalog

    for rec in get_catalog().records():
        if rec.name == name and rec.flops > 0:
            return rec.flops
    return None


def lora_flops_model(params, cfg, batch: int, seq: int):
    """(model FLOPs per LoRA optimizer step, total param count) — see module
    docstring for the FLOPs basis."""
    import jax

    from fedml_tpu.train.llm.trainer import is_lora_path

    n_total = sum(x.size for x in jax.tree.leaves(params))
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    n_lora = sum(v.size for p, v in flat if is_lora_path(p))
    tokens = batch * seq
    matmul = (4.0 * (n_total - n_lora) + 6.0 * n_lora) * tokens
    # causal attention: fwd 2·B·T²·h per layer (QKᵀ+AV halved), bwd ≈ 2×
    attn = 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq * tokens * 0.5
    return matmul + attn, n_total


def bench_flash(batch=2, heads=16, seq=4096, head_dim=64):
    """Pallas flash vs plain-XLA attention, fwd+bwd, chained timing.

    T=4096 is the long-context regime the kernel exists for (measured sweep
    on v5e: flash 2.4× at T=2048, 5× at 4096, >100× at 8192, and the naive
    path OOMs at 16384 where flash still runs)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.flash_attention import flash_attention, reference_attention

    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    shape = (batch, heads, seq, head_dim)
    q0 = jax.random.normal(k1, shape, jnp.bfloat16)
    k = jax.random.normal(k2, shape, jnp.bfloat16)
    v = jax.random.normal(k3, shape, jnp.bfloat16)

    def make(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True).astype(jnp.float32))

        grad = jax.jit(jax.grad(loss))

        def run_chain(n):
            t0 = time.perf_counter()
            q = q0
            for _ in range(n):
                q = q - 1e-6 * grad(q, k, v)  # real data dependency
            float(jnp.sum(q.astype(jnp.float32)))
            return time.perf_counter() - t0

        return run_chain

    try:
        # the kernel is ~4 ms/iter at this shape — the chain must be long
        # enough that (long-short) clears the ~10 ms tunnel RTT noise
        t_flash = chain_time(make(flash_attention), 4, 64, trials=3)
    except Exception:
        return None  # no TPU pallas path on this backend
    t_ref = chain_time(make(reference_attention), 2, 10, trials=3)
    return {
        "flash_ms": round(t_flash * 1e3, 3),
        "xla_ms": round(t_ref * 1e3, 3),
        "flash_vs_xla_speedup": round(t_ref / t_flash, 3),
    }


def bench_reference_torch(cfg):
    """Measured throughput of the reference engine (torch eager, CPU — the
    only hardware it runs on here) on the same architecture.

    Times one fwd+bwd on a reduced token count and scales linearly in
    tokens (eager torch CPU is compute-bound; linear scaling flatters it if
    anything, since bigger batches amortize dispatch).
    Returns reference tokens/sec, or None if torch is unusable.
    """
    try:
        import torch
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM as HFModel
    except Exception:
        return None, "reference engine unavailable"
    try:
        torch.set_num_threads(os.cpu_count() or 8)
        # at 7B scale a full-depth fp32 torch step takes many minutes on
        # this host's CPU: measure a reduced-depth model with the SAME
        # per-layer shape and scale by depth (linear in layers — embed/lm
        # head overhead is ignored, which flatters the reference)
        layers = min(cfg.num_hidden_layers, 4)
        hf = HFConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            max_position_embeddings=cfg.max_position_embeddings,
            use_cache=False,
        )
        model = HFModel(hf)
        b, t = 1, 128 if cfg.hidden_size >= 4096 else 256
        x = torch.randint(0, cfg.vocab_size, (b, t))
        out = model(input_ids=x, labels=x)  # warm once (allocations)
        out.loss.backward()
        model.zero_grad(set_to_none=True)
        t0 = time.perf_counter()
        out = model(input_ids=x, labels=x)
        out.loss.backward()
        dt = time.perf_counter() - t0
        kind = "reference torch-eager CPU, same arch/work, token-scaled"
        if layers < cfg.num_hidden_layers:
            # the 7B ratio is depth-EXTRAPOLATED, not measured-vs-measured
            # — carry that caveat in the emitted JSON (ADVICE r4)
            kind += (f", depth-extrapolated {layers}/"
                     f"{cfg.num_hidden_layers} layers")
        return (b * t) / dt * (layers / cfg.num_hidden_layers), kind
    except Exception:
        return None, "reference engine unavailable"


def main() -> None:
    if "--compare" in sys.argv:
        # regression gate: diff the newest two archived BENCH_*.json and
        # fail on >10% drop of the headline metric (tools/bench_compare)
        from tools.bench_compare import run_compare

        row = run_compare(os.path.dirname(os.path.abspath(__file__)))
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--wire" in sys.argv:
        # compressed-transport micro-bench: one JSON line per codec
        # (bytes before/after, encode/decode ms) on a resnet-sized
        # pytree — same ONE-line-per-record contract as --stage. The
        # 4-bit rows carry ratio gates (>=6x vs f32, >=1.8x vs int8);
        # a failed gate exits 1 like every other gated bench mode.
        from tools.wire_bench import apply_wire_gates, run_wire_bench

        rows = run_wire_bench()
        for row in rows:
            print(json.dumps(row))
        if not apply_wire_gates(rows):
            raise SystemExit(1)
        return

    if "--secagg" in sys.argv:
        # secure-aggregation gates: masked wire bytes ≤ 1.2× plain int8
        # on a resnet-sized delta, and a chaos-killed masked round
        # closing via seed-reveal recovery at ≤ 1 extra round-trip per
        # dropout — one JSON line (see tools/secagg_bench.py)
        from tools.secagg_bench import run_secagg_bench

        row = run_secagg_bench()
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--chaos" in sys.argv:
        # resilience micro-bench: seam overhead on the hot send path
        # (< 1% acceptance) + broker kill/restart recovery time — same
        # ONE-JSON-line contract as --wire/--stage
        from tools.chaos_bench import run_chaos_bench

        row = run_chaos_bench()
        print(json.dumps(row))
        if not (row["ok_overhead"] and row["recovered"]):
            raise SystemExit(1)
        return

    if "--recover" in sys.argv:
        # crash-anywhere durability gates: journal seam < 2% of a durable
        # round, kill-the-server MTTR within budget, every journaled
        # upload salvaged (none retrained), identity-codec final params
        # bit-identical to an uninterrupted run — one JSON line
        # (tools/recover_bench.py; FEDML_RECOVER_* env knobs)
        from tools.recover_bench import run_recover_bench

        row = run_recover_bench()
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--integrity" in sys.argv:
        # update-integrity gates: ring 1's screen seam < 2% of a round,
        # a poisoned same-seed federation (NaN + magnitude poison at the
        # comm seam) finishing within tolerance of clean with every
        # corrupt upload screened or rolled back, and a round rollback
        # (reject -> restore -> re-run) inside its MTTR budget — one
        # JSON line (tools/integrity_bench.py; FEDML_INTEGRITY_* env)
        from tools.integrity_bench import run_integrity_bench

        row = run_integrity_bench()
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--preempt" in sys.argv:
        # job-plane gates: deterministic crasher contained (bounded
        # attempts, bit-deterministic backoff), drained node's federation
        # finishes with salvaged uploads never retrained, identity-codec
        # final params bit-identical to an undisturbed run, and
        # preempt-to-resumed MTTR within budget — one JSON line
        # (tools/preempt_bench.py; FEDML_PREEMPT_* env knobs)
        from tools.preempt_bench import run_preempt_bench

        row = run_preempt_bench()
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--tree" in sys.argv:
        # hierarchical-federation bench: a seeded 3-tier 100k-client
        # aggregation tree on this machine — rounds/s, peak wire bytes
        # per tier, peak host RSS (one JSON line, env-tunable via
        # FEDML_TREE_*; see tools/tree_bench.py)
        from tools.tree_bench import run_tree_bench

        row = run_tree_bench()
        print(json.dumps(row))
        if not (row["completed"] and row["ok_no_f32_trees"]):
            raise SystemExit(1)
        return

    if "--fa" in sys.argv:
        # federated-analytics gates: masked sketch wire ≤ 1.2× the plain
        # int32 sketch, heavy-hitter recall/precision ≥ 0.95 vs the
        # plaintext reference on the same seeded data, and the
        # traced-client-sketch proof (no host-side per-client plaintext
        # in masked mode) — one JSON line, archived as FA_r01.json
        # (tools/fa_bench.py; FEDML_FA_* env knobs)
        from tools.fa_bench import run_fa_bench, write_artifact

        row = run_fa_bench()
        write_artifact(row)
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--live" in sys.argv:
        # live-telemetry overhead gate: the SAME in-proc federation run
        # with streaming on vs off (rounds/s within tolerance), the
        # micro-measured per-round streaming seam, and the steady-state
        # telemetry wire bytes per node per round (bounded) — one JSON
        # line (tools/live_bench.py; FEDML_LIVE_* env knobs)
        from tools.live_bench import run_live_bench

        row = run_live_bench()
        print(json.dumps(row))
        if not (row["completed"] and row["ok_overhead"] and row["ok_bytes"]
                and row["ok_rounds"]):
            raise SystemExit(1)
        return

    if "--tracepath" in sys.argv:
        # causal-tracing overhead gate: the SAME in-proc federation run
        # with span streaming on vs off (rounds/s within tolerance), the
        # micro-measured span-batch seam as a fraction of a round
        # (<1%), and the steady-state trace wire bytes per node per
        # round (bounded) — one JSON line (tools/tracepath_bench.py;
        # FEDML_TRACEPATH_* env knobs)
        from tools.tracepath_bench import run_tracepath_bench

        row = run_tracepath_bench()
        print(json.dumps(row))
        # ok_rounds (the end-to-end on/off rounds/s ratio) is reported
        # but not gated: at in-proc round walls the A/B diff is host
        # noise — the deterministic seam measurement is the gate
        if not (row["completed"] and row["ok_overhead"]
                and row["ok_bytes"]):
            raise SystemExit(1)
        return

    if "--serve" in sys.argv:
        # live-serving SLO gate: sustained concurrent HTTP load through
        # the OpenAI endpoint across N federation hot swaps — qps,
        # latency percentiles vs the no-swap baseline, swap stalls,
        # dropped MUST be 0 (tools/serve_bench.py; FEDML_SERVE_* env)
        from tools.serve_bench import run_serve_bench, write_artifact

        row = run_serve_bench()
        print(json.dumps(row))
        write_artifact(row)
        # ok_obs_overhead gates here (not inside `completed`): the
        # deterministic micro-measured request-observability seam must
        # stay under 2% of the inter-token latency
        if not (row["completed"] and row["ok_p99"]
                and row["ok_obs_overhead"]):
            raise SystemExit(1)
        return

    if "--profile" in sys.argv:
        # attribution-overhead gate: the SAME run with the program
        # catalog on vs off (interleaved trials) plus the deterministic
        # per-call wrapper seam — always-on profiling must cost < 1%
        # rounds/s (tools/profile_bench.py; FEDML_PROFILE_* env knobs)
        from tools.profile_bench import run_profile_bench

        row = run_profile_bench()
        print(json.dumps(row))
        if not (row["completed"] and row["ok_overhead"] and row["ok_rounds"]):
            raise SystemExit(1)
        return

    if "--multichip" in sys.argv:
        # mesh scale-out gates: fused-round scaling efficiency across
        # N = 1, 2, 4, … devices (client-parallel lanes on dp, base on
        # fsdp) and the per-shard HBM plan under the per-device limit —
        # one JSON line, archived as MULTICHIP_r06.json
        # (tools/multichip_bench.py; FEDML_MULTICHIP_* env knobs)
        from tools.multichip_bench import run_multichip_bench, write_artifact

        row = run_multichip_bench()
        write_artifact(row)
        print(json.dumps(row))
        if not row["ok"]:
            raise SystemExit(1)
        return

    if "--stage" in sys.argv:
        # staging-path micro-bench (pipelined round engine): staged
        # bytes/s, vectorized assembly ms, prefetch overlap ratio —
        # same ONE-JSON-line contract, orthogonal to the LLM metric
        from tools.stage_bench import run_stage_bench

        print(json.dumps(run_stage_bench(
            prefetch="--no-prefetch" not in sys.argv)))
        return

    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    n_chips = jax.device_count()
    try:
        hbm = float(dev.memory_stats()["bytes_limit"])
    except Exception:
        hbm = 16e9 if dev.platform == "tpu" else 0.0

    from fedml_tpu.train.llm.trainer import LLMTrainer, extract_lora

    cfg, batch, seq = llm_shape(hbm)

    # flash kernel micro-bench FIRST: its XLA reference path materializes
    # multi-GB T×T score tensors, which cannot coexist with the 7B
    # trainer's 13.5 GB of live params later in this process.
    # FEDML_BENCH_SKIP_FLASH=1 skips it (A/B tool for memory-state
    # effects on the trainer sections; see PERF_NOTES MFU-variance note)
    skip_flash = os.environ.get("FEDML_BENCH_SKIP_FLASH") == "1"
    flash = (bench_flash()
             if dev.platform == "tpu" and not skip_flash else None)

    class Args:
        max_seq_length = seq
        per_device_batch_size = batch
        gradient_accumulation_steps = 1
        learning_rate = 1e-4
        mesh_dp = 1
        mesh_fsdp = -1  # absorb all devices → works on multi-chip hosts too
        mesh_tp = 1
        mesh_sp = 1
        random_seed = 0
        # FEDML_BENCH_QUANTIZE=int8|int4|nf4 picks the frozen-base
        # residency directly; 7b_qlora keeps its int8 default.
        # FEDML_BENCH_QUANTIZE_MIN_SIZE lowers the kernel-size floor so
        # the CPU tiny-dev model exercises the quantized-resident path.
        base_quantize = os.environ.get("FEDML_BENCH_QUANTIZE", "").lower() \
            or ("int8" if os.environ.get(
                "FEDML_BENCH_MODEL", "").lower() == "7b_qlora" else "")
        base_quantize_min_size = int(os.environ.get(
            "FEDML_BENCH_QUANTIZE_MIN_SIZE", 65536))

    trainer = LLMTrainer(cfg, Args())
    trainer.init(seed=0)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, seq), dtype=np.int32)
    x = jnp.asarray(tokens)
    y = jnp.asarray((tokens + 1) % cfg.vocab_size)
    m = jnp.ones((batch,), jnp.float32)

    # --- A. single-step throughput: tokens/sec + MFU ----------------------
    # the train step donates (params, opt_state): iterations are chained by
    # construction; the final loss readback forces the whole queue.
    # FLOPs basis: XLA's own cost model via the program catalog — the
    # wrapped step AOT-compiles ONCE at its first (throwaway) call and
    # every later call runs that same executable, so the chain pays no
    # second compile and the catalog record carries the analysis.
    step_fn = trainer._train_step  # cataloged as "llm/train_step"

    def step_chain(n):
        t0 = time.perf_counter()
        p, o = trainer.params, trainer.opt_state
        loss = None
        for _ in range(n):
            p, o, loss = step_fn(p, o, x[None], y[None], m[None])
        trainer.params, trainer.opt_state = p, o
        float(loss)
        return time.perf_counter() - t0

    sec_per_step = chain_time(step_chain, 2, 22, trials=3)
    step_xla_flops = catalog_flops("llm/train_step")
    tok_per_sec = batch * seq / sec_per_step
    flops_analytic, n_params = lora_flops_model(trainer.params, cfg, batch, seq)
    flops = step_xla_flops if step_xla_flops is not None else flops_analytic
    mfu_source = "xla" if step_xla_flops is not None else "analytic"
    peak = PEAK_BF16.get(dev.device_kind)
    mfu = (flops / sec_per_step / peak) if peak else None

    # --- B. federated LLM round: 8 clients, LoRA FedAvg -------------------
    # the ENTIRE round is one XLA program (compile_federated_round):
    # client-switch, local steps, and the adapter FedAvg run on device with
    # donated buffers — round 4 lost ~22% of this metric to host-Python
    # LoRA merge/extract interleaved between the device steps
    n_clients, local_steps = 8, 2

    fed_round = trainer.compile_federated_round(n_clients, local_steps)
    crng = np.random.default_rng(1)
    xs = np.repeat(  # each client reuses its batch for both local steps
        crng.integers(0, cfg.vocab_size,
                      size=(n_clients, 1, batch, seq), dtype=np.int32),
        local_steps, axis=1)
    ys_r = (xs + 1) % cfg.vocab_size
    ms_r = np.ones((n_clients, local_steps, batch), np.float32)
    wts = np.ones((n_clients,), np.float32)

    # XLA cost model of the WHOLE fused round (client-switch + local
    # steps + FedAvg): flops_per_round comes from the catalog record of
    # the compiled program ("llm/fused_round"), not the analytic 4N
    # approximation; the catalog's AOT executable runs the chain so the
    # cost analysis costs no extra compile
    round_fn = fed_round

    def round_chain(n_rounds):
        t0 = time.perf_counter()
        p, o = trainer.params, trainer.opt_state
        # fresh copy per chain: the donated global-lora buffers from the
        # previous chain are dead
        g = jax.tree.map(jnp.copy, extract_lora(p))
        loss = None
        for _ in range(n_rounds):
            p, o, g, loss = round_fn(p, o, g, xs, ys_r, ms_r, wts)
        trainer.params, trainer.opt_state = p, o
        float(loss)  # readback forces the whole donated chain
        return time.perf_counter() - t0

    round_sec = chain_time(round_chain, 1, 5, trials=3)
    round_xla_flops = catalog_flops("llm/fused_round")
    rounds_per_sec_per_chip = 1.0 / round_sec / n_chips
    round_tokens = n_clients * local_steps * batch * seq

    # --trace-rounds r1,r2: capture a deep device trace of N extra fused
    # rounds AFTER the measurement (tracing inside the timed chain would
    # perturb it) through the budgeted TraceController
    from fedml_tpu.telemetry.profiling import parse_rounds

    trace_rounds = []
    for i, a in enumerate(sys.argv):
        if a == "--trace-rounds" and i + 1 < len(sys.argv):
            trace_rounds = parse_rounds(sys.argv[i + 1])
    if trace_rounds:
        from fedml_tpu.telemetry.profiling import get_trace_controller

        tc = get_trace_controller()
        tc.arm_rounds(trace_rounds,
                      trace_dir=os.environ.get("FEDML_TRACE_DIR",
                                               ".fedml_logs/bench_traces"))
        g = jax.tree.map(jnp.copy, extract_lora(trainer.params))
        p, o = trainer.params, trainer.opt_state
        for r in trace_rounds:
            tc.on_round_start(r)
            p, o, g, loss = round_fn(p, o, g, xs, ys_r, ms_r, wts)
            float(loss)  # drain before stop_trace so the trace sees it
            tc.on_round_end(r)
        trainer.params, trainer.opt_state = p, o

    # --- C. reference engine measured on same work -------------------------
    ref_tps, baseline_kind = bench_reference_torch(cfg)
    if ref_tps is not None:
        ref_round_sec = round_tokens / ref_tps
        vs_baseline = ref_round_sec / round_sec
    else:
        vs_baseline = 0.0

    extra = {
        "device": dev.device_kind,
        "n_chips": n_chips,
        "model": {
            "params": int(n_params),
            "base_quantize": Args.base_quantize or None,
            **{k: getattr(cfg, k) for k in (
                "hidden_size", "intermediate_size", "num_hidden_layers",
                "num_attention_heads", "num_key_value_heads", "vocab_size",
                "lora_rank")},
        },
        "batch": batch,
        "seq_len": seq,
        "llm_tokens_per_sec": round(tok_per_sec, 1),
        "llm_step_ms": round(sec_per_step * 1e3, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        # FLOPs provenance: "xla" = lowered.compile().cost_analysis() on
        # the compiled programs themselves; "analytic" = the hand model
        # (4N + 6N_lora + attn; frozen wgrads DCE'd) where XLA's cost
        # model is unavailable on this backend
        "mfu_source": mfu_source,
        "flops_per_step": round(flops, 1),
        "flops_per_round": round(
            round_xla_flops if round_xla_flops is not None
            else flops_analytic * n_clients * local_steps, 1),
        "flops_per_round_source": ("xla" if round_xla_flops is not None
                                   else "analytic"),
        "mfu_basis": (
            "XLA cost_analysis() flops of the compiled train step"
            if mfu_source == "xla" else
            "LoRA model-flops (4N + 6N_lora + attn); frozen wgrads are DCE'd"),
        "round_shape": {"clients": n_clients, "local_steps": local_steps,
                        "round_tokens": round_tokens},
        "round_path": "fused on-device round: client-switch + local steps "
                      "+ LoRA FedAvg in ONE donated-buffer XLA program",
        "reference_tokens_per_sec": round(ref_tps, 1) if ref_tps else None,
        "baseline_kind": baseline_kind,
        "timing": "chained-dependency, long-minus-short readback (tunnel-safe)",
    }
    # per-program catalog summary (name → flops/bytes/peak-HBM/compile):
    # tools/bench_compare.py diffs these across BENCH files so an MFU or
    # HBM regression is attributed to a PROGRAM, not just whole-run
    # rounds/s
    from fedml_tpu.telemetry.profiling import get_catalog

    extra["programs"] = get_catalog().programs_summary()
    if flash:
        extra.update(flash)

    print(json.dumps({
        "metric": "fedavg_llm_rounds_per_sec_per_chip",
        "value": round(rounds_per_sec_per_chip, 5),
        "unit": "rounds/s/chip",
        "vs_baseline": round(vs_baseline, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
