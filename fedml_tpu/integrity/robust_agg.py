"""Ring 2 — Byzantine-robust aggregation fused into the compressed domain.

Coordinate-wise trimmed mean and median (Yin et al., ICML'18) as
drop-in alternatives to the weighted mean of
:func:`fedml_tpu.compression.fused_weighted_sum`: the stacked client
blocks reduce inside ONE jitted program (``integrity/robust_agg`` in
the program catalog) — per-block dequant → sort along the client axis →
trim → mean — so the server never materializes N decoded f32 client
trees. Dequantized values exist only as XLA temporaries inside the
reduction, the same contract every fused path in this repo holds; the
host-visible peak is the stacked int8 blocks (wire size) plus the one
aggregated f32 tree.

These statistics are SHIFT-EQUIVARIANT, which is why they compose with
the delta wire: ``median_i(g + d_i) = g + median_i(d_i)`` (likewise the
trimmed mean), so the robust statistic of the *deltas* plus the global
equals the reference defenses' statistic of the full client *models* —
up to quantization, which the acceptance tests bound. They are also
deliberately UNWEIGHTED: an ``n_k``-weighted robust statistic would
hand a poisoner back the exact lever (claim a huge sample count) the
robustness exists to remove.

The spec (``agg_robust: trimmed_mean@0.1 | median``) rides the
round-config negotiation header exactly like the PR 3 codec spec, so
every aggregation point of a federation — server, or any tier of an
aggregation tree — applies the same statistic.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.compression.codecs import (
    Codec,
    CompressedTree,
    _is_float_meta,
    get_codec,
)

Pytree = Any

__all__ = [
    "ROBUST_MODES",
    "fused_robust_sum",
    "masked_robust_leaf",
    "parse_robust_spec",
    "resolve_agg_robust",
    "robust_reduce_leaf",
    "robust_spec_str",
    "trim_k",
]

ROBUST_MODES = ("trimmed_mean", "median")


def parse_robust_spec(spec: Any) -> Optional[Tuple[str, float]]:
    """``'trimmed_mean@0.1' | 'trimmed_mean' | 'median' | '' → None``.

    Returns ``(mode, trim_fraction)``; the fraction is per side and only
    meaningful for ``trimmed_mean``. Unknown modes and malformed or
    out-of-range fractions raise ``ValueError`` — a misheard negotiation
    header must fail loudly, not silently average.
    """
    spec = str(spec or "").strip().lower()
    if spec in ("", "none", "off"):
        return None
    base, _, param = spec.partition("@")
    if base not in ROBUST_MODES:
        raise ValueError(
            f"unknown agg_robust mode {base!r}; "
            f"available: {', '.join(ROBUST_MODES)}")
    if base == "median":
        if param:
            raise ValueError(f"agg_robust median takes no parameter ({spec!r})")
        return ("median", 0.0)
    trim = 0.1
    if param:
        try:
            trim = float(param)
        except ValueError:
            raise ValueError(
                f"malformed trim fraction in agg_robust spec {spec!r}"
            ) from None
    if not 0.0 < trim < 0.5:
        raise ValueError(
            f"agg_robust trim fraction must be in (0, 0.5), got {trim}")
    return ("trimmed_mean", trim)


def robust_spec_str(mode: str, trim: float) -> str:
    """The negotiation-header form (inverse of :func:`parse_robust_spec`)."""
    return "median" if mode == "median" else f"trimmed_mean@{trim:g}"


def trim_k(n: int, trim: float) -> int:
    """Per-side trim count for an ``n``-client cohort — the SAME rule the
    reference :class:`TrimmedMeanDefense` applies, so the fused path and
    the decode-fallback defense agree on which ranks are discarded."""
    return min(int(float(trim) * int(n)), (int(n) - 1) // 2)


def masked_robust_leaf(dec: jax.Array, valid: jax.Array, mode: str,
                       trim: float) -> jax.Array:
    """Traced robust statistic over axis 0 with a validity mask.

    The fixed-shape twin of :func:`robust_reduce_leaf` for compiled
    cohort programs where dead/padded slots are weight-masks, not shape
    changes (the PR 6 leaf-chunk contract): invalid rows sort to the
    end behind a big sentinel and the statistic is computed over the
    traced valid count — same +1e-4 truncation guard as the reference
    ``TrimmedMeanDefense.defend_stacked`` so f32 ``trim·nv`` landing
    just under an exact integer can't disagree with the host path.
    """
    big = jnp.float32(3.0e38)
    nv = jnp.sum(valid.astype(jnp.int32))
    vcol = valid.reshape((-1,) + (1,) * (dec.ndim - 1))
    s = jnp.sort(jnp.where(vcol, dec, big), axis=0)
    if mode == "median":
        lo = (nv - 1) // 2
        hi = nv // 2
        return 0.5 * (jnp.take(s, lo, axis=0) + jnp.take(s, hi, axis=0))
    k = jnp.minimum((jnp.float32(trim) * nv + 1e-4).astype(jnp.int32),
                    (nv - 1) // 2)
    rank = jnp.arange(dec.shape[0]).reshape((-1,) + (1,) * (dec.ndim - 1))
    keep = (rank >= k) & (rank < nv - k)
    denom = jnp.maximum(nv - 2 * k, 1).astype(jnp.float32)
    return jnp.sum(jnp.where(keep, s, 0.0), axis=0) / denom


def robust_reduce_leaf(dec: jax.Array, mode: str, k: int) -> jax.Array:
    """Traced robust statistic over axis 0 of dequantized [C, ...] values.

    ``jnp.median`` semantics for even counts (mean of the two middles);
    trimmed mean discards ``k`` per side then averages the rest.
    """
    if mode == "median":
        return jnp.median(dec, axis=0)
    xs = jnp.sort(dec, axis=0)
    n = dec.shape[0]
    kept = jax.lax.slice_in_dim(xs, k, n - k, axis=0)
    return jnp.mean(kept, axis=0)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _robust_agg_program(codec: Codec, meta, mode: str, k: int, stacked):
    """Per-block dequant-sort-trim as ONE program over all leaves."""
    out = []
    for parts, (dt, sh) in zip(stacked, meta):
        if _is_float_meta(dt):
            dec = jax.vmap(
                lambda *p, dt=dt, sh=sh: codec.decode_leaf(p, dt, sh)
            )(*parts).astype(jnp.float32)
        else:
            dec = parts[0].astype(jnp.float32)
        red = robust_reduce_leaf(dec, mode, k)
        from fedml_tpu.compression.codecs import _dtype_from_str

        out.append(red.astype(_dtype_from_str(dt)))
    return tuple(out)


from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_robust_agg_program = _wrap_jit(
    "integrity/robust_agg", _robust_agg_program,
    static_argnums=(0, 1, 2, 3), multi_shape=True)


def fused_robust_sum(cts: Sequence[CompressedTree], mode: str,
                     trim: float = 0.1, mesh=None) -> Pytree:
    """Coordinate-wise robust statistic of ``decode(ct_i)`` over clients.

    The robust twin of :func:`~fedml_tpu.compression.fused_weighted_sum`
    — same homogeneity contract, same stacked-block layout, but the
    reduction is a sort-based statistic instead of an einsum, and there
    are no weights (see module docstring). Bit-deterministic: two
    same-seed runs stack identical blocks and sort identically.

    ``mesh`` (optional, >1-device) runs the same program per-shard:
    coordinate axes split across the mesh, the client axis stays whole,
    so every per-coordinate sort-trim is local to its shard — result
    bit-identical to the unsharded call, per-device bytes ÷ mesh size
    (see :mod:`fedml_tpu.parallel.multichip`).
    """
    if mode not in ROBUST_MODES:
        raise ValueError(f"unknown robust aggregation mode {mode!r}")
    if not cts:
        raise ValueError("empty compressed update list")
    first = cts[0]
    for ct in cts[1:]:
        if (ct.codec != first.codec or ct.version != first.version
                or ct.meta != first.meta
                or ct.is_delta != first.is_delta):
            raise ValueError(
                "cannot robust-fuse heterogeneous compressed updates "
                f"({ct.codec}/v{ct.version} vs {first.codec}/"
                f"v{first.version})")
    codec = get_codec(first.codec)._resolve_wire(first)
    if getattr(codec, "maskable", False):
        raise ValueError(
            "masked (secure-aggregation) updates cannot ride robust "
            "aggregation — per-coordinate sorting needs per-client "
            "values, which the masks exist to hide")
    if codec.name == "topk":
        raise ValueError(
            "agg_robust needs dense per-coordinate values; topk updates "
            "leave most coordinates implicit-zero, which would let a "
            "sparse poisoner dominate every coordinate it keeps — use "
            "int8/bf16/identity with robust aggregation")
    n_leaves = len(first.meta)
    if any(len(ct.arrays) != n_leaves for ct in cts):
        raise ValueError("compressed update leaf count mismatch")
    for ct in cts:
        codec.check_wire(ct)
    try:
        stacked = tuple(
            tuple(jnp.stack([ct.arrays[j][p] for ct in cts])
                  for p in range(len(first.arrays[j])))
            for j in range(n_leaves)
        )
    except (ValueError, TypeError) as e:
        raise ValueError(
            "compressed update block shapes differ across clients "
            f"({first.codec}): {e}") from None
    if mesh is not None and getattr(mesh, "size", 1) > 1:
        from fedml_tpu.parallel.multichip import shard_stacked

        stacked = shard_stacked(stacked, mesh)
    k = trim_k(len(cts), trim) if mode == "trimmed_mean" else 0
    flat = _robust_agg_program(codec, first.meta, mode, k, stacked)
    return jax.tree.map(lambda i: flat[i], first.structure)


def resolve_agg_robust(args: Any, codec: Any = None) -> Optional[str]:
    """The run's robust-aggregation spec, normalized — from an explicit
    ``agg_robust`` arg, else from an active fused-capable defense
    (``trimmed_mean`` / ``coordinate_wise_median``), else None.

    ONE definition for every caller (cross-silo server, sp simulation,
    tree runner), so the negotiation header, the fused reduction and
    ``requires_full_trees(codec)`` can never disagree about which
    statistic a run aggregates with. An EXPLICIT spec always resolves
    (its caller validates codec compatibility and refuses loudly at
    construction); a DEFENSE-derived spec resolves only when ``codec``
    is a dense plain codec — uncompressed and top-k runs keep the
    reference defense on the decode path, exactly as before.
    """
    parsed = parse_robust_spec(getattr(args, "agg_robust", ""))
    if parsed is not None:
        return robust_spec_str(*parsed)
    if (codec is None or not getattr(codec, "broadcast_safe", False)
            or getattr(codec, "maskable", False)):
        return None
    from fedml_tpu.core.security.defender import FedMLDefender

    defender = FedMLDefender.get_instance()
    if defender.is_fused_defense():
        return defender.fused_agg_spec()
    return None
