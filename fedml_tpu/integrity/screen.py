"""Ring 1 — admission screening in the compressed domain.

Every upload is reduced to two facts inside ONE jitted program
(``integrity/screen`` in the program catalog): *is every block and scale
finite*, and *what is the per-leaf squared norm* — for int8 read
straight off the blocks × scales (``scale² · Σq²``), for top-k off the
kept values, so no per-client f32 tree is ever materialized. The host
then applies three rules:

- **non-finite**: any NaN/Inf block, scale or leaf → dropped outright
  (a single NaN coordinate would otherwise poison the whole aggregate —
  NaN is absorbing under every weighted sum);
- **norm overflow**: the upload's total norm exceeds ``norm_mult ×`` the
  running median of previously *accepted* upload norms (the same
  cohort-median basis the PR 4 health tracker scores against) — the
  classic magnitude attack;
- **per-block robust z** (at round close, when the cohort is known):
  median/MAD z of each leaf's norm across this round's cohort; an
  upload whose worst block sits past ``z_threshold`` is an outlier even
  when its total norm hides inside the cohort envelope.

Flagged uploads are dropped-and-counted like PR 5 stale uploads; the
senders go to the :class:`~fedml_tpu.integrity.quarantine.QuarantineList`.
Screening does NOT run under masked secure aggregation — a masked
upload is exactly the thing the server must not be able to introspect
(``docs/privacy.md``); SecAgg's own bound clip is its admission control.
"""
from __future__ import annotations

import functools
import logging
import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression.codecs import (
    CompressedTree,
    _is_float_meta,
    _tree_meta,
    get_codec,
)

logger = logging.getLogger(__name__)

Pytree = Any

__all__ = ["ScreenStats", "UpdateScreen", "screen_stats"]


def _part_finite(x) -> jax.Array:
    """all-finite reduction of one array (ints are finite by dtype)."""
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return jnp.all(jnp.isfinite(x.astype(jnp.float32)))
    return jnp.asarray(True)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _screen_program(codec_name: Optional[str], meta, arrays):
    """(all_finite: bool, per-leaf sqnorm: f32[n_leaves]) in one program.

    ``arrays`` is the codec-positional tuple-of-tuples of a
    :class:`CompressedTree` (or ``((leaf,), ...)`` for a plain tree with
    ``codec_name=None``). The int8 branch never decodes: the leaf's
    squared norm is ``scale² · Σq²`` with the int8 blocks cast only as
    an XLA temporary inside the reduction.
    """
    finite = jnp.asarray(True)
    sqnorms: List[jax.Array] = []
    for parts, (dt, shape) in zip(arrays, meta):
        for p in parts:
            finite = finite & _part_finite(p)
        if not _is_float_meta(dt):
            sqnorms.append(jnp.sum(jnp.square(
                jnp.asarray(parts[0]).astype(jnp.float32))))
            continue
        if codec_name == "int8":
            q, scale = parts
            sqnorms.append(jnp.square(scale.astype(jnp.float32))
                           * jnp.sum(jnp.square(q.astype(jnp.float32))))
        elif codec_name == "topk":
            # kept values carry the whole mass; indices are positions
            sqnorms.append(jnp.sum(jnp.square(
                parts[0].astype(jnp.float32))))
        elif codec_name in (None, "identity", "bf16"):
            sqnorms.append(jnp.sum(jnp.square(
                parts[0].astype(jnp.float32))))
        elif codec_name in ("int4", "nf4"):
            # block-size independent: the nibble unpack + codebook
            # lookup are XLA temporaries, and padding decodes to exact
            # zero so it adds no mass — Σ_b scale_b² · Σ_k v_bk²
            packed, scale = parts
            c4 = get_codec(codec_name)
            vals = c4._lookup(c4._unpack(packed))
            sqnorms.append(jnp.sum(
                jnp.square(scale.astype(jnp.float32))
                * jnp.sum(jnp.square(vals), axis=-1)))
        else:
            # unknown third-party codec: decode THIS leaf in-program (an
            # XLA temporary, not a host tree) and norm the result
            leaf = get_codec(codec_name).decode_leaf(parts, dt, shape)
            sqnorms.append(jnp.sum(jnp.square(leaf.astype(jnp.float32))))
    return finite, jnp.stack(sqnorms)


from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_screen_program = _wrap_jit("integrity/screen", _screen_program,
                            static_argnums=(0, 1), multi_shape=True)


class ScreenStats:
    """One upload's screen facts (the single device→host readback)."""

    __slots__ = ("finite", "norm", "leaf_norms")

    def __init__(self, finite: bool, norm: float, leaf_norms: np.ndarray):
        self.finite = bool(finite)
        self.norm = float(norm)
        self.leaf_norms = np.asarray(leaf_norms, np.float64)


def screen_stats(payload: Any, base: Optional[Pytree] = None) -> ScreenStats:
    """Screen facts for one upload — compressed or plain.

    ``CompressedTree`` deltas are screened straight off their wire
    arrays (no decode); a plain pytree is screened as ``payload − base``
    (or raw without a base). One jitted program, one readback.
    """
    if isinstance(payload, CompressedTree):
        codec = get_codec(payload.codec)
        if getattr(codec, "maskable", False):
            raise ValueError(
                "masked (secure-aggregation) uploads cannot be screened — "
                "per-client introspection is what the masks exist to "
                "prevent")
        if not payload.is_delta and base is not None:
            # a compressed FULL model with a base: the norm that matters
            # is the displacement, which only exists decoded — this is
            # the rare non-delta-upload path, not the fused hot path
            # (check_wire inside decode guards the scales first)
            return screen_stats(codec.decode(payload), base=base)
        arrays = tuple(tuple(p) for p in payload.arrays)
        finite, sq = _screen_program(payload.codec, payload.meta, arrays)
    else:
        tree = payload
        if base is not None:
            from fedml_tpu.compression.codecs import tree_delta

            tree = tree_delta(payload, base)
        leaves = jax.tree.leaves(tree)
        meta = _tree_meta(leaves)
        finite, sq = _screen_program(
            None, meta, tuple((leaf,) for leaf in leaves))
    sq = np.asarray(sq, np.float64)
    # a non-finite block yields NaN/Inf sqnorms — norm stays honest
    total = float(np.sqrt(np.sum(sq))) if np.all(np.isfinite(sq)) else (
        float("nan"))
    return ScreenStats(bool(finite), total, np.sqrt(np.maximum(sq, 0.0)))


class UpdateScreen:
    """Per-round admission screen + cohort outlier close.

    Drive :meth:`admit` as uploads arrive (immediate verdicts:
    non-finite, norm overflow), then :meth:`close_round` once the
    cohort is assembled (per-block robust z needs the whole cohort).
    Thread-safe: cross-silo handlers run on the comm thread while the
    deadline close runs on the timer thread.
    """

    def __init__(self, norm_mult: float = 10.0, z_threshold: float = 8.0,
                 norm_history: int = 256, registry=None):
        from fedml_tpu.telemetry.registry import get_registry

        self.norm_mult = float(norm_mult)
        self.z_threshold = float(z_threshold)
        self._reg = registry or get_registry()
        self._lock = threading.Lock()
        # accepted-upload norms across rounds: the overflow baseline
        # (needs >= 4 accepted uploads before the rule can fire — a cold
        # start must not flag the first honest client it sees)
        self._norm_hist: deque = deque(maxlen=int(norm_history))
        # round -> client -> ScreenStats of ADMITTED uploads (the z close
        # and the rollback-suspect ranking read these)
        self._pending: Dict[int, Dict[Any, ScreenStats]] = {}
        self.last_round_stats: Dict[Any, ScreenStats] = {}

    def _flag(self, counter: str, client: Any, round_idx: int,
              reason: str) -> str:
        from fedml_tpu.telemetry import flight_recorder
        from fedml_tpu.telemetry.health import log_health_event

        self._reg.counter("integrity/screened_uploads").inc()
        self._reg.counter(counter).inc()
        rec = {"kind": "integrity_event", "event": "upload_screened",
               "client": client, "round": int(round_idx), "reason": reason}
        try:
            log_health_event(rec)
        except Exception:  # pragma: no cover - observability must not kill
            logger.exception("integrity event logging failed")
        flight_recorder.record("integrity_event", event="upload_screened",
                               client=client, round=int(round_idx),
                               reason=reason)
        return reason

    def admit(self, client: Any, round_idx: int, payload: Any,
              base: Optional[Pytree] = None) -> Optional[str]:
        """Screen one upload at arrival. Returns a reason string when the
        upload must be DROPPED (never aggregated), None when admitted."""
        try:
            stats = screen_stats(payload, base=base)
        except ValueError as e:
            if "non-finite" in str(e):
                # the decode-side wire guard tripped first (non-delta
                # payloads decode for their displacement norm): same
                # verdict as the in-program finite check
                return self._flag("integrity/nonfinite_uploads", client,
                                  round_idx, str(e))
            raise  # maskable refusal = caller misconfiguration
        except Exception:  # screening must never break the round
            logger.exception("upload screen failed for client %s "
                             "(admitting unscreened)", client)
            return None
        if not stats.finite or not math.isfinite(stats.norm):
            return self._flag("integrity/nonfinite_uploads", client,
                              round_idx, "non-finite blocks or scales")
        with self._lock:
            hist = list(self._norm_hist)
        if len(hist) >= 4:
            from fedml_tpu.telemetry.health import _median

            med = _median(hist)
            if med > 0 and stats.norm > self.norm_mult * med:
                return self._flag(
                    "integrity/norm_overflows", client, round_idx,
                    f"norm {stats.norm:.3g} > {self.norm_mult:g}x cohort "
                    f"median {med:.3g}")
        with self._lock:
            self._pending.setdefault(int(round_idx), {})[client] = stats
        return None

    def drop(self, client: Any, round_idx: int) -> None:
        """Forget an admitted upload (the caller dropped it for its own
        reasons — secagg validation, stale close)."""
        with self._lock:
            self._pending.get(int(round_idx), {}).pop(client, None)

    def _screen_z(self, values: Dict[Any, float]) -> Dict[Any, float]:
        """High-side robust z for SCREENING — stricter than the health
        tracker's :func:`~fedml_tpu.telemetry.health.robust_z`, because
        screening DROPS data where health only scores it.

        Two hardenings against small-cohort MAD instability (four
        near-identical honest norms make the MAD vanish, exploding any
        legitimate spread into z of 10+): the scale gets a relative
        floor of 20% of the median (norm variation inside the cohort's
        own envelope can never flag), and only the HIGH side counts with
        a 3× ratio condition (a block 2% above its siblings is noise; a
        poisoned block is a multiple of them — a *small* block is a weak
        update, not an attack).
        """
        if len(values) < 4:
            return {}
        from fedml_tpu.telemetry.health import _median

        vals = list(values.values())
        med = _median(vals)
        if med <= 0:
            # a frozen/near-frozen block: most of the cohort is exactly
            # zero, the relative floor vanishes, and any tiny nonzero
            # value would z past every threshold — there is no cohort
            # envelope to be an outlier OF (a poisoner hiding here still
            # trips the total-norm and nonzero-block rules)
            return {}
        mad = _median([abs(v - med) for v in vals])
        scale = max(1.4826 * mad, 0.2 * abs(med), 1e-12)
        return {k: (v - med) / scale for k, v in values.items()
                if v > 3.0 * med}

    def close_round(self, round_idx: int) -> Dict[Any, str]:
        """Per-block robust-z outlier pass over the round's admitted
        cohort; returns {client: reason} for uploads to drop. Accepted
        clients' norms enter the overflow baseline."""
        with self._lock:
            cohort = self._pending.pop(int(round_idx), {})
        flagged: Dict[Any, str] = {}
        if len(cohort) >= 4:
            n_leaves = min(len(s.leaf_norms) for s in cohort.values())
            worst: Dict[Any, Tuple[float, int]] = {
                c: (0.0, -1) for c in cohort}
            for j in range(n_leaves):
                zs = self._screen_z({c: float(s.leaf_norms[j])
                                     for c, s in cohort.items()})
                for c, z in zs.items():
                    if abs(z) > worst[c][0]:
                        worst[c] = (abs(z), j)
            for c, (z, j) in worst.items():
                if z >= self.z_threshold:
                    flagged[c] = self._flag(
                        "integrity/z_outliers", c, round_idx,
                        f"block {j} robust z {z:.1f} >= "
                        f"{self.z_threshold:g}")
        accepted = {c: s for c, s in cohort.items() if c not in flagged}
        with self._lock:
            for s in accepted.values():
                self._norm_hist.append(s.norm)
            self.last_round_stats = accepted
        return flagged

    def suspects(self) -> List[Any]:
        """The last accepted round's DISTINGUISHED suspects, ranked
        most-suspicious first: clients whose total update norm exceeds
        2× the round's cohort median (after ring 1's z pass, magnitude
        is the strongest signal a poisoned-but-admitted update leaves),
        falling back to the single largest update when nothing stands
        out. Deliberately a subset — a rollback must quarantine the
        likely poisoner, not the cohort that happened to be present."""
        from fedml_tpu.telemetry.health import _median

        with self._lock:
            stats = dict(self.last_round_stats)
        if not stats:
            return []
        norms = {c: s.norm for c, s in stats.items()}
        med = _median(list(norms.values()))
        out = [c for c, n in norms.items() if n > 2.0 * med]
        if not out:
            out = [max(norms, key=lambda c: norms[c])]
        return sorted(out, key=lambda c: -norms[c])
