"""Update-integrity containment — the layer that survives a *bad update*.

Every failure mode the resilience stack hardens (PR 5 quorum/dropout,
PR 9 SecAgg recovery, PR 12 journal, PR 13 preemption) is a *process*
failure; this package defends the MODEL against a corrupt or hostile
update — a client shipping NaN/Inf blocks, a diverging loss, or a
poisoned delta. Three concentric rings, all on the fused compressed
aggregation path (classic robust-aggregation results compose naturally
with the block-quantized wire: Krum, Blanchard et al. 2017;
coordinate-wise median / trimmed mean, Yin et al. 2018):

- :mod:`screen` — **ring 1, admission**: every upload is screened in
  the compressed domain inside one jitted program (non-finite blocks /
  scales, norm overflow vs the cohort median, per-block robust-z
  outliers read straight off int8 blocks × scales — no f32
  materialization). Flagged uploads are dropped-and-counted like PR 5
  stale uploads and their senders quarantined.
- :mod:`robust_agg` — **ring 2, aggregation**: coordinate-wise trimmed
  mean and median as dequant-fused alternatives to the weighted mean —
  one jitted reduction over the stacked blocks, so the reference's
  ``requires_full_trees()`` decode fallback is no longer the price of a
  robust aggregate.
- :mod:`rollback` — **ring 3, acceptance**: a post-aggregate guard
  (non-finite params, eval-loss spike vs EWMA history) that restores
  the last committed round state, quarantines the suspects, journals a
  ``round_rolled_back`` record and re-runs the round with a fresh
  cohort — bounded by ``max_rollbacks`` with a loud abort.

:mod:`quarantine` holds the :class:`QuarantineList` both outer rings
feed; it composes with the PR 5 evict/probe/rejoin machinery — a
quarantined client that rejoins stays excluded from selection until its
``quarantine_rounds`` elapse.

Everything lands in the ``integrity/*`` metric namespace (one segment,
counter/gauge only — lint-enforced) plus ``integrity_event`` records in
``health.jsonl`` and the flight recorder, which is what ``telemetry
doctor``'s "update integrity" section reads. See ``docs/integrity.md``.
"""
from __future__ import annotations

from typing import Any, Optional

from fedml_tpu.integrity.quarantine import QuarantineList
from fedml_tpu.integrity.robust_agg import (
    fused_robust_sum,
    parse_robust_spec,
    resolve_agg_robust,
)
from fedml_tpu.integrity.rollback import AcceptanceGuard, RollbackBudgetExceeded
from fedml_tpu.integrity.screen import UpdateScreen, screen_stats


class IntegrityConfig:
    """The integrity knobs, read once off the flat args namespace.

    ``integrity: true`` arms rings 1 and 3 together; each ring can be
    toggled individually (``integrity_screen`` / ``integrity_rollback``).
    Ring 2 is selected by ``agg_robust`` (or an active fused defense) —
    see :func:`resolve_agg_robust`. Defaults keep pre-subsystem behavior:
    everything off.
    """

    def __init__(self, args: Any = None):
        g = lambda k, d: getattr(args, k, d) if args is not None else d
        master = bool(g("integrity", False))
        self.screen_enabled = bool(g("integrity_screen", master))
        self.rollback_enabled = bool(g("integrity_rollback", master))
        # ring 1: an upload whose norm exceeds mult × the running cohort
        # median is an overflow; a per-block robust z past the threshold
        # is an outlier (8.0 is deliberately far past the health
        # tracker's 4.0 ANOMALY threshold — screening DROPS data, so it
        # must only fire on updates no honest client produces)
        self.norm_mult = float(g("integrity_norm_mult", 10.0))
        self.z_threshold = float(g("integrity_z_threshold", 8.0))
        # quarantine: rounds a flagged sender sits out of selection
        self.quarantine_rounds = int(g("quarantine_rounds", 2))
        # ring 3: eval-loss spike factor vs the accepted-rounds EWMA, the
        # history needed before the spike rule can fire, and the rollback
        # budget before the federation aborts loudly
        self.loss_mult = float(g("integrity_loss_mult", 2.0))
        self.loss_min_history = int(g("integrity_loss_min_history", 1))
        self.max_rollbacks = int(g("max_rollbacks", 2))

    @property
    def any_enabled(self) -> bool:
        return self.screen_enabled or self.rollback_enabled

    @classmethod
    def from_args(cls, args: Any) -> Optional["IntegrityConfig"]:
        cfg = cls(args)
        return cfg if cfg.any_enabled else None


__all__ = [
    "AcceptanceGuard",
    "IntegrityConfig",
    "QuarantineList",
    "RollbackBudgetExceeded",
    "UpdateScreen",
    "fused_robust_sum",
    "parse_robust_spec",
    "resolve_agg_robust",
    "screen_stats",
]
