"""Ring 3 — post-aggregate acceptance guard + round rollback bookkeeping.

The last line of defense: an update that slipped both outer rings (or a
poisoned *cohort* whose members look individually plausible) still has
to land a model the server will *accept*. After every aggregate the
guard checks two facts:

- **finiteness** of the new global params (one jitted all-isfinite
  reduction, one scalar readback — ``integrity/accept_check`` in the
  program catalog);
- **eval-loss spike**: the round's eval loss against an EWMA of the
  accepted-rounds history (``loss > loss_mult × ewma``, armed only once
  ``min_history`` rounds have been accepted so a cold start can't trip
  it).

A rejected round is the *caller's* to unwind — restore the last
committed round state (under durability that state IS the last PR 12
checkpoint: the journal forces a checkpoint at every commit), quarantine
the suspects, journal ``round_rolled_back``, re-run with a fresh cohort.
This class owns the decision and the budget: past ``max_rollbacks``
consecutive rollbacks it raises :class:`RollbackBudgetExceeded`, which
every engine turns into a loud federation abort — a persistently
poisoned federation must die visibly, not oscillate forever.
"""
from __future__ import annotations

import logging
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

Pytree = Any

__all__ = ["AcceptanceGuard", "RollbackBudgetExceeded", "params_finite"]


class RollbackBudgetExceeded(RuntimeError):
    """More consecutive rollbacks than ``max_rollbacks`` — the poisoning
    is persistent and containment has failed; abort loudly."""


@jax.jit
def _finite_program(leaves):
    finite = jnp.asarray(True)
    for x in leaves:
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            finite = finite & jnp.all(jnp.isfinite(x.astype(jnp.float32)))
    return finite


from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_finite_program = _wrap_jit("integrity/accept_check", _finite_program,
                            multi_shape=True)


def params_finite(params: Pytree) -> bool:
    """All float leaves finite — one program, one scalar readback."""
    return bool(_finite_program(tuple(jax.tree.leaves(params))))


class AcceptanceGuard:
    """Accept-or-rollback decision per aggregated round."""

    def __init__(self, loss_mult: float = 2.0, min_history: int = 1,
                 max_rollbacks: int = 2, ewma_alpha: float = 0.3):
        self.loss_mult = float(loss_mult)
        self.min_history = max(1, int(min_history))
        self.max_rollbacks = int(max_rollbacks)
        self.ewma_alpha = float(ewma_alpha)
        self._loss_ewma: Optional[float] = None
        self._accepted = 0
        # CONSECUTIVE rollbacks — an accepted round proves containment
        # worked and re-arms the budget
        self.rollbacks = 0
        self.total_rollbacks = 0

    def check(self, params: Optional[Pytree],
              eval_loss: Optional[float] = None) -> Optional[str]:
        """None = accept; else the rejection reason.

        ``params=None`` skips the finiteness reduction — for a second
        gate on params a FIRST gate already proved finite this round
        (the whole-model all-isfinite pass is not free on large models).
        """
        if params is not None and not params_finite(params):
            return "aggregated params contain non-finite values"
        if eval_loss is not None:
            try:
                loss = float(eval_loss)
            except (TypeError, ValueError):
                return None
            if not math.isfinite(loss):
                return f"eval loss is non-finite ({eval_loss})"
            if (self._accepted >= self.min_history
                    and self._loss_ewma is not None
                    and self._loss_ewma > 0
                    and loss > self.loss_mult * self._loss_ewma):
                return (f"eval loss {loss:.4g} spiked past "
                        f"{self.loss_mult:g}x the accepted-history EWMA "
                        f"{self._loss_ewma:.4g}")
        return None

    def accept(self, eval_loss: Optional[float] = None) -> None:
        """The round passed: fold its loss into the history, re-arm the
        consecutive-rollback budget."""
        self._accepted += 1
        self.rollbacks = 0
        if eval_loss is not None:
            try:
                loss = float(eval_loss)
            except (TypeError, ValueError):
                return
            if math.isfinite(loss):
                a = self.ewma_alpha
                self._loss_ewma = (loss if self._loss_ewma is None
                                   else a * loss + (1 - a) * self._loss_ewma)

    def record_rollback(self, round_idx: int, reason: str) -> None:
        """Book one rollback; raises past the consecutive budget."""
        from fedml_tpu.telemetry import flight_recorder
        from fedml_tpu.telemetry.health import log_health_event
        from fedml_tpu.telemetry.registry import get_registry

        self.rollbacks += 1
        self.total_rollbacks += 1
        get_registry().counter("integrity/rollbacks").inc()
        rec = {"kind": "integrity_event", "event": "round_rolled_back",
               "round": int(round_idx), "reason": str(reason),
               "consecutive": self.rollbacks}
        try:
            log_health_event(rec)
        except Exception:  # pragma: no cover - observability must not kill
            logger.exception("rollback event logging failed")
        flight_recorder.record("integrity_event", event="round_rolled_back",
                               round=int(round_idx), reason=str(reason),
                               consecutive=self.rollbacks)
        logger.error("round %d REJECTED (%s) — rolling back to the last "
                     "accepted state (rollback %d/%d)", round_idx, reason,
                     self.rollbacks, self.max_rollbacks)
        if self.rollbacks > self.max_rollbacks:
            get_registry().counter("integrity/rollback_aborts").inc()
            raise RollbackBudgetExceeded(
                f"round {round_idx} rolled back {self.rollbacks} "
                f"consecutive time(s) (> max_rollbacks="
                f"{self.max_rollbacks}): the corruption is persistent — "
                "aborting instead of oscillating")
