"""QuarantineList — time-boxed exclusion that composes with evict/rejoin.

Eviction (PR 5) answers "is this peer ALIVE?"; quarantine answers "is
this peer TRUSTED?". The two are orthogonal by design: a screened-out
client is usually evicted too (its upload went missing for the round),
then probed, then readmitted on its next sign of life — but readmission
only restores *liveness*. Selection asks the quarantine list as well,
so the client keeps sitting out until its ``quarantine_rounds`` elapse,
and its first post-quarantine selection goes through the normal rejoin
resync (fresh model, EF residual reset — exactly a rejoiner's state).
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["QuarantineList"]


class QuarantineList:
    """client → round the quarantine expires (exclusive).

    A client quarantined at round ``r`` for ``rounds`` sits out
    selections for rounds ``r+1 .. r+rounds`` and becomes selectable at
    ``r+rounds+1``. Re-quarantining extends, never shortens. Thread-safe
    (comm receive thread + deadline timer thread both flag senders).
    """

    def __init__(self, rounds: int = 2, registry=None):
        from fedml_tpu.telemetry.registry import get_registry

        self.rounds = int(rounds)
        self._reg = registry or get_registry()
        self._lock = threading.Lock()
        self._until: Dict[Any, int] = {}
        self._reason: Dict[Any, str] = {}

    def quarantine(self, client: Any, round_idx: int,
                   reason: str = "") -> bool:
        """Quarantine ``client`` as of ``round_idx``; False if an equal
        or longer quarantine was already in place."""
        from fedml_tpu.telemetry import flight_recorder
        from fedml_tpu.telemetry.health import log_health_event

        until = int(round_idx) + self.rounds
        with self._lock:
            if self._until.get(client, -1) >= until:
                return False
            self._until[client] = until
            self._reason[client] = str(reason)
            active = len(self._until)
        self._reg.counter("integrity/quarantined").inc()
        self._reg.gauge("integrity/quarantine_active").set(active)
        rec = {"kind": "integrity_event", "event": "quarantined",
               "client": client, "round": int(round_idx),
               "until_round": until, "reason": str(reason)}
        try:
            log_health_event(rec)
        except Exception:  # pragma: no cover - observability must not kill
            logger.exception("quarantine event logging failed")
        flight_recorder.record("integrity_event", event="quarantined",
                               client=client, round=int(round_idx),
                               until_round=until, reason=str(reason))
        logger.warning("client %s QUARANTINED until round %d: %s",
                       client, until, reason)
        return True

    def is_quarantined(self, client: Any, round_idx: int) -> bool:
        with self._lock:
            until = self._until.get(client)
        return until is not None and int(round_idx) <= until

    def active(self, round_idx: int) -> List[Any]:
        """Clients quarantined at ``round_idx`` (expired entries are
        dropped — release is implicit, no message round-trip)."""
        released = []
        with self._lock:
            for c in [c for c, u in self._until.items()
                      if u < int(round_idx)]:
                self._until.pop(c, None)
                self._reason.pop(c, None)
                released.append(c)
            out = sorted(self._until, key=str)
            active = len(self._until)
        if released:
            self._reg.counter("integrity/quarantine_released").inc(
                len(released))
            self._reg.gauge("integrity/quarantine_active").set(active)
            logger.info("quarantine released for %s at round %d",
                        released, round_idx)
        return out

    def reason(self, client: Any) -> Optional[str]:
        with self._lock:
            return self._reason.get(client)

    def filter_selection(self, candidates: List[Any],
                         round_idx: int) -> List[Any]:
        """Selection hook: candidates minus the active quarantine."""
        q = set(self.active(round_idx))
        if not q:
            return list(candidates)
        return [c for c in candidates if c not in q]
