"""YAML-driven configuration.

Same developer contract as the reference (``python/fedml/arguments.py:36-223``):
a single YAML file whose sections are flattened onto one ``args`` namespace,
plus CLI overrides ``--cf/--rank/--role/--run_id``. Downstream code reads
``args.<attr>`` duck-typed, so algorithms written against the reference's
config surface translate directly.
"""
from __future__ import annotations

import argparse
import os
from typing import Any, Optional

import yaml

from fedml_tpu import constants


class Arguments:
    """Flat attribute bag loaded from a YAML config.

    Sections (common_args/data_args/model_args/train_args/device_args/
    comm_args/tracking_args/...) are flattened: every key inside every
    section becomes a top-level attribute, exactly like the reference's
    ``Arguments.set_attr_from_config`` (``arguments.py:187``).
    """

    def __init__(
        self,
        cmd_args: Optional[argparse.Namespace] = None,
        training_type: Optional[str] = None,
        comm_backend: Optional[str] = None,
    ):
        if cmd_args is not None:
            for k, v in vars(cmd_args).items():
                setattr(self, k, v)
        if training_type is not None and not hasattr(self, "training_type"):
            self.training_type = training_type
        if comm_backend is not None and not hasattr(self, "backend"):
            self.backend = comm_backend
        config_file = getattr(self, "yaml_config_file", None) or getattr(
            self, "config_file", None
        )
        if config_file:
            self.load_yaml_config(config_file)

    # -- yaml ------------------------------------------------------------
    def load_yaml_config(self, path: str | os.PathLike) -> None:
        with open(path, "r") as f:
            cfg = yaml.safe_load(f) or {}
        self.set_attr_from_config(cfg)
        self.yaml_paths = [str(path)]

    def set_attr_from_config(self, configuration: dict) -> None:
        for section, payload in configuration.items():
            if isinstance(payload, dict):
                for k, v in payload.items():
                    setattr(self, k, v)
            else:
                setattr(self, section, payload)

    # -- dict-like conveniences ------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def __contains__(self, key: str) -> bool:
        return hasattr(self, key)

    def to_dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:  # pragma: no cover
        return f"Arguments({self.to_dict()!r})"


def add_args(parser: Optional[argparse.ArgumentParser] = None) -> argparse.Namespace:
    """CLI surface parity with the reference (``arguments.py:36-73``)."""
    parser = parser or argparse.ArgumentParser(description="fedml_tpu")
    parser.add_argument(
        "--yaml_config_file", "--cf", help="yaml configuration file", type=str, default=""
    )
    parser.add_argument("--run_id", type=str, default="0")
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--local_rank", type=int, default=0)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--role", type=str, default=constants.ROLE_CLIENT)
    args, _ = parser.parse_known_args()
    return args


def load_arguments(
    training_type: Optional[str] = None, comm_backend: Optional[str] = None
) -> Arguments:
    cmd_args = add_args()
    args = Arguments(cmd_args, training_type, comm_backend)
    _apply_defaults(args)
    return args


def load_arguments_from_dict(
    config: dict,
    training_type: Optional[str] = None,
) -> Arguments:
    """Programmatic entry: build args from an in-memory config dict."""
    args = Arguments(training_type=training_type)
    args.set_attr_from_config(config)
    _apply_defaults(args)
    return args


def update_client_specific_args(args: Arguments) -> None:
    """Per-silo yaml overrides (parity: reference ``arguments.py:171-183``
    hierarchical ``server_config_path``/``client_silo_config_paths`` and
    ``__init__.py:187-211`` ``data_silo_config``).

    ``data_silo_config`` lists one yaml per client silo; rank r > 0 loads
    entry r-1 on top of the global config — the cross-cloud story, where
    every silo brings its own transport/compute/data settings.
    Relative paths resolve against the main yaml's directory.
    """
    rank = int(getattr(args, "rank", 0))

    def _apply(path: str) -> None:
        if not os.path.isabs(path):
            base = os.path.dirname(
                (getattr(args, "yaml_paths", None) or [""])[0])
            path = os.path.join(base, path) if base else path
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        args.set_attr_from_config(cfg)

    silo_cfgs = getattr(args, "data_silo_config", None)
    if silo_cfgs:
        args.worker_num = len(silo_cfgs)
        if rank > 0:
            if rank > len(silo_cfgs):
                raise ValueError(
                    f"rank {rank} but data_silo_config lists only "
                    f"{len(silo_cfgs)} silos")
            _apply(str(silo_cfgs[rank - 1]))
    elif str(getattr(args, "scenario", "")) == "hierarchical":
        if rank == 0 and getattr(args, "server_config_path", None):
            _apply(str(args.server_config_path))
        elif rank > 0 and getattr(args, "client_silo_config_paths", None):
            paths = args.client_silo_config_paths
            if rank <= len(paths):
                _apply(str(paths[rank - 1]))


def load_arguments_from_yaml_path(
    path: str, training_type: Optional[str] = None
) -> Arguments:
    """Programmatic entry: build args straight from a yaml file (no CLI)."""
    args = Arguments(training_type=training_type)
    args.load_yaml_config(path)
    _apply_defaults(args)
    return args


_DEFAULTS = dict(
    training_type=constants.FEDML_TRAINING_PLATFORM_SIMULATION,
    backend=constants.FEDML_SIMULATION_TYPE_SP,
    federated_optimizer=constants.FEDML_FEDERATED_OPTIMIZER_FEDAVG,
    dataset="synthetic",
    data_cache_dir="",
    partition_method="hetero",
    partition_alpha=0.5,
    model="lr",
    client_num_in_total=4,
    client_num_per_round=2,
    comm_round=2,
    epochs=1,
    batch_size=32,
    client_optimizer="sgd",
    learning_rate=0.03,
    weight_decay=0.0,
    server_optimizer="sgd",
    server_lr=1.0,
    server_momentum=0.9,
    frequency_of_the_test=1,
    random_seed=0,
    rank=0,
    run_id="0",
    role=constants.ROLE_CLIENT,
    using_mlops=False,
    enable_wandb=False,
    dtype="float32",
    scenario=constants.CROSS_SILO_SCENARIO_HORIZONTAL,
    # compressed update transport (fedml_tpu/compression): '' disables;
    # identity | bf16 | int8 | topk select the wire codec for model
    # payloads (upload deltas + broadcast); topk keeps this fraction
    compression="",
    compression_topk_ratio=0.05,
)


def _apply_defaults(args: Arguments) -> None:
    for k, v in _DEFAULTS.items():
        if not hasattr(args, k):
            setattr(args, k, v)
