"""In-process aggregation-tree runner: 100k+ virtual clients, one machine.

The :class:`TreeRunner` drives a whole N-tier federation round-by-round
in one process: virtual leaf clients generate seeded deltas and upload
them compressed (the generate → EF → encode → fused-reduce pipeline runs
as one jitted program per fixed-size chunk), edge aggregators forward
partial sums in the compressed block domain, and the root closes the
global round. Chaos (kill windows at ANY tier), quorum closes, eviction
and rejoin are deterministic functions of the seed — two runs of the
same scenario end bit-identical.

Telemetry lands per tier under ``tier/<d>/...`` (upload bytes,
contributions, quorum closes, evict/rejoin counts, peak buffered bytes)
plus ``resilience_event`` records carrying a ``tier`` field, which is
what ``telemetry doctor``'s tier-triage section reads.
"""
from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression.codecs import (
    _is_float_meta,
    _tree_meta,
    derive_key,
    get_codec,
    tree_undelta,
)
from fedml_tpu.hierarchy.edge import EdgeAggregator, LeafCohort
from fedml_tpu.hierarchy.partial_sum import PartialSum, compressed_nbytes
from fedml_tpu.hierarchy.tree import TreeTopology
from fedml_tpu.resilience import quorum_size

logger = logging.getLogger(__name__)

Pytree = Any

__all__ = ["EdgeKillWindow", "KillWindow", "TreeRunner",
           "default_template", "last_dp_trace"]

# key-space offset for tier-aggregator encode keys, so edge re-encode
# streams can never collide with leaf-client upload streams
_EDGE_KEY_BASE = 0x40000000
# key id for the root's central-DP noise draw — its own stream, disjoint
# from client and edge encode keys
_DP_KEY_ID = 0x60000000

# proof probe for the central-DP seam (PR 9 pattern): the root mean must
# be a tracer when the noise lands — i.e. noise is added INSIDE the one
# jitted root-update program, never to a host-materialized pre-noise
# aggregate something could log or checkpoint
_DP_TRACE: Dict[str, Any] = {"pre_noise_traced": None,
                             "noised_in_program": None}


def last_dp_trace() -> Dict[str, Any]:
    """Snapshot of the central-DP in-program proof probe."""
    return dict(_DP_TRACE)


class KillWindow:
    """Chaos: node ``node`` at tier ``tier`` is dead for rounds
    ``[round, until)`` (default: one round). At the leaf tier ``node``
    is a global client index."""

    __slots__ = ("tier", "node", "round", "until")

    def __init__(self, tier: int, node: int, round: int,
                 until: Optional[int] = None):
        self.tier = int(tier)
        self.node = int(node)
        self.round = int(round)
        self.until = int(until) if until is not None else self.round + 1

    def dead_at(self, tier: int, round_idx: int) -> bool:
        return self.tier == tier and self.round <= round_idx < self.until


class EdgeKillWindow:
    """Chaos for the aggregator itself: CRASH the interior aggregator at
    ``(tier, node)`` during round ``round``, after it has accepted
    ``after_children`` offers — then restart it from its write-ahead
    journal (requires ``TreeRunner(durability_dir=...)``).

    Unlike :class:`KillWindow` (the node is *absent* for the window and
    its cohort quorum-closes around it), this models the preemption the
    durability layer exists for: the node comes straight back and must
    finish its round with every already-buffered partial sum intact —
    the run ends digest-identical to an unkilled one.
    """

    __slots__ = ("tier", "node", "round", "after_children")

    def __init__(self, tier: int, node: int, round: int,
                 after_children: int = 1):
        self.tier = int(tier)
        self.node = int(node)
        self.round = int(round)
        self.after_children = max(1, int(after_children))


def default_template(n_params: int = 1024) -> Dict[str, np.ndarray]:
    """A small two-leaf f32 model template with ~n_params elements."""
    d = max(2, int(round((int(n_params) * 3 // 4) ** 0.5)))
    k = max(1, (int(n_params) - d) // d)
    return {"w": np.zeros((d, k), np.float32),
            "b": np.zeros((k,), np.float32)}


def _make_delta_fn(meta) -> Callable:
    """Seeded virtual-client delta: per-leaf normal draws (traceable)."""

    def delta_fn(key):
        out = []
        for i, (dt, sh) in enumerate(meta):
            k = jax.random.fold_in(key, i)
            out.append(0.05 * jax.random.normal(k, sh, jnp.float32))
        return tuple(out)

    return delta_fn


class TreeRunner:
    """Run a hierarchical federation on a :class:`TreeTopology`.

    ``codec`` is the wire codec at EVERY tier (leaf uploads and partial
    sums); ``quorum`` the per-cohort close fraction; ``chaos`` a list of
    :class:`KillWindow`; ``ef=True`` keeps stacked per-client error
    feedback at the leaf tier (small-cohort mode). ``delta_fn`` may
    replace the virtual clients' update generator (a traceable
    ``key -> flat leaf tuple`` over the template's leaves).
    """

    def __init__(self, topology: TreeTopology, template: Optional[Pytree]
                 = None, codec: str = "int8", seed: int = 0,
                 quorum: float = 1.0, chunk: int = 2048, ef: bool = False,
                 chaos: Optional[Sequence[KillWindow]] = None,
                 delta_fn: Optional[Callable] = None,
                 server_lr: float = 1.0,
                 on_round: Optional[Callable[[int, Pytree], None]] = None,
                 live: Optional[Any] = None,
                 secagg: bool = False,
                 secagg_clip: float = 0.1,
                 secagg_mod_bits: int = 8,
                 dp_sigma: float = 0.0,
                 durability_dir: Optional[str] = None,
                 agg_robust: Optional[str] = None,
                 screen: bool = False):
        from fedml_tpu.resilience.chaos import CorruptUpdateWindow

        self.topology = topology
        self.codec = get_codec(codec)
        if self.codec is None:
            raise ValueError("TreeRunner needs a codec; use 'identity' for "
                             "an uncompressed wire")
        self.seed = int(seed)
        self.quorum = float(quorum)
        # update integrity: agg_robust closes EVERY tier's cohort with
        # the fused coordinate-wise robust statistic; screen=True arms
        # per-tier admission screening of the partial sums that travel
        # between tiers (corrupt uplinks are refused at the next tier up)
        self.agg_robust = None
        if agg_robust:
            from fedml_tpu.integrity import parse_robust_spec

            parse_robust_spec(agg_robust)  # validate, fail loudly
            if secagg:
                raise ValueError(
                    "agg_robust cannot run under per-cohort secagg — "
                    "per-coordinate sorting needs the per-client values "
                    "the masks hide")
            self.agg_robust = str(agg_robust)
        self._screens: Dict[int, Any] = {}
        if screen:
            if secagg:
                raise ValueError(
                    "per-tier screening cannot run under secagg (masked "
                    "partials are opaque by design)")
            from fedml_tpu.integrity import UpdateScreen

            # one screen per tier: the norm-overflow baseline must not
            # mix leaf-delta norms with interior cohort-mean norms
            self._screens = {
                d: UpdateScreen() for d in range(topology.n_tiers)}
        # EdgeKillWindows (crash-and-journal-restart) are a different
        # fault class than KillWindows (absent for the window);
        # CorruptUpdateWindows poison a node's UPLINK at the comm seam
        self.corrupts = [k for k in (chaos or [])
                         if isinstance(k, CorruptUpdateWindow)]
        self.edge_kills = [k for k in (chaos or [])
                           if isinstance(k, EdgeKillWindow)]
        self.chaos = [k for k in (chaos or [])
                      if not isinstance(k, (EdgeKillWindow,
                                            CorruptUpdateWindow))]
        self.durability_dir = durability_dir
        if self.edge_kills and not durability_dir:
            raise ValueError(
                "EdgeKillWindow chaos needs durability_dir — a crashed "
                "edge can only restart from its write-ahead journal")
        self.server_lr = float(server_lr)
        # central DP at the root: Gaussian noise with std ``dp_sigma``
        # on the global SUM (so ``dp_sigma / total_weight`` on the mean),
        # drawn from its own seeded stream INSIDE the jitted root-update
        # program — the pre-noise aggregate is never a host array
        self.dp_sigma = float(dp_sigma)
        self._dp_update_fn = None
        self.last_root_weight = 0.0
        template = default_template() if template is None else template
        leaves, self._treedef = jax.tree.flatten(template)
        self.global_leaves = [np.array(x) for x in leaves]
        self.meta = _tree_meta(leaves)
        if not all(_is_float_meta(dt) for dt, _ in self.meta):
            raise ValueError(
                "TreeRunner virtual cohorts support float-leaf templates "
                "only (int/bool leaves have no mean-delta semantics here)")
        self.delta_fn = delta_fn or _make_delta_fn(self.meta)
        # live serving plane: called with (round_idx, global_params) after
        # every root close — the serving publisher hooks here so the tree's
        # aggregate hot-swaps into a running endpoint. Guarded at call
        # time: a serving failure must not corrupt the federation.
        self.on_round = on_round
        # live telemetry plane (optional LivePlane): the tree root loops
        # its per-tier counters/health scores into the collector after
        # every global round, so the /metrics endpoint and the online
        # doctor track a 100k-client tree while it runs
        self.live = live
        self._f32_tree_nbytes = sum(
            int(np.prod(sh, dtype=np.int64)) * 4 for _, sh in self.meta)

        L = topology.leaf_tier
        # leaf cohorts (tier L), owned by the tier L-1 edges. Under
        # per-edge-cohort SecAgg the cohort masks inside itself and the
        # edge only ever sees (and re-encodes) the unmasked cohort SUM —
        # no tier holds an individual leaf delta.
        self.secagg = bool(secagg)
        self.cohorts: List[LeafCohort] = []
        for e in range(topology.levels[L - 1]):
            cids = topology.children(L - 1, e)
            if self.secagg:
                from fedml_tpu.privacy.secagg.hierarchy import (
                    SecAggLeafCohort,
                )

                if ef:
                    raise ValueError(
                        "secagg tree mode does not support per-client EF")
                self.cohorts.append(SecAggLeafCohort(
                    L, e, cids, self.codec, self.meta, self.delta_fn,
                    self.seed, chunk=chunk, clip=float(secagg_clip),
                    mod_bits=int(secagg_mod_bits)))
            else:
                self.cohorts.append(LeafCohort(
                    L, e, cids, self.codec, self.meta, self.delta_fn,
                    self.seed, chunk=chunk, ef=ef,
                    agg_robust=self.agg_robust))
        # interior aggregators for tiers 0..L-2 (children are tier d+1
        # node indices; the tier L-1 edges' children are their cohorts,
        # handled vectorized above)
        self.aggregators: Dict[int, List[EdgeAggregator]] = {}
        for d in range(0, L - 1):
            self.aggregators[d] = [
                EdgeAggregator(d, i, topology.children(d, i).tolist(),
                               self.codec, self.quorum,
                               agg_robust=self.agg_robust)
                for i in range(topology.levels[d])
            ]
        if self.durability_dir:
            # one journal per interior node, colocated like the server's:
            # buffered partial sums become durable at wire size
            from fedml_tpu.resilience.durability import RoundJournal

            for d, aggs in self.aggregators.items():
                for agg in aggs:
                    agg.bind_journal(RoundJournal(
                        f"{self.durability_dir}/edge_t{d}_n"
                        f"{agg.node_id}.journal"))
        # per-client wire bytes, computed once from an encoded template
        ct = self.codec.encode(
            jax.tree.unflatten(self._treedef,
                               [jnp.asarray(x) for x in leaves]),
            key=derive_key(self.seed, 0, 0), is_delta=True)
        self.per_client_wire_nbytes = compressed_nbytes(ct)
        # PR 4 health scoring, one tier up: each leaf-parent edge is a
        # "client" of the health tracker — per-round reduce walls feed
        # the straggler EWMA/median machinery, so a consistently slow
        # edge aggregator surfaces through `telemetry doctor` exactly
        # like a straggling cross-silo client
        from fedml_tpu.telemetry.health import ClientHealthTracker

        self._health = ClientHealthTracker()
        self.stats: Dict[str, Any] = {}

    # -- chaos + telemetry helpers ----------------------------------------
    def _dead(self, tier: int, round_idx: int) -> set:
        return {kw.node for kw in self.chaos if kw.dead_at(tier, round_idx)}

    def _event(self, event: str, tier: int, counter, n: int = 1,
               **fields) -> None:
        """One tier event, landed where the doctor looks: the tier/<d>/*
        counter (the caller registers it with a LITERAL signal segment,
        keeping the taxonomy lintable) plus a tier-tagged
        resilience_event in health.jsonl."""
        from fedml_tpu.telemetry.health import log_health_event

        counter.inc(n)
        try:
            log_health_event({"kind": "resilience_event", "event": event,
                              "tier": tier, **fields})
        except Exception:  # pragma: no cover - observability must not kill
            logger.exception("tier event logging failed")

    def _maybe_corrupt(self, tier: int, node: int, round_idx: int,
                       ps: PartialSum, reg) -> PartialSum:
        """CorruptUpdateWindow seam: poison node ``(tier, node)``'s
        UPLINK partial sum for the window — the tree's comm seam, where
        a hostile or sick aggregator would land its damage."""
        from fedml_tpu.resilience.chaos import corrupt_model_payload

        for w in self.corrupts:
            if (w.tier == tier and w.rank == node
                    and w.round <= round_idx < w.until):
                reg.counter("resilience/chaos_injections",
                            labels={"action": "corrupt_update"}).inc()
                ps = PartialSum(
                    corrupt_model_payload(ps.ct, w.mode, w.factor),
                    ps.weight, ps.count)
        return ps

    def _screen_partials(self, tier: int, round_idx: int,
                         partials: Dict[int, PartialSum],
                         reg) -> Dict[int, PartialSum]:
        """Per-tier admission screen (integrity ring 1): a corrupt
        partial sum is refused at the tier ABOVE its producer — the
        producer counts as missing for the round, so the quorum/evict
        machinery reweights its whole subtree out."""
        screen = self._screens.get(tier)
        if screen is None:
            return partials
        admitted: Dict[int, PartialSum] = {}
        for node, ps in sorted(partials.items()):
            reason = screen.admit(node, round_idx, ps.ct)
            if reason is not None:
                self._event("upload_screened", tier,
                            reg.counter(f"tier/{tier}/screened"), 1,
                            round=round_idx, node=node, reason=reason)
                continue
            admitted[node] = ps
        for node, reason in screen.close_round(round_idx).items():
            if admitted.pop(node, None) is not None:
                self._event("upload_screened", tier,
                            reg.counter(f"tier/{tier}/screened"), 1,
                            round=round_idx, node=node, reason=reason)
        return admitted

    def _restart_edge(self, round_idx: int, tier: int, node: int,
                      dead: EdgeAggregator, reg) -> EdgeAggregator:
        """EdgeKillWindow seam: the interior aggregator 'process' dies
        mid-round and a fresh one restarts from its journal — every
        buffered partial sum must survive the hop (the digest-identity
        test is the proof). Models per-tier preemption recovery."""
        fresh = EdgeAggregator(tier, node, list(dead.child_ids),
                               self.codec, self.quorum)
        fresh.bind_journal(dead._journal)
        salvaged = fresh.restore_from_journal()
        self.aggregators[tier][node] = fresh
        reg.counter("resilience/restarts").inc()
        reg.counter("resilience/journal_replays").inc()
        reg.counter("resilience/journal_salvaged").inc(salvaged)
        self._event("edge_restarted", tier,
                    reg.counter(f"tier/{tier}/restarts"), 1,
                    round=round_idx, node=node, salvaged=salvaged)
        logger.warning(
            "chaos: tier %d node %d killed and journal-restarted at "
            "round %d with %d salvaged partial sum(s)", tier, node,
            round_idx, salvaged)
        return fresh

    # -- the round ---------------------------------------------------------
    def _leaf_round(self, round_idx: int, reg) -> Dict[int, PartialSum]:
        """Reduce every leaf cohort; returns tier-(L-1) node partials."""
        topo = self.topology
        L = topo.leaf_tier
        dead_clients = self._dead(L, round_idx)
        partials: Dict[int, PartialSum] = {}
        upload_bytes = 0
        peak_chunk_bytes = 0
        for e, cohort in enumerate(self.cohorts):
            lo = int(cohort.client_ids[0]) if len(cohort.client_ids) else 0
            # probe/rejoin BEFORE selection: an evicted client alive again
            # this round answers the probe, readmits (EF residual reset at
            # this edge) and re-enters the cohort
            if cohort.evicted_mask.any():
                ev_local = np.nonzero(cohort.evicted_mask)[0]
                alive_again = np.asarray(
                    [i for i in ev_local
                     if (lo + int(i)) not in dead_clients], np.int64)
                back = cohort.readmit(alive_again)
                if len(back):
                    self._event("rejoined", L,
                                reg.counter(f"tier/{L}/rejoined"),
                                len(back),
                                round=round_idx,
                                clients=[int(c) for c in back[:16]])
            alive = np.ones(len(cohort.client_ids), bool)
            for c in dead_clients:
                if 0 <= c - lo < len(alive):
                    alive[c - lo] = False
            expected = cohort.n_expected()
            t_reduce = time.perf_counter()
            sum_leaves, total_w, n_recv = cohort.reduce(round_idx, alive)
            # PR 4 health scoring per edge: the reduce wall is the edge's
            # round latency; a persistently slow edge scores as a
            # straggler in doctor triage
            self._health.observe(int(e), round_idx,
                                 latency_s=time.perf_counter() - t_reduce)
            dead_local = np.nonzero(~alive & ~cohort.evicted_mask)[0]
            if len(dead_local):
                gone = cohort.evict(dead_local)
                self._event("evicted", L,
                            reg.counter(f"tier/{L}/evicted"), len(gone),
                            round=round_idx,
                            clients=[int(c) for c in gone[:16]])
            if n_recv < quorum_size(max(1, expected), self.quorum) or (
                    sum_leaves is None):
                self._event("quorum_failed", L - 1,
                            reg.counter(f"tier/{L - 1}/quorum_failures"), 1,
                            round=round_idx, node=e, received=n_recv,
                            expected=expected)
                continue
            if n_recv < expected:
                self._event("quorum_close", L - 1,
                            reg.counter(f"tier/{L - 1}/quorum_closes"), 1,
                            round=round_idx, node=e, received=n_recv,
                            expected=expected)
            if getattr(cohort, "returns_mean", False):
                # robust cohorts reduce straight to the coordinate-wise
                # statistic — already the mean, no division
                mean = jax.tree.unflatten(
                    self._treedef, [jnp.asarray(s) for s in sum_leaves])
            else:
                mean = jax.tree.unflatten(
                    self._treedef,
                    [s / jnp.float32(total_w) for s in sum_leaves])
            key = derive_key(self.seed, round_idx,
                             _EDGE_KEY_BASE + ((L - 1) << 20) + e)
            ct = self.codec.encode(mean, key=key, is_delta=True)
            partials[e] = self._maybe_corrupt(
                L - 1, e, round_idx, PartialSum(ct, total_w, n_recv), reg)
            upload_bytes += n_recv * self.per_client_wire_nbytes
            peak_chunk_bytes = max(
                peak_chunk_bytes,
                min(len(cohort.client_ids), cohort.chunk)
                * self.per_client_wire_nbytes)
        reg.counter(f"tier/{L}/upload_bytes").inc(upload_bytes)
        reg.counter(f"tier/{L}/contributions").inc(
            sum(p.count for p in partials.values()))
        self._tier_round_bytes[L] = upload_bytes
        # leaf-tier buffering is the in-flight chunk of compressed blocks
        self._tier_peak_buffer[L] = max(
            self._tier_peak_buffer.get(L, 0), peak_chunk_bytes)
        return partials

    def _interior_round(self, round_idx: int, tier: int,
                        child_partials: Dict[int, PartialSum],
                        reg) -> Dict[int, PartialSum]:
        """One interior tier: children's partials → this tier's partials."""
        dead_here = self._dead(tier + 1, round_idx)  # children that died
        # ring 1 at this tier's ingress: corrupt child uplinks are
        # refused before any aggregator buffers them — the child is
        # simply missing this round (quorum close handles the rest)
        child_partials = self._screen_partials(
            tier + 1, round_idx, child_partials, reg)
        out: Dict[int, PartialSum] = {}
        upload_bytes = 0
        for node, agg in enumerate(self.aggregators[tier]):
            # probe/rejoin before the round opens (same rule as leaves)
            for c in agg.evicted():
                if c not in dead_here and c in child_partials:
                    if agg.readmit(c):
                        self._event(
                            "rejoined", tier + 1,
                            reg.counter(f"tier/{tier + 1}/rejoined"), 1,
                                    round=round_idx, node=c)
            expected = agg.begin_round(round_idx)
            kill = next(
                (k for k in self.edge_kills
                 if k.tier == tier and k.node == node
                 and k.round == round_idx), None)
            accepted = 0
            for c in expected:
                ps = child_partials.get(c)
                if ps is not None and c not in dead_here:
                    if agg.offer(c, ps):
                        accepted += 1
                    upload_bytes += ps.nbytes
                    if kill is not None and accepted == kill.after_children:
                        agg = self._restart_edge(round_idx, tier, node,
                                                 agg, reg)
                        kill = None
            received = agg.received()
            key = derive_key(self.seed, round_idx,
                             _EDGE_KEY_BASE + (tier << 20) + node)
            if tier == 0:
                mean, total_w, missing = agg.close_round_root()
                if missing:
                    self._event("evicted", 1,
                                reg.counter("tier/1/evicted"), len(missing),
                                round=round_idx, nodes=missing)
                if mean is None:
                    raise RuntimeError(
                        f"global round {round_idx} below quorum at the "
                        f"root: {received}/{len(expected)} tier-1 partial "
                        f"sums (need {quorum_size(max(1, len(expected)), self.quorum)})")
                if received < len(expected):
                    self._event("quorum_close", 0,
                                reg.counter("tier/0/quorum_closes"), 1,
                                round=round_idx, received=received,
                                expected=len(expected))
                self._root_close = (mean, total_w)
            else:
                ps, missing = agg.close_round(key)
                if missing:
                    self._event("evicted", tier + 1,
                                reg.counter(f"tier/{tier + 1}/evicted"),
                                len(missing), round=round_idx,
                                nodes=missing)
                if ps is None:
                    self._event("quorum_failed", tier,
                                reg.counter(f"tier/{tier}/quorum_failures"),
                                1,
                                round=round_idx, node=node,
                                received=received, expected=len(expected))
                    continue
                if received < len(expected):
                    self._event("quorum_close", tier,
                                reg.counter(f"tier/{tier}/quorum_closes"),
                                1,
                                round=round_idx, node=node,
                                received=received, expected=len(expected))
                out[node] = self._maybe_corrupt(tier, node, round_idx,
                                                ps, reg)
            self._tier_peak_buffer[tier] = max(
                self._tier_peak_buffer.get(tier, 0),
                agg.peak_buffered_nbytes)
        reg.counter(f"tier/{tier + 1}/upload_bytes").inc(upload_bytes)
        self._tier_round_bytes[tier + 1] = max(
            self._tier_round_bytes.get(tier + 1, 0), upload_bytes)
        return out

    def run(self, rounds: int) -> Dict[str, Any]:
        """Run ``rounds`` global rounds; returns the scenario result."""
        from fedml_tpu import telemetry

        reg = telemetry.get_registry()
        topo = self.topology
        L = topo.leaf_tier
        for d in range(L + 1):
            reg.gauge(f"tier/{d}/nodes").set(topo.levels[d])
        self._tier_peak_buffer: Dict[int, int] = {}
        peak_round_bytes: Dict[int, int] = {}
        from fedml_tpu.telemetry.profiling import get_trace_controller

        t0 = time.perf_counter()
        try:
            self._run_rounds(rounds, reg, L, peak_round_bytes)
        finally:
            # a quorum abort mid-round must not leave a trace recording
            get_trace_controller().finish()
        wall = time.perf_counter() - t0
        for d, v in self._tier_peak_buffer.items():
            reg.gauge(f"tier/{d}/peak_buffer_bytes").set(v)

        digest = hashlib.blake2b(digest_size=16)
        for x in self.global_leaves:
            digest.update(np.ascontiguousarray(x).tobytes())
        per_tier = {}
        for d in range(L + 1):
            per_tier[str(d)] = {
                "nodes": topo.levels[d],
                "peak_round_upload_bytes": peak_round_bytes.get(d, 0),
                "peak_buffer_bytes": self._tier_peak_buffer.get(d, 0),
            }
        self.stats = {
            "clients": topo.n_clients,
            "tiers": topo.n_tiers,
            "levels": list(topo.levels),
            "rounds": int(rounds),
            "codec": self.codec.spec,
            "agg_robust": self.agg_robust,
            "secagg": self.secagg,
            "dp_sigma": self.dp_sigma,
            "root_total_weight": self.last_root_weight,
            "seed": self.seed,
            "quorum": self.quorum,
            "wall_s": wall,
            "rounds_per_s": (rounds / wall) if wall > 0 else 0.0,
            "per_client_wire_bytes": self.per_client_wire_nbytes,
            "f32_tree_nbytes": self._f32_tree_nbytes,
            "per_tier": per_tier,
            "final_digest": digest.hexdigest(),
            "completed": True,
        }
        return self.stats

    def _run_rounds(self, rounds: int, reg, L: int,
                    peak_round_bytes: Dict[int, int]) -> None:
        from fedml_tpu.telemetry.profiling import get_trace_controller

        topo = self.topology
        for r in range(int(rounds)):
            # deep-trace seam: --trace-rounds or a doctor-requested
            # capture brackets exactly one tree round
            get_trace_controller().on_round_start(r)
            self._tier_round_bytes: Dict[int, int] = {}
            self._root_close = None
            partials = self._leaf_round(r, reg)
            if L == 1:
                # 2-tier degenerate tree: the root IS the single leaf
                # cohort's edge — decode its partial directly (screened
                # first: the root is this partial's consuming tier)
                partials = self._screen_partials(0, r, partials, reg)
                if 0 not in partials:
                    raise RuntimeError(
                        f"global round {r} below quorum at the root "
                        "(leaf cohort did not reach quorum)")
                self._root_close = (self.codec.decode(partials[0].ct),
                                    partials[0].weight)
            for tier in range(L - 2, -1, -1):
                partials = self._interior_round(r, tier, partials, reg)
            if self._root_close is None:  # pragma: no cover - defensive
                raise RuntimeError(f"round {r} never reached the root")
            self._health.finish_round(r)  # edge straggler/EWMA scoring
            mean, total_w = self._root_close
            self.last_root_weight = float(total_w)
            if self.dp_sigma > 0.0:
                self.global_leaves = [
                    np.array(x)
                    for x in self._dp_root_update(r, mean, total_w)]
            else:
                new_global = tree_undelta(
                    jax.tree.unflatten(self._treedef, [
                        jnp.asarray(x) for x in self.global_leaves]),
                    jax.tree.map(
                        lambda m: jnp.float32(self.server_lr) * m, mean))
                self.global_leaves = [
                    np.array(x) for x in jax.tree.leaves(new_global)]
            if self.on_round is not None:
                try:
                    self.on_round(r, self.global_params)
                except Exception:  # serving must never corrupt training
                    logger.exception("round listener failed at round %d", r)
            if self.live is not None:
                try:
                    self.live.pump(round_idx=r)
                except Exception:  # observability must never corrupt it
                    logger.exception("live telemetry pump failed at "
                                     "round %d", r)
            for d, b in self._tier_round_bytes.items():
                peak_round_bytes[d] = max(peak_round_bytes.get(d, 0), b)
            get_trace_controller().on_round_end(r)

    def _dp_root_update(self, round_idx: int, mean: Pytree, total_w):
        """Noise + apply the root mean in ONE jitted program.

        The central-DP contract: the only post-aggregation value that
        ever lands on the host is the *noised* global — the probe
        records that the pre-noise mean was still a tracer when the
        Gaussian draw was added (see :func:`last_dp_trace`)."""
        sigma = jnp.float32(self.dp_sigma)
        lr = jnp.float32(self.server_lr)
        if self._dp_update_fn is None:

            def upd(glob, means, w, key):
                out = []
                for i, (g, m) in enumerate(zip(glob, means)):
                    _DP_TRACE["pre_noise_traced"] = isinstance(
                        m, jax.core.Tracer)
                    noise = sigma * jax.random.normal(
                        jax.random.fold_in(key, i), m.shape, jnp.float32)
                    out.append(g + lr * (m + noise / w))
                _DP_TRACE["noised_in_program"] = bool(
                    _DP_TRACE["pre_noise_traced"])
                return tuple(out)

            self._dp_update_fn = jax.jit(upd)
        key = derive_key(self.seed, round_idx, _DP_KEY_ID)
        return self._dp_update_fn(
            tuple(jnp.asarray(x) for x in self.global_leaves),
            tuple(jnp.asarray(x) for x in jax.tree.leaves(mean)),
            jnp.float32(total_w), key)

    @property
    def global_params(self) -> Pytree:
        return jax.tree.unflatten(self._treedef, list(self.global_leaves))
