"""Partial sums in the compressed block domain — the tree's wire unit.

Hierarchical aggregation (HierFAVG, Liu et al. 2020) only scales if the
intermediate tiers stay cheap: an edge aggregator that decodes its
cohort into N f32 trees has already paid the memory bill the tree was
built to avoid. The unit that travels UP the tree is therefore a
:class:`PartialSum` — a :class:`CompressedTree` (int8 blocks + scales,
bf16 halves, …) holding the cohort's *weighted mean*, plus the
**accumulated sample weight** of everything underneath it. Any tier can
combine partial sums from its children with the PR 3 dequant-fused
weighted sum (``fused_weighted_sum``: the blocks reduce inside ONE
jitted program) and re-encode the result for its own uplink — the only
f32 tree a tier ever materializes is its single cohort aggregate.

Carrying (mean, weight) instead of raw sums keeps the arithmetic
associative by construction::

    combine(combine(a, b), c) == combine(a, combine(b, c))
      where combine(x, y).mean = (Wx·x.mean + Wy·y.mean) / (Wx + Wy)
            combine(x, y).weight = Wx + Wy

so a 2-tier tree, a 3-tier tree and flat aggregation compute the same
weighted mean (bit-identically for the identity codec on exactly
representable data; within per-tier re-quantization error for int8).
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.compression.codecs import (
    Codec,
    CompressedTree,
    fused_weighted_sum,
)

Pytree = Any

__all__ = [
    "PartialSum",
    "compressed_nbytes",
    "finalize_root",
    "reduce_cohort",
]


class PartialSum:
    """A cohort's aggregate, ready for the uplink.

    ``ct``      the cohort weighted mean, encoded by the tier codec
    ``weight``  accumulated sample weight under this subtree
    ``count``   leaf contributions folded in (diagnostics only)
    """

    __slots__ = ("ct", "weight", "count")

    def __init__(self, ct: CompressedTree, weight: float, count: int):
        self.ct = ct
        self.weight = float(weight)
        self.count = int(count)

    @property
    def nbytes(self) -> int:
        return compressed_nbytes(self.ct)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"PartialSum(codec={self.ct.codec}, weight={self.weight:g}, "
                f"count={self.count})")


def compressed_nbytes(ct: CompressedTree) -> int:
    """Wire bytes of a compressed tree's blocks (q/scales/values/indices).

    Counts the encoded arrays only — the structure/meta envelope is a few
    hundred bytes of JSON and identical at every tier.
    """
    total = 0
    for parts in ct.arrays:
        for a in parts:
            dt = getattr(a, "dtype", None)
            sh = getattr(a, "shape", ())
            if dt is None:
                total += np.asarray(a).nbytes
            else:
                itemsize = 2 if str(dt) == "bfloat16" else np.dtype(
                    str(dt)).itemsize
                total += int(np.prod(sh, dtype=np.int64)) * itemsize
    return total


def _weighted_mean(contribs: Sequence[Tuple[CompressedTree, float]]) -> Tuple[
        Pytree, float]:
    """Dequant-fused weighted mean over (ct, weight) contributions.

    ONE jitted program; per-contributor f32 trees are never materialized
    (the blocks reduce inside the einsum/scatter of the codec's fused
    ``weighted_sum_leaf``). Contribution counts travel separately (the
    ``counts`` argument of :func:`reduce_cohort`), never through here.
    """
    if not contribs:
        raise ValueError("empty cohort: nothing to reduce")
    cts = [ct for ct, _ in contribs]
    weights = np.asarray([w for _, w in contribs], np.float64)
    total = float(weights.sum())
    if total <= 0:
        raise ValueError(f"cohort weights must sum > 0, got {total}")
    mean = fused_weighted_sum(cts, (weights / total).astype(np.float32))
    return mean, total


def _robust_mean(contribs: Sequence[Tuple[CompressedTree, float]],
                 agg_robust: str) -> Tuple[Pytree, float]:
    """Coordinate-wise robust statistic over the cohort's contributions.

    Per-tier Byzantine robustness: at an interior tier the contributions
    are the children's cohort MEANS, so a poisoned subtree's mean is an
    outlier among its siblings and the trimmed mean / median discards
    it — same fused contract as :func:`_weighted_mean` (one jitted
    program, no per-contributor f32 trees), but deliberately unweighted:
    a subtree claiming a huge accumulated weight is exactly the lever
    robustness removes. The accumulated weight still flows up for
    bookkeeping and leaf-count diagnostics.
    """
    from fedml_tpu.integrity import fused_robust_sum, parse_robust_spec

    if not contribs:
        raise ValueError("empty cohort: nothing to reduce")
    mode, trim = parse_robust_spec(agg_robust)
    total = float(np.sum([w for _, w in contribs], dtype=np.float64))
    if total <= 0:
        raise ValueError(f"cohort weights must sum > 0, got {total}")
    return fused_robust_sum([ct for ct, _ in contribs], mode, trim), total


def reduce_cohort(contribs: Sequence[Tuple[CompressedTree, float]],
                  out_codec: Codec, key,
                  counts: Optional[Sequence[int]] = None,
                  agg_robust: Optional[str] = None) -> PartialSum:
    """Reduce one cohort's compressed contributions into a PartialSum.

    ``contribs`` are ``(CompressedTree, weight)`` pairs — leaf-client
    deltas at the bottom tier, child PartialSum.ct's anywhere above. The
    dequant-fused weighted mean (or, with ``agg_robust``, the fused
    coordinate-wise robust statistic) and the re-encode each run as one
    jitted program; nothing per-contributor ever exists in f32. This is
    the "dequant-sort-trim-requant" tier step: the robust mean re-encodes
    for the uplink exactly like the weighted mean does.
    """
    if agg_robust:
        mean, total = _robust_mean(contribs, agg_robust)
    else:
        mean, total = _weighted_mean(contribs)
    is_delta = contribs[0][0].is_delta
    ct = out_codec.encode(mean, key=key, is_delta=is_delta)
    count = int(sum(counts)) if counts is not None else len(contribs)
    return PartialSum(ct, total, count)


def finalize_root(contribs: Sequence[Tuple[CompressedTree, float]],
                  agg_robust: Optional[str] = None) -> Tuple[
        Pytree, float]:
    """Close the global round: fused weighted mean (or robust statistic)
    of the top-tier partial sums, decoded exactly once — the only full
    f32 tree of the round."""
    if agg_robust:
        return _robust_mean(contribs, agg_robust)
    mean, total = _weighted_mean(contribs)
    return mean, total


def flat_reference(contribs: Sequence[Tuple[CompressedTree, float]]) -> Pytree:
    """Flat (tree-less) aggregation of the same contributions — the
    baseline the associativity acceptance test compares against."""
    mean, _ = _weighted_mean(contribs)
    return mean
