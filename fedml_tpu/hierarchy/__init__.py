"""Hierarchical federation: aggregation trees + buffered-async (FedBuff).

The planet-scale half of the cross-device story. Two compositions over
the PR 3 compressed transport and the PR 5 resilience machinery:

- **Aggregation trees** — leaf clients upload compressed deltas to edge
  aggregators; every tier reduces its cohort with the dequant-fused
  weighted sum and forwards a :class:`~fedml_tpu.hierarchy.partial_sum.
  PartialSum` (re-encoded blocks + accumulated weight) upward — no tier
  ever materializes a per-contributor f32 tree. Each cohort closes on
  all-received or quorum, evicts the missing and readmits rejoiners
  (EF residual reset at the edge). :class:`TreeRunner` simulates a
  100k+-client N-tier federation in one process, with chaos kill
  windows at any tier and per-tier ``tier/<d>/...`` telemetry.

- **FedBuff** (:mod:`fedml_tpu.hierarchy.fedbuff`) — bounded buffer of
  K delta contributions, staleness-weighted ``1/sqrt(1+τ)``, applied in
  one fused program when the buffer fills; the async cross-silo server
  (``cross_silo/server/async_server_manager.py``) rides it for
  compressed-delta uploads.

CLI: ``fedml_tpu tree`` runs a seeded scenario and prints one JSON line;
``python bench.py --tree`` measures the 100k-client claim. See
``docs/hierarchy.md``.
"""
from fedml_tpu.hierarchy.edge import EdgeAggregator, LeafCohort
from fedml_tpu.hierarchy.fedbuff import FedBuffBuffer, staleness_weight
from fedml_tpu.hierarchy.partial_sum import (
    PartialSum,
    compressed_nbytes,
    finalize_root,
    flat_reference,
    reduce_cohort,
)
from fedml_tpu.hierarchy.runner import (
    EdgeKillWindow,
    KillWindow,
    TreeRunner,
    default_template,
)
from fedml_tpu.hierarchy.tree import TreeTopology

__all__ = [
    "EdgeAggregator",
    "EdgeKillWindow",
    "FedBuffBuffer",
    "KillWindow",
    "LeafCohort",
    "PartialSum",
    "TreeRunner",
    "TreeTopology",
    "compressed_nbytes",
    "default_template",
    "finalize_root",
    "flat_reference",
    "reduce_cohort",
    "staleness_weight",
]
