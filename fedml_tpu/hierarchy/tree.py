"""Tree topology: N tiers, contiguous balanced cohorts, zero per-client
Python objects.

A topology is just the node count per tier — ``levels[0] == 1`` (the
root), ``levels[-1] == n_clients`` (the virtual leaves) — plus arithmetic
for the balanced contiguous child ranges. Cohort membership is computed,
never stored, so a million-leaf tree costs a tuple of ints.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

__all__ = ["TreeTopology"]


class TreeTopology:
    """``levels[d]`` = number of nodes at tier ``d`` (0 = root)."""

    def __init__(self, levels: Tuple[int, ...]):
        levels = tuple(int(x) for x in levels)
        if len(levels) < 2:
            raise ValueError("a tree needs at least root + leaf tiers")
        if levels[0] != 1:
            raise ValueError(f"tier 0 is the root (1 node), got {levels[0]}")
        for d in range(1, len(levels)):
            if levels[d] < levels[d - 1]:
                raise ValueError(
                    f"tier {d} ({levels[d]} nodes) narrower than its "
                    f"parent tier ({levels[d - 1]})")
        self.levels = levels

    @classmethod
    def build(cls, n_clients: int, tiers: int = 3) -> "TreeTopology":
        """Balanced geometric tree: tier d gets ~n^(d/(tiers-1)) nodes —
        for 100k clients and 3 tiers, ~316 edges of ~316 clients."""
        n = int(n_clients)
        t = int(tiers)
        if n < 1:
            raise ValueError(f"n_clients must be >= 1, got {n}")
        if t < 2:
            raise ValueError(f"tiers must be >= 2 (root + leaves), got {t}")
        levels: List[int] = [1]
        for d in range(1, t - 1):
            levels.append(max(levels[-1],
                              int(round(n ** (d / (t - 1))))))
        levels.append(n)
        return cls(tuple(levels))

    @property
    def n_tiers(self) -> int:
        return len(self.levels)

    @property
    def n_clients(self) -> int:
        return self.levels[-1]

    @property
    def leaf_tier(self) -> int:
        return len(self.levels) - 1

    def children(self, tier: int, node: int) -> np.ndarray:
        """Child node indices (at ``tier + 1``) of ``node`` at ``tier`` —
        the balanced contiguous range [node·m//k, (node+1)·m//k)."""
        if not 0 <= tier < self.leaf_tier:
            raise ValueError(f"tier {tier} has no children")
        k = self.levels[tier]
        m = self.levels[tier + 1]
        lo = node * m // k
        hi = (node + 1) * m // k
        return np.arange(lo, hi, dtype=np.int64)

    def parent(self, tier: int, node: int) -> int:
        """Parent node index (at ``tier - 1``) of ``node`` at ``tier``."""
        if tier <= 0:
            raise ValueError("the root has no parent")
        k = self.levels[tier - 1]
        m = self.levels[tier]
        # inverse of the contiguous split: the p with lo(p) <= node < hi(p)
        return int((int(node) * k + k - 1) // m) if m else 0

    def describe(self) -> dict:
        return {
            "tiers": self.n_tiers,
            "levels": list(self.levels),
            "clients": self.n_clients,
            "fanout": [
                round(self.levels[d + 1] / self.levels[d], 1)
                for d in range(self.n_tiers - 1)
            ],
        }

    def __repr__(self) -> str:  # pragma: no cover
        return f"TreeTopology(levels={self.levels})"
