"""Buffered asynchronous aggregation (FedBuff, Nguyen et al. 2022).

The synchronous tree closes rounds; FedBuff closes *buffers*: the server
collects K delta contributions — each tagged with the model version it
was trained against — and applies them in one fused step,

    x ← x + η · Σᵢ wᵢ·Δᵢ / Σᵢ wᵢ,     wᵢ = nᵢ · s(τᵢ),  s(τ) = (1+τ)^(-a)

where τᵢ is the contribution's staleness (server versions advanced since
its base) and ``a = 0.5`` gives the paper's ``1/sqrt(1+τ)`` discount.
At τ = 0 the weight reduces to the plain sample count, so a buffer of
fresh contributions is EXACTLY a synchronous FedAvg step.

Determinism: the flush sorts contributions by ``(base_version, sender,
seq)`` before the fused reduction, so arrival-order races (the async
server's whole point) cannot change the aggregate bit-wise — the same
set of contributions flushes to the same result regardless of the order
the transport delivered them.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression import CompressedTree, get_codec
from fedml_tpu.compression.codecs import fused_weighted_sum, tree_delta

Pytree = Any

__all__ = ["FedBuffBuffer", "staleness_weight"]


def staleness_weight(tau: float, exponent: float = 0.5) -> float:
    """Polynomial staleness discount ``(1+τ)^(-exponent)``.

    ``staleness_weight(0) == 1.0`` (a fresh contribution carries full
    synchronous-FedAvg weight) and the discount decays monotonically.
    """
    return float((1.0 + max(0.0, float(tau))) ** (-float(exponent)))


class _Entry:
    __slots__ = ("sender", "base_version", "n_samples", "payload", "seq")

    def __init__(self, sender, base_version, n_samples, payload, seq):
        self.sender = int(sender)
        self.base_version = int(base_version)
        self.n_samples = float(n_samples)
        self.payload = payload
        self.seq = int(seq)


class FedBuffBuffer:
    """Bounded buffer of (possibly compressed) delta contributions.

    ``add`` accepts either a delta-encoded :class:`CompressedTree` (the
    compressed transport's native upload) or a plain full model tree
    (compression off) — plain models are converted to deltas against the
    CURRENT global at flush, which makes the τ=0 full-buffer flush equal
    a synchronous FedAvg round in both modes.
    """

    def __init__(self, capacity: int, staleness_exponent: float = 0.5):
        self.capacity = max(1, int(capacity))
        self.staleness_exponent = float(staleness_exponent)
        self._entries: List[_Entry] = []
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def full(self) -> bool:
        return len(self._entries) >= self.capacity

    def add(self, sender: int, base_version: int, n_samples: float,
            payload: Any) -> None:
        if self.full:
            raise RuntimeError(
                f"FedBuff buffer overflow (capacity {self.capacity}); "
                "flush before adding")
        if isinstance(payload, CompressedTree) and not payload.is_delta:
            raise ValueError(
                "FedBuff buffers delta contributions; got a compressed "
                "FULL model (decode it first or enable delta uploads)")
        self._entries.append(_Entry(sender, base_version, n_samples,
                                    payload, next(self._seq)))

    def flush(self, current_version: int,
              global_params: Pytree) -> Tuple[Pytree, Dict]:
        """Apply the buffer: returns ``(new_global, stats)``.

        Homogeneous compressed entries reduce through the dequant-fused
        weighted sum (ONE jitted program over the stacked blocks);
        plain-tree entries deltify against ``global_params`` and reduce
        in the same canonical order. Mixed buffers decode the compressed
        minority (K-bounded) rather than failing the round.
        """
        if not self._entries:
            raise RuntimeError("flush of an empty FedBuff buffer")
        # canonical order: arrival order must never change the aggregate
        entries = sorted(self._entries,
                         key=lambda e: (e.base_version, e.sender, e.seq))
        self._entries = []
        stale = [max(0, int(current_version) - e.base_version)
                 for e in entries]
        weights = np.asarray(
            [e.n_samples * staleness_weight(t, self.staleness_exponent)
             for e, t in zip(entries, stale)], np.float64)
        total = float(weights.sum())
        if total <= 0:
            weights = np.ones(len(entries), np.float64)
            total = float(len(entries))
        w = (weights / total).astype(np.float32)

        payloads = [e.payload for e in entries]
        compressed = [isinstance(p, CompressedTree) for p in payloads]
        if all(compressed) and len({p.codec for p in payloads}) == 1:
            mean_delta = fused_weighted_sum(payloads, w)
        else:
            # mixed or plain: K-bounded decode, same canonical order
            deltas = [
                get_codec(p.codec).decode(p) if isinstance(
                    p, CompressedTree)
                else tree_delta(p, global_params)
                for p in payloads
            ]
            mean_delta = deltas[0]
            mean_delta = jax.tree.map(
                lambda d: w[0] * d.astype(jnp.float32), mean_delta)
            for wi, d in zip(w[1:], deltas[1:]):
                mean_delta = jax.tree.map(
                    lambda acc, x: acc + wi * x.astype(jnp.float32),
                    mean_delta, d)
            mean_delta = jax.tree.map(
                lambda acc, g: acc.astype(jnp.asarray(g).dtype),
                mean_delta, global_params)
        from fedml_tpu.compression.codecs import tree_undelta

        new_global = tree_undelta(global_params, mean_delta)
        stats = {
            "flushed": len(entries),
            "staleness": stale,
            "mean_staleness": float(sum(stale)) / len(stale),
            "senders": [e.sender for e in entries],
            "weights": [float(x) for x in w],
        }
        return new_global, stats
