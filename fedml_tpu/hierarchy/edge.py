"""Tier nodes: edge aggregators + vectorized virtual leaf cohorts.

Two kinds of node live in an aggregation tree:

- :class:`EdgeAggregator` — an interior node. Buffers its children's
  :class:`~fedml_tpu.hierarchy.partial_sum.PartialSum` uploads for the
  round (compressed domain only — buffering N children costs N sets of
  int8 blocks, never N f32 trees), closes on all-received or on quorum
  (PR 5's ``quorum_size`` + ``RoundDeadline``), evicts children that
  missed the close and readmits them on their next sign of life.

- :class:`LeafCohort` — the bottom tier of the in-process simulator: one
  edge's virtual leaf clients, reduced in fixed-size padded chunks where
  generate → error-feedback → encode → dequant-fused weighted sum run as
  ONE jitted program per chunk. Per-client f32 deltas exist only as XLA
  intermediates inside that program; the host holds at most the optional
  stacked EF residuals (the clients' own state, small-test mode only)
  and the running f32 cohort sum. Dead clients are masked to weight 0 in
  the same program, so a chaos kill changes inputs, not program shapes —
  recompiles can't leak into the round.
"""
from __future__ import annotations

import functools
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression.codecs import (
    Codec,
    _is_float_meta,
    _raw_weighted_sum,
    derive_key_data_batch,
)
from fedml_tpu.hierarchy.partial_sum import PartialSum, reduce_cohort
from fedml_tpu.resilience import RoundDeadline, quorum_size

logger = logging.getLogger(__name__)

Pytree = Any
DeltaFn = Callable[[Any], Tuple[jax.Array, ...]]

__all__ = ["EdgeAggregator", "LeafCohort"]


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


class EdgeAggregator:
    """One interior tree node: per-round buffer + quorum close + dropout.

    The buffer holds (child_id → PartialSum) for the current round only;
    ``buffered_nbytes`` is what the peak-memory gauge reads — compressed
    blocks, by construction.
    """

    def __init__(self, tier: int, node_id: int, child_ids: Sequence[int],
                 codec: Codec, quorum_frac: float = 1.0,
                 agg_robust: Optional[str] = None):
        self.tier = int(tier)
        self.node_id = int(node_id)
        self.child_ids = [int(c) for c in child_ids]
        self.codec = codec
        self.quorum_frac = float(quorum_frac)
        # Byzantine-robust tier reduction (integrity ring 2): close the
        # cohort with the fused coordinate-wise trimmed mean / median of
        # the children's partial sums instead of their weighted mean
        self.agg_robust = str(agg_robust) if agg_robust else None
        self._evicted: set = set()
        self._buffer: Dict[int, PartialSum] = {}
        self._round: Optional[int] = None
        self._deadline = RoundDeadline(self._on_deadline)
        self._on_expire: Optional[Callable[[int], None]] = None
        self._buffered_nbytes = 0  # running sum: offer is O(1), not O(C)
        self.peak_buffered_nbytes = 0
        self._journal = None  # crash durability, opt-in via bind_journal

    # -- crash durability --------------------------------------------------
    def bind_journal(self, journal) -> None:
        """Opt this edge into the write-ahead journal: round opens and
        accepted offers (the compressed partial sums — wire-sized, never
        f32 trees) become durable, so a killed edge re-enters its open
        round with the buffer intact (:meth:`restore_from_journal`)."""
        self._journal = journal

    def restore_from_journal(self) -> int:
        """Rehydrate the open (un-closed) journaled round; returns the
        number of salvaged child partial sums (0 = nothing open)."""
        if self._journal is None:
            return 0
        from fedml_tpu.resilience.durability.journal import scan_open_round

        # the shared replay state machine; an edge's terminal record is
        # its round_closed (the uplink partial is the parent's problem)
        open_rec, uploads, _ = scan_open_round(
            self._journal.records(), terminal_kinds=("round_closed",),
            note_kinds=())
        if open_rec is None:
            return 0
        offers: Dict[int, PartialSum] = {
            int(rec["child"]): PartialSum(rec["ct"], float(rec["weight"]),
                                          int(rec["count"]))
            for rec in uploads}
        self._round = int(open_rec["round"])
        # pre-crash evictions are implied by the journaled expectation
        expected = {int(c) for c in open_rec.get("expected") or []}
        self._evicted = {c for c in self.child_ids if c not in expected}
        self._buffer = {}
        self._buffered_nbytes = 0
        for child, ps in offers.items():
            self._buffer[child] = ps
            self._buffered_nbytes += ps.nbytes
        self.peak_buffered_nbytes = max(self.peak_buffered_nbytes,
                                        self._buffered_nbytes)
        return len(offers)

    # -- round lifecycle ---------------------------------------------------
    def begin_round(self, round_idx: int) -> List[int]:
        """Open the round; returns the expected (non-evicted) children."""
        self._round = int(round_idx)
        self._buffer = {}
        self._buffered_nbytes = 0
        expected = self.expected()
        if self._journal is not None:
            self._journal.append("round_open", round=self._round,
                                 expected=[int(c) for c in expected])
        return expected

    def expected(self) -> List[int]:
        return [c for c in self.child_ids if c not in self._evicted]

    def arm_deadline(self, timeout_s: float,
                     on_expire: Callable[[int], None]) -> None:
        """Arm this cohort's round deadline (PR 5 timer; the callback
        runs on the timer thread with the armed round)."""
        self._on_expire = on_expire
        self._deadline.arm(int(self._round or 0), timeout_s)

    def _on_deadline(self, round_idx: int) -> None:
        if self._on_expire is not None:
            self._on_expire(round_idx)

    def offer(self, child_id: int, ps: PartialSum) -> bool:
        """A child's upload for the open round. Returns False (stale) for
        unknown children or closed rounds; an upload from an evicted
        child is its sign of life — the caller readmits it for the NEXT
        round, this round's quorum already reweighted it out."""
        child_id = int(child_id)
        if self._round is None or child_id not in self.child_ids:
            return False
        if child_id in self._evicted or child_id in self._buffer:
            return False
        if self._journal is not None:
            # durable BEFORE buffered, same contract as the server's
            # upload journal — a crash after this line salvages the offer
            self._journal.append("upload_received", round=self._round,
                                 child=child_id, ct=ps.ct,
                                 weight=float(ps.weight),
                                 count=int(ps.count))
        self._buffer[child_id] = ps
        self._buffered_nbytes += ps.nbytes
        self.peak_buffered_nbytes = max(self.peak_buffered_nbytes,
                                        self._buffered_nbytes)
        return True

    @property
    def buffered_nbytes(self) -> int:
        return self._buffered_nbytes

    def received(self) -> int:
        return len(self._buffer)

    def quorum_met(self) -> bool:
        return self.received() >= quorum_size(
            max(1, len(self.expected())), self.quorum_frac)

    def all_received(self) -> bool:
        return self.received() >= len(self.expected())

    def _close_common(self):
        """Shared close tail: cancel the deadline, evict the missing,
        return (ordered contribs or None-when-below-quorum, missing).

        The quorum is judged against the PRE-eviction expectation: the
        children that just went missing are exactly the ones the quorum
        exists to count, so evicting them first would let any single
        survivor "meet quorum" over a cohort of one.
        """
        self._deadline.cancel()
        if self._journal is not None:
            # the close is the edge's commit point: the uplink partial is
            # the parent's (journaled) problem from here on
            self._journal.append("round_closed", durable=False,
                                 round=int(self._round or 0))
            self._journal.reset()
        expected = self.expected()
        missing = [c for c in expected if c not in self._buffer]
        need = quorum_size(max(1, len(expected)), self.quorum_frac)
        for c in missing:
            self._evicted.add(c)
        if not self._buffer or self.received() < need:
            logger.warning(
                "tier %d node %d below quorum: %d/%d children reported",
                self.tier, self.node_id, self.received(), len(expected))
            self._round = None
            return None, missing
        order = sorted(self._buffer)  # canonical order: child id
        contribs = [(self._buffer[c].ct, self._buffer[c].weight)
                    for c in order]
        counts = [self._buffer[c].count for c in order]
        self._round = None
        return (contribs, counts), missing

    def close_round(self, key) -> Tuple[Optional[PartialSum], List[int]]:
        """Close the round: reduce the received children (quorum
        permitting) into a re-encoded PartialSum for the uplink, and
        evict the missing. ``partial`` is None when the cohort fell
        below quorum (the parent then treats THIS node as missing)."""
        closed, missing = self._close_common()
        if closed is None:
            return None, missing
        contribs, counts = closed
        return reduce_cohort(contribs, self.codec, key, counts=counts,
                             agg_robust=self.agg_robust), missing

    def close_round_root(self) -> Tuple[Optional[Pytree], float, List[int]]:
        """Root variant: decode the global mean instead of re-encoding —
        the round's single full f32 tree. Returns (mean, weight, missing).
        """
        from fedml_tpu.hierarchy.partial_sum import finalize_root

        closed, missing = self._close_common()
        if closed is None:
            return None, 0.0, missing
        contribs, _ = closed
        mean, total = finalize_root(contribs, agg_robust=self.agg_robust)
        return mean, total, missing

    def readmit(self, child_id: int) -> bool:
        """Rejoin path: any sign of life from an evicted child readmits
        it for the next round."""
        if int(child_id) not in self._evicted:
            return False
        self._evicted.discard(int(child_id))
        return True

    def evicted(self) -> List[int]:
        return sorted(self._evicted)


# -- leaf tier: fused chunked reduction ------------------------------------
@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _leaf_chunk_program(codec: Codec, meta, delta_fn: DeltaFn, ef: bool,
                        agg: str, trim: float,
                        key_data, weights, residuals):
    """generate → (EF) → encode → dequant-fused reduction, one program.

    ``key_data`` [C, …] per-client PRNG key data, ``weights`` [C] f32
    (0 for dead/padded slots), ``residuals`` tuple of [C, …] stacked EF
    leaves (empty tuple when ``ef`` is False). With ``agg='mean'``
    returns the cohort's *unnormalized* weighted-sum leaves; with a
    robust mode (``'trimmed_mean'``/``'median'`` — integrity ring 2)
    the coordinate-wise robust statistic over the live (weight > 0)
    clients, which is already the cohort MEAN — dead/padded slots are
    masked rows in the sort, not shape changes. Per-client f32 deltas
    and decoded blocks are XLA temporaries only, either way.
    """

    def per_client(kd, res):
        key = jax.random.wrap_key_data(kd)
        leaves = tuple(delta_fn(jax.random.fold_in(key, 1)))
        if ef:
            leaves = tuple(x + r for x, r in zip(leaves, res))
        enc_key = jax.random.fold_in(key, 2)
        enc = codec._encode_leaves(leaves, meta, enc_key)
        if not ef:
            return tuple(tuple(p) for p in enc), ()
        dec = codec._decode_leaves(enc, meta)
        new_res = tuple(
            (c - d.astype(c.dtype)) if _is_float_meta(dt)
            else jnp.zeros_like(c)
            for c, d, (dt, _) in zip(leaves, dec, meta))
        return tuple(tuple(p) for p in enc), new_res

    if ef:
        enc_stacked, new_res = jax.vmap(per_client)(key_data, residuals)
    else:
        enc_stacked, new_res = jax.vmap(
            lambda kd: per_client(kd, ()))(key_data)
    w = weights.astype(jnp.float32)
    if agg == "mean":
        summed = tuple(
            codec.weighted_sum_leaf(parts, w, dt, sh)
            if _is_float_meta(dt) else _raw_weighted_sum(parts[0], w)
            for parts, (dt, sh) in zip(enc_stacked, meta))
        return summed, new_res
    from fedml_tpu.integrity.robust_agg import masked_robust_leaf

    valid = w > 0
    out = []
    for parts, (dt, sh) in zip(enc_stacked, meta):
        if _is_float_meta(dt):
            dec = jax.vmap(
                lambda *p, dt=dt, sh=sh: codec.decode_leaf(p, dt, sh)
            )(*parts).astype(jnp.float32)
        else:
            dec = parts[0].astype(jnp.float32)
        out.append(masked_robust_leaf(dec, valid, agg, trim).astype(
            jnp.float32))
    return tuple(out), new_res


# cataloged: the hierarchy tier's hot program — one variant per
# power-of-2 chunk bucket is the design, not treedef churn
from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_leaf_chunk_program = _wrap_jit(
    "hierarchy/leaf_chunk", _leaf_chunk_program,
    static_argnums=(0, 1, 2, 3, 4, 5), multi_shape=True)


class LeafCohort:
    """One edge's virtual leaf clients, reduced in fixed-size chunks.

    ``client_ids`` are the global client ids owned by this edge;
    ``weights`` their sample weights (default 1.0 — virtual cohorts).
    ``ef=True`` keeps stacked per-client error-feedback residuals (the
    clients' own state, held AT the edge tier in this simulator) —
    memory is O(cohort × tree f32), so it is the small-test mode; the
    planet-scale mode runs EF-less.
    """

    def __init__(self, tier: int, edge_id: int, client_ids: np.ndarray,
                 codec: Codec, meta, delta_fn: DeltaFn, seed: int,
                 chunk: int = 2048, ef: bool = False,
                 weights: Optional[np.ndarray] = None,
                 agg_robust: Optional[str] = None):
        self.tier = int(tier)
        self.edge_id = int(edge_id)
        self.client_ids = np.asarray(client_ids, np.int64)
        self.codec = codec
        self.meta = meta
        self.delta_fn = delta_fn
        self.seed = int(seed)
        n = len(self.client_ids)
        # Byzantine-robust cohort reduction (integrity ring 2): the
        # chunk program computes the coordinate-wise robust statistic
        # instead of the weighted sum. A robust statistic is NOT
        # chunk-decomposable (the per-coordinate sort needs every
        # client), so the cohort is forced into ONE chunk — robust leaf
        # cohorts are a bounded-cohort mode, same as ef=True.
        self._robust = None
        if agg_robust:
            from fedml_tpu.integrity import parse_robust_spec

            self._robust = parse_robust_spec(agg_robust)
        self.returns_mean = self._robust is not None
        if self._robust is not None:
            chunk = _next_pow2(n)
        # bucket the chunk to the cohort: padding a 316-client cohort to
        # a 4096-slot program is 13x wasted compute; the power-of-2
        # bucket keeps near-identical cohort sizes (316 vs 317) on ONE
        # compiled program while never padding more than 2x
        self.chunk = max(1, min(int(chunk), _next_pow2(n)))
        self.ef = bool(ef)
        self.weights = (np.ones(n, np.float32) if weights is None
                        else np.asarray(weights, np.float32))
        self.evicted_mask = np.zeros(n, bool)
        self._residuals = None
        if self.ef:
            # float leaves carry f32 residuals (simulator templates are
            # f32); raw-passthrough int/bool leaves carry typed zeros so
            # the in-program `delta + residual` never promotes them
            self._residuals = [
                np.zeros((n,) + tuple(sh),
                         np.float32 if _is_float_meta(dt) else np.dtype(dt))
                for dt, sh in meta
            ]

    def n_expected(self) -> int:
        return int((~self.evicted_mask).sum())

    def evicted_ids(self) -> np.ndarray:
        return self.client_ids[self.evicted_mask]

    def evict(self, dead_local: np.ndarray) -> np.ndarray:
        """Mark locally-indexed clients evicted; returns their global ids."""
        fresh = dead_local[~self.evicted_mask[dead_local]]
        self.evicted_mask[fresh] = True
        return self.client_ids[fresh]

    def readmit(self, local_idx: np.ndarray) -> np.ndarray:
        """Rejoin: readmit clients and RESET their EF residual rows — a
        rejoiner's pre-drop quantization error must not leak into its
        post-rejoin uploads (same rule as the cross-silo rejoin sync)."""
        back = local_idx[self.evicted_mask[local_idx]]
        self.evicted_mask[back] = False
        if self._residuals is not None and len(back):
            for r in self._residuals:
                r[back] = 0.0
        return self.client_ids[back]

    def residual_rows(self, local_idx: int) -> List[np.ndarray]:
        if self._residuals is None:
            return []
        return [np.asarray(r[local_idx]) for r in self._residuals]

    def reduce(self, round_idx: int, alive_local: np.ndarray) -> Tuple[
            Optional[List[jax.Array]], float, int]:
        """Reduce the round's surviving cohort to unnormalized sum leaves.

        ``alive_local`` is the boolean per-client liveness mask for this
        round (chaos); evicted clients are excluded regardless. Returns
        ``(sum_leaves, total_weight, n_received)`` — sum_leaves is None
        when nobody reported. With ``agg_robust`` (``returns_mean``) the
        leaves are already the cohort's robust MEAN (single-chunk by
        construction) and the caller must not divide by the weight.
        """
        live = np.asarray(alive_local, bool) & ~self.evicted_mask
        n = len(self.client_ids)
        w_round = np.where(live, self.weights, 0.0).astype(np.float32)
        n_received = int(live.sum())
        if n_received == 0:
            return None, 0.0, 0
        sum_leaves = None
        for start in range(0, n, self.chunk):
            idx = np.arange(start, min(start + self.chunk, n))
            pad = self.chunk - len(idx)
            cids = np.concatenate([self.client_ids[idx],
                                   np.zeros(pad, np.int64)])
            w = np.concatenate([w_round[idx],
                                np.zeros(pad, np.float32)])
            kd = derive_key_data_batch(self.seed, round_idx, cids)
            if self.ef:
                res = tuple(
                    jnp.concatenate([
                        jnp.asarray(r[idx]),
                        jnp.zeros((pad,) + r.shape[1:], r.dtype)])
                    for r in self._residuals)
            else:
                res = ()
            agg, trim = (("mean", 0.0) if self._robust is None
                         else self._robust)
            summed, new_res = _leaf_chunk_program(
                self.codec, self.meta, self.delta_fn, self.ef,
                agg, trim, jnp.asarray(kd), jnp.asarray(w), res)
            if self.ef:
                # only clients that actually trained advance their
                # residual; dead/evicted ones keep their state
                trained = live[idx]
                for r, nr in zip(self._residuals, new_res):
                    nr = np.asarray(nr)[:len(idx)]
                    r[idx[trained]] = nr[trained]
            sum_leaves = (list(summed) if sum_leaves is None else
                          [a + b for a, b in zip(sum_leaves, summed)])
        return sum_leaves, float(w_round.sum()), n_received
