"""Cross-device ("BeeHive") server.

Parity: ``cross_device/mnn_server.py:6`` + ``server_mnn/fedml_aggregator.py``
in the reference, where a Python server aggregates models trained by
C++/MNN mobile clients over MQTT+S3.

TPU-era re-design: the server IS the cross-silo server FSM — the message
protocol (handshake → init → per-round sync/upload → finish) is identical;
what differs on-device is the client runtime, not the server. Mobile/edge
clients speak the same typed-message wire format (pickle-free, see
``utils/serialization.py``) over a broker transport, and upload plain
pytree deltas instead of ``.mnn`` files. The device-side runtime
(FedMLBaseTrainer engine seam, JAX engine, plain + SecAgg managers) is
:mod:`fedml_tpu.cross_device.client`.
"""
from __future__ import annotations

from typing import Any

from fedml_tpu.cross_silo.server.server import Server


class ServerCrossDevice(Server):
    """Cross-device aggregation server (cross-silo FSM, device clients)."""

    def __init__(self, args: Any, device: Any, dataset: Any, model: Any,
                 server_aggregator=None):
        # device clients are never co-scheduled as mesh slices: force the
        # federation transport (broker/grpc/local), never 'sp'/'mesh'
        super().__init__(args, device, dataset, model, server_aggregator)
