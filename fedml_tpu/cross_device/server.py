"""Cross-device ("BeeHive") server.

Parity: ``cross_device/mnn_server.py:6`` + ``server_mnn/fedml_aggregator.py``
in the reference, where a Python server aggregates models trained by
C++/MNN mobile clients over MQTT+S3.

TPU-era re-design: the server IS the cross-silo server FSM — the message
protocol (handshake → init → per-round sync/upload → finish) is identical;
what differs on-device is the client runtime, not the server. Mobile/edge
clients speak the same typed-message wire format (pickle-free, see
``utils/serialization.py``) over a broker transport, and upload plain
pytree deltas instead of ``.mnn`` files. The device-side runtime
(FedMLBaseTrainer engine seam, JAX engine, plain + SecAgg managers) is
:mod:`fedml_tpu.cross_device.client`.

Cohorts beyond what one flat FSM can carry route through the
hierarchical federation subsystem (:mod:`fedml_tpu.hierarchy`):
``hierarchy_tiers >= 2`` in the config selects :func:`run_hierarchical`,
which simulates the whole aggregation tree (compressed partial sums,
per-tier quorum/evict/rejoin, chaos) in-process. Wire-level tree
deployment — real edge-aggregator processes between the phones and the
root — is the part that does not exist yet, and the flat server refuses
hierarchy configs loudly instead of silently running flat.
"""
from __future__ import annotations

from typing import Any

from fedml_tpu.cross_silo.server.server import Server


def run_hierarchical(args: Any) -> dict:
    """Run a cross-device cohort as an in-process aggregation tree.

    Reads the flat args: ``client_num_in_total`` (virtual cohort size),
    ``hierarchy_tiers`` (tree depth, default 3), ``compression`` (wire
    codec at every tier, default int8), ``round_quorum`` (per-cohort
    close fraction), ``comm_round`` (global rounds),
    ``hierarchy_params`` (virtual model size), ``hierarchy_ef``
    (stacked per-client error feedback — small cohorts only). Returns
    the :class:`~fedml_tpu.hierarchy.TreeRunner` scenario stats.
    """
    from fedml_tpu import telemetry
    from fedml_tpu.hierarchy import TreeRunner, TreeTopology, default_template

    telemetry.configure_from_args(args)
    topo = TreeTopology.build(
        int(getattr(args, "client_num_in_total", 1000)),
        tiers=int(getattr(args, "hierarchy_tiers", 3) or 3))
    runner = TreeRunner(
        topo,
        template=default_template(int(getattr(args, "hierarchy_params",
                                              1024))),
        codec=str(getattr(args, "compression", "") or "int8"),
        seed=int(getattr(args, "random_seed", 0)),
        quorum=float(getattr(args, "round_quorum", 1.0) or 1.0),
        ef=bool(getattr(args, "hierarchy_ef", False)),
    )
    stats = runner.run(int(getattr(args, "comm_round", 1)))
    telemetry.flush_run()
    return stats


class ServerCrossDevice(Server):
    """Cross-device aggregation server (cross-silo FSM, device clients)."""

    def __init__(self, args: Any, device: Any, dataset: Any, model: Any,
                 server_aggregator=None):
        if int(getattr(args, "hierarchy_tiers", 0) or 0) >= 2:
            raise NotImplementedError(
                "hierarchy_tiers is set, but the flat cross-device server "
                "FSM does not drive wire-level aggregation trees yet — "
                "run the in-process tree engine instead: "
                "fedml_tpu.cross_device.run_hierarchical(args) / "
                "fedml_tpu.hierarchy.TreeRunner (CLI: `fedml_tpu tree`)")
        # device clients are never co-scheduled as mesh slices: force the
        # federation transport (broker/grpc/local), never 'sp'/'mesh'
        super().__init__(args, device, dataset, model, server_aggregator)
