"""Cross-device ("BeeHive") engine: server + on-device client runtime."""
from fedml_tpu.cross_device.client import (
    DeviceClient,
    FedMLBaseTrainer,
    JaxDeviceTrainer,
    build_device_client,
)
from fedml_tpu.cross_device.server import ServerCrossDevice

__all__ = [
    "DeviceClient",
    "FedMLBaseTrainer",
    "JaxDeviceTrainer",
    "ServerCrossDevice",
    "build_device_client",
]
