"""Cross-device ("BeeHive") engine: server + on-device client runtime.

Flat cohorts run the cross-silo FSM with device clients; planet-scale
cohorts (``hierarchy_tiers`` configured) route through the hierarchical
federation subsystem — see :func:`run_hierarchical` and
:mod:`fedml_tpu.hierarchy`.
"""
from fedml_tpu.cross_device.client import (
    DeviceClient,
    FedMLBaseTrainer,
    JaxDeviceTrainer,
    build_device_client,
)
from fedml_tpu.cross_device.server import ServerCrossDevice, run_hierarchical

__all__ = [
    "DeviceClient",
    "FedMLBaseTrainer",
    "JaxDeviceTrainer",
    "ServerCrossDevice",
    "build_device_client",
    "run_hierarchical",
]
