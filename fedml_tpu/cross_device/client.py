"""Cross-device ("BeeHive") client runtime.

Parity target: the reference's on-device C++ stack —
``android/fedmlsdk/MobileNN/includes/train/FedMLBaseTrainer.h:1`` (the
train-loop abstraction with progress/accuracy/loss callbacks and a stop
flag, implemented over two NN engines: MNN and torch-mobile),
``src/FedMLClientManager.cpp`` (orchestrates the round against the
server), and ``src/train/FedMLTrainerSA.cpp`` (the SecAgg on-device
variant). Re-design for this build:

- :class:`FedMLBaseTrainer` keeps the C++ interface shape — ``init``
  with host callbacks, ``train``, ``get_epoch_and_loss``,
  ``stop_training`` — as the pluggable engine seam; the in-tree engine
  is :class:`JaxDeviceTrainer`, a compact per-epoch jitted SGD loop
  (epoch-granular on purpose: a device reports progress per epoch, so
  the host loop is per-epoch with one compiled step program — unlike the
  datacenter trainer that scans all epochs inside one XLA program).
- :class:`DeviceClient` is the FedMLClientManager twin: it binds the
  trainer to the cross-silo wire protocol (plain rounds) or the Bonawitz
  SecAgg FSM (``secure_aggregation: true``) over any federation
  transport — so the same server (``ServerCrossDevice``) drives phones,
  sim processes, or CI subprocesses identically.

Run standalone:  ``python -m fedml_tpu.cross_device.client --cf cfg.yaml
--rank N``.
"""
from __future__ import annotations

import logging
import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)

Pytree = Any

ProgressCallback = Callable[[float], None]
EpochMetricCallback = Callable[[int, float], None]


class FedMLBaseTrainer:
    """On-device train-loop abstraction (FedMLBaseTrainer.h parity).

    Subclasses implement :meth:`train`; the host (JNI bridge in the
    reference, the DeviceClient here) drives ``init`` → per-round
    ``set_model``/``train`` and may poll ``get_epoch_and_loss`` or flip
    the stop flag from another thread.
    """

    def init(self, dataset: Any, train_size: int, batch_size: int,
             learning_rate: float, epochs: int,
             progress_callback: Optional[ProgressCallback] = None,
             accuracy_callback: Optional[EpochMetricCallback] = None,
             loss_callback: Optional[EpochMetricCallback] = None) -> None:
        self.dataset = dataset
        self.train_size = int(train_size)
        self.batch_size = int(batch_size)
        self.learning_rate = float(learning_rate)
        self.epochs = int(epochs)
        self.progress_callback = progress_callback
        self.accuracy_callback = accuracy_callback
        self.loss_callback = loss_callback
        self.cur_epoch = 0
        self.cur_loss = 0.0
        self._stop_flag = False

    def set_model(self, params: Pytree) -> None:
        """Load the round's global model (the .mnn file write parity)."""
        self.params = params

    def train(self) -> Tuple[Pytree, int]:
        """Run local training; returns (new_params, n_samples)."""
        raise NotImplementedError

    def get_epoch_and_loss(self) -> Tuple[int, float]:
        return self.cur_epoch, self.cur_loss

    def stop_training(self) -> bool:
        self._stop_flag = True
        return True


class JaxDeviceTrainer(FedMLBaseTrainer):
    """The in-tree on-device engine: per-epoch jitted minibatch SGD."""

    def __init__(self, apply_fn: Callable):
        self.apply_fn = apply_fn
        self._epoch_step = None

    def _build(self) -> None:
        from fedml_tpu.ml.trainer.local_sgd import softmax_ce_loss

        loss_fn = softmax_ce_loss(self.apply_fn)
        opt = optax.sgd(self.learning_rate)

        def epoch(params, opt_state, xs, ys, mask):
            def step(carry, batch):
                params, opt_state = carry
                x, y, m = batch
                (loss, (correct, denom)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, x, y, m)
                updates, opt_state = opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state), (
                    loss * denom, correct, denom)
            (params, opt_state), (losses, corrects, denoms) = jax.lax.scan(
                step, (params, opt_state), (xs, ys, mask))
            total = jnp.maximum(jnp.sum(denoms), 1.0)
            return params, opt_state, {
                "loss": jnp.sum(losses) / total,
                "acc": jnp.sum(corrects) / total,
            }

        self._epoch_step = jax.jit(epoch)
        self._opt = opt

    def train(self) -> Tuple[Pytree, int]:
        if self._epoch_step is None:
            self._build()
        x, y = self.dataset
        n = min(self.train_size, len(x)) or len(x)
        x, y = np.asarray(x[:n]), np.asarray(y[:n])
        steps = max(1, math.ceil(n / self.batch_size))
        pad = steps * self.batch_size - n
        mask = np.concatenate([np.ones(n, np.float32),
                               np.zeros(pad, np.float32)])
        xs = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        ys = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        xs = xs.reshape((steps, self.batch_size) + x.shape[1:])
        ys = ys.reshape((steps, self.batch_size) + y.shape[1:])
        mask = mask.reshape(steps, self.batch_size)

        params = self.params
        opt_state = self._opt.init(params)
        for epoch in range(self.epochs):
            if self._stop_flag:
                logger.info("device trainer: stop flag set at epoch %d", epoch)
                break
            params, opt_state, metrics = self._epoch_step(
                params, opt_state, xs, ys, mask)
            self.cur_epoch = epoch
            self.cur_loss = float(metrics["loss"])
            if self.loss_callback:
                self.loss_callback(epoch, self.cur_loss)
            if self.accuracy_callback:
                self.accuracy_callback(epoch, float(metrics["acc"]))
            if self.progress_callback:
                self.progress_callback((epoch + 1) / self.epochs)
        return params, n


class _DeviceAdapter:
    """Presents the manager-side adapter interface (update_dataset/train)
    over a FedMLBaseTrainer — the FedMLClientManager glue."""

    def __init__(self, trainer: FedMLBaseTrainer):
        self.trainer = trainer
        self.client_index = None

    def update_dataset(self, client_index: int) -> None:
        # the device owns its data; the server-sent index is recorded only
        # for logging parity with silo clients
        self.client_index = int(client_index)

    def train(self, round_idx: int, global_params: Pytree) -> Tuple[Pytree, int]:
        self.trainer.set_model(global_params)
        return self.trainer.train()


class DeviceClient:
    """FedMLClientManager twin: trainer + wire protocol for one device.

    ``args.secure_aggregation`` selects the SecAgg FSM
    (FedMLClientManagerSA / FedMLTrainerSA parity) — masking happens
    on-device in ``core/mpc/secagg``; the server never sees this
    device's raw update.
    """

    def __init__(self, args: Any, trainer: FedMLBaseTrainer):
        self.args = args
        backend = str(getattr(args, "comm_backend", None)
                      or getattr(args, "backend", "LOCAL"))
        rank = int(getattr(args, "rank", 1))
        n_clients = int(getattr(args, "client_num_per_round",
                                getattr(args, "client_num_in_total", 1)))
        adapter = _DeviceAdapter(trainer)
        if bool(getattr(args, "secure_aggregation", False)):
            from fedml_tpu.cross_silo.secagg.sa_client_manager import (
                SAClientManager,
            )

            self.manager = SAClientManager(
                args, adapter, rank=rank, size=n_clients + 1, backend=backend)
        else:
            from fedml_tpu.cross_silo.client.fedml_client_master_manager import (
                ClientMasterManager,
            )

            self.manager = ClientMasterManager(
                args, adapter, rank=rank, size=n_clients + 1, backend=backend)

    def run(self) -> None:
        self.manager.run()

    def run_async(self):
        return self.manager.run_async()


def build_device_client(args: Any) -> DeviceClient:
    """Assemble a device client from flat args: local data shard + model
    apply fn + JaxDeviceTrainer + wire manager."""
    if int(getattr(args, "hierarchy_tiers", 0) or 0) >= 2:
        raise NotImplementedError(
            "hierarchy_tiers is set, but device clients do not speak the "
            "aggregation-tree wire protocol yet (they would need an edge "
            "aggregator to upload to) — simulate the cohort with "
            "fedml_tpu.cross_device.run_hierarchical(args) / "
            "fedml_tpu.hierarchy.TreeRunner (CLI: `fedml_tpu tree`)")
    from fedml_tpu import models as models_mod
    from fedml_tpu.data import load_federated

    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    rank = int(getattr(args, "rank", 1))
    local = ds.train_data_local_dict[rank - 1]
    trainer = JaxDeviceTrainer(model.apply)
    trainer.init(
        dataset=local,
        train_size=int(getattr(args, "train_size_device", 0)) or len(local[0]),
        batch_size=int(getattr(args, "batch_size", 32)),
        learning_rate=float(getattr(args, "learning_rate", 0.03)),
        epochs=int(getattr(args, "epochs", 1)),
    )
    return DeviceClient(args, trainer)


def main(argv=None) -> None:
    import fedml_tpu
    from fedml_tpu.arguments import load_arguments

    args = load_arguments(None, None)
    args = fedml_tpu.init(args)
    client = build_device_client(args)
    client.run()


if __name__ == "__main__":
    main()
