"""Pallas TPU flash attention — the long-context hot op.

Parity target: the reference's long-context support is a FlashAttention
monkey-patch over HF models (``train/llm/models/attention.py:30-101``,
GPT-NeoX impl ``models/modeling_gpt_neox.py``). Here the kernel is a
first-class framework op: an online-softmax tiled attention written in
Pallas for the TPU MXU/VMEM hierarchy, with a custom VJP whose backward is
also two Pallas kernels (dq; dk/dv) so neither pass materialises the
[T, S] score matrix in HBM.

Design notes (pallas_guide.md):
- grid is (batch, q_heads, q_blocks, kv_blocks) with the kv axis innermost —
  on TPU the innermost grid axis is sequential per core, so the online
  softmax accumulators live in VMEM scratch across kv steps and the output
  block is written once, on the last kv step;
- GQA is expressed in the BlockSpec index maps (kv head = q head // group)
  instead of materialising repeated K/V in HBM;
- causal masking skips whole kv blocks past the diagonal via ``pl.when``
  (compute is masked, the DMA pipeline stays regular);
- off-TPU (CPU tests) the same kernels run under ``interpret=True``.

The public entry is :func:`flash_attention` — identical math to
``jax.nn.dot_product_attention`` for supported shapes, verified by tests.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled builds; interpret mode needs no TPU
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _zero_phantom_rows(x, start, limit):
    """Zero block-padding rows past ``limit`` — padded loads can be NaN/garbage,
    and 0*NaN from an otherwise-masked contribution would still poison sums."""
    rows = start + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(rows < limit, x, 0.0)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_i, l_i, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                kv_steps: int, s_len: int, t_len: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_i[...] = jnp.full_like(m_i, -jnp.inf)
        l_i[...] = jnp.zeros_like(l_i)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [block_k, d]
        v = v_ref[0, 0].astype(jnp.float32)  # [block_k, d]
        if (t_len % block_q) != 0:
            q = _zero_phantom_rows(q, q_start, t_len)
        if (s_len % block_k) != 0:
            k = _zero_phantom_rows(k, k_start, s_len)
            v = _zero_phantom_rows(v, k_start, s_len)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal or (s_len % block_k) != 0:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            valid = cols < s_len  # phantom padding columns past S
            if causal:
                valid = valid & (rows >= cols)
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        m_prev = m_i[:, 0]
        l_prev = l_i[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = alpha * l_prev + jnp.sum(p, axis=1)
        acc[...] = acc[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_i[...] = jnp.broadcast_to(m_cur[:, None], m_i.shape)
        l_i[...] = jnp.broadcast_to(l_cur[:, None], l_i.shape)

    if causal:
        # whole kv block strictly above the diagonal contributes nothing
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == kv_steps - 1)
    def _finalize():
        l = jnp.maximum(l_i[:, 0], 1e-30)
        o_ref[0, 0] = (acc[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_i[:, :1] + jnp.log(l)[:, None])


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    _, hkv, s, _ = k.shape
    group = h // hkv
    block_q = min(block_q, t)
    block_k = min(block_k, s)
    q_steps, kv_steps = pl.cdiv(t, block_q), pl.cdiv(s, block_k)

    grid = (b, h, q_steps, kv_steps)
    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)
    )
    out_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_steps=kv_steps, s_len=s, t_len=t,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[out_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, sm_scale, causal, block_q, block_k, kv_steps,
                   s_len, t_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q_start, k_start = qi * block_q, ki * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        if (t_len % block_q) != 0:
            q = _zero_phantom_rows(q, q_start, t_len)
            do = _zero_phantom_rows(do, q_start, t_len)
            lse = jnp.where(q_start + jnp.arange(block_q) < t_len, lse, 0.0)
            delta = jnp.where(q_start + jnp.arange(block_q) < t_len, delta, 0.0)
        if (s_len % block_k) != 0:
            k = _zero_phantom_rows(k, k_start, s_len)
            v = _zero_phantom_rows(v, k_start, s_len)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal or (s_len % block_k) != 0:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            valid = cols < s_len
            if causal:
                valid = valid & (rows >= cols)
            s = jnp.where(valid, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == kv_steps - 1)
    def _write():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                    block_q, block_k, q_steps, t_len, s_len):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start, k_start = qi * block_q, ki * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        if (t_len % block_q) != 0:
            q = _zero_phantom_rows(q, q_start, t_len)
            do = _zero_phantom_rows(do, q_start, t_len)
            lse = jnp.where(q_start + jnp.arange(block_q) < t_len, lse, 0.0)
            delta = jnp.where(q_start + jnp.arange(block_q) < t_len, delta, 0.0)
        if (s_len % block_k) != 0:
            k = _zero_phantom_rows(k, k_start, s_len)
            v = _zero_phantom_rows(v, k_start, s_len)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            s = jnp.where(rows >= cols, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        if (t_len % block_q) != 0:
            # phantom q rows (block padding past T) carry garbage lse/delta —
            # zero their probability mass so dk/dv sums stay exact
            p = jnp.where(rows < t_len, p, 0.0)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        if (t_len % block_q) != 0:
            # delta for phantom rows is garbage; p==0 there, but 0*inf=nan
            ds = jnp.where(rows < t_len, ds, 0.0)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        # q block entirely above diagonal sees none of this kv block
        pl.when(q_start + block_q - 1 >= k_start)(_compute)
    else:
        _compute()

    @pl.when(qi == q_steps - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    b, h, t, d = q.shape
    _, hkv, s, _ = k.shape
    group = h // hkv
    bq, bk = min(block_q, t), min(block_k, s)
    q_steps, kv_steps = pl.cdiv(t, bq), pl.cdiv(s, bk)
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # [b, h, t, 1] — trailing singleton keeps TPU block tiling legal

    def scratch(shape):
        return pltpu.VMEM(shape, jnp.float32)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0))
    lse_spec = pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, kv_steps=kv_steps,
                          s_len=s, t_len=t),
        grid=(b, h, q_steps, kv_steps),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[scratch((bq, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv accumulate over q heads within a group as well: run per q-head
    # into a [b, h, ...] buffer, then sum the group axis outside the kernel.
    kq_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kkv_spec = pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi // group, ki, 0))
    klse_spec = pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kout_spec = pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=bq, block_k=bk, q_steps=q_steps,
                          t_len=t, s_len=s),
        grid=(b, h, kv_steps, q_steps),
        in_specs=[kq_spec, kkv_spec, kkv_spec, kq_spec, klse_spec, klse_spec],
        out_specs=[kout_spec, kout_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
        ],
        scratch_shapes=[scratch((bk, d)), scratch((bk, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk = dk_h.reshape(b, hkv, group, s, d).sum(axis=2).astype(k.dtype)
    dv = dv_h.reshape(b, hkv, group, s, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------
@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    return _bwd(sm_scale, causal, block_q, block_k, interpret, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def reference_attention(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """Plain-XLA attention (numerics oracle + CPU fallback). [B,H,T,D] layout."""
    b, h, t, d = q.shape
    _, hkv, s_len, _ = k.shape
    if hkv != h:
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    logits = jnp.einsum(
        "bhtd,bhsd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, s_len), bool), k=s_len - t)
        logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", probs, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Tiled online-softmax attention. q: [B,H,T,D]; k/v: [B,Hkv,S,D].

    Dispatches to the Pallas kernels on TPU; off-TPU it uses the plain-XLA
    reference path (the kernels still run under ``interpret=True`` when
    forced, which is how the unit tests exercise them on CPU).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        if not _on_tpu():
            return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)
        interpret = False
    return _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret)
