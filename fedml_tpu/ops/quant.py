"""Int8 weight-only quantization for TPU serving.

Quantizes 2-D kernels to per-output-channel int8 and swaps them into the
params pytree as :class:`QuantizedTensor` leaves; ``LoRADense`` / the lm
head consume them as ``(x @ q.astype(bf16)) * scale`` — mathematically
identical to dequantize-then-matmul with the scale folded into outputs.

What it buys (measured, PERF_NOTES addendum 4): **HBM residency halves**
(2.25 GB → 1.13 GB for the 1.1B bench model), fitting ~2× the model per
serving chip. What it does NOT buy on current XLA: decode speed — the
int8→bf16 convert is materialized rather than staying fused into the
dot's operand load, so the decode step measured *slower* (7.1 vs 4.5 ms
at B8); use it for capacity, not latency. The latency path is full
int8×int8 (activation quant, MXU-native) — future work.

No reference counterpart: the reference delegates quantized serving to
vLLM/Triton containers.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Per-output-channel symmetric int8 weight: ``w ≈ data * scale``."""

    def __init__(self, data, scale):
        self.data = data    # int8  [in, out]
        self.scale = scale  # f32   [out]

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- array-ish surface ----------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.data.shape

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def dequantize(self, dtype=jnp.float32):
        return self.data.astype(dtype) * self.scale.astype(dtype)[None, :]

    def matmul(self, x, dtype):
        """``x @ W`` with the scale folded into the OUTPUT channels —
        exact w.r.t. dequantize-then-matmul, but the int8→bf16 convert
        fuses into the dot so the weights are read from HBM as int8."""
        return (x @ self.data.astype(dtype)) * self.scale.astype(dtype)


def quantize_int8(w: Any) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of a [in, out] kernel."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)          # [out]
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale)


def quantize_params_int8(params: Any, min_size: int = 65536) -> Any:
    """Swap every large 2-D non-LoRA kernel leaf for a QuantizedTensor.

    LoRA adapters stay fp32 (they are tiny and trained); embeddings stay
    full precision (gather, not matmul); norms/bias are 1-D and skipped.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        # partitioning metadata boxes end the path with GetAttrKey('value');
        # the param NAME is the last dict key
        dict_keys = [str(p.key) for p in path if hasattr(p, "key")]
        name = "/".join(dict_keys)
        is_kernel = dict_keys and dict_keys[-1] in ("kernel", "lm_head")
        if (is_kernel and getattr(leaf, "ndim", 0) == 2
                and leaf.size >= min_size
                and "lora" not in name
                and "embed" not in name):
            out.append(quantize_int8(leaf))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def matmul_maybe_quantized(x, w, dtype):
    """``x @ w`` that accepts either a plain kernel or a QuantizedTensor —
    the single dispatch point model code uses, so new quantized formats
    only need to be handled here."""
    if isinstance(w, QuantizedTensor):
        return w.matmul(x, dtype)
    return x @ w.astype(dtype)


def tree_bytes(params: Any) -> int:
    """Actual bytes a (possibly quantized) params tree occupies."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = int(np.prod(getattr(leaf, "shape", (0,)) or (0,)))
        total += n * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize
    return total
