"""Int8 weight-only quantization for TPU serving.

Quantizes 2-D kernels to per-output-channel int8 and swaps them into the
params pytree as :class:`QuantizedTensor` leaves; ``LoRADense`` / the lm
head consume them as ``(x @ q.astype(bf16)) * scale`` — mathematically
identical to dequantize-then-matmul with the scale folded into outputs.

What it buys (measured on-chip, PERF_NOTES round-4 addendum): **HBM
residency halves** (2.25 GB → 1.13 GB for the 1.1B bench model) AND,
with the default Pallas fused dequant-matmul, **decode gets 1.7× faster**
(3.14 ms vs 5.38 ms bf16 at B8/ctx512 → 2548 vs 1486 tok/s). The fusion
XLA refuses — it materializes the int8→bf16 convert, which is why the
plain lowering measured *slower* than bf16 (8.0 ms) — is done by hand in
``pallas_dequant_matmul``: weight tiles stream from HBM as int8 and
convert in-VMEM. ``w8a8`` (int8×int8 MXU dot) also loses under XLA's
lowering (6.8 ms); the kernel wins on pure weight bandwidth.

No reference counterpart: the reference delegates quantized serving to
vLLM/Triton containers.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Per-output-channel symmetric int8 weight: ``w ≈ data * scale``.

    ``mode`` selects the matmul lowering:
      * ``"dequant"`` — x·dequant(W) in bf16 (exact w.r.t. the quantized
        weights; XLA materializes the int8→bf16 convert, so it buys HBM
        capacity but not decode latency);
      * ``"w8a8"``    — dynamic per-row activation quant + int8×int8 dot
        accumulated in int32 (``preferred_element_type``), MXU-native;
      * ``"pallas"``  — fused dequant-matmul kernel: weight tiles DMA'd
        from HBM as int8 and converted in-VMEM (half the weight
        bandwidth — the decode-latency path). bf16-activation-only:
        exact for bf16 compute; fp32 requests fall back to "dequant".
    """

    def __init__(self, data, scale, mode: str = "dequant"):
        self.data = data    # int8  [in, out]
        self.scale = scale  # f32   [out]
        self.mode = mode

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale), self.mode

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, mode=aux)

    # -- array-ish surface ----------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.data.shape

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def dequantize(self, dtype=jnp.float32):
        return self.data.astype(dtype) * self.scale.astype(dtype)[None, :]

    def matmul(self, x, dtype):
        """``x @ W`` under the tensor's mode (see class docstring)."""
        if self.mode == "w8a8":
            return self._matmul_w8a8(x, dtype)
        if self.mode == "pallas":
            return pallas_dequant_matmul(x, self.data, self.scale, dtype)
        return (x @ self.data.astype(dtype)) * self.scale.astype(dtype)

    def _matmul_w8a8(self, x, dtype):
        # dynamic symmetric per-row activation quant: rounding error only
        # (~0.4% rms for typical activations), standard W8A8 serving
        x32 = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
        xs = jnp.where(amax > 0, amax / 127.0, 1.0)
        xq = jnp.clip(jnp.round(x32 / xs), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, self.data, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return (acc.astype(jnp.float32) * xs * self.scale).astype(dtype)


def quantize_int8(w: Any, mode: str = "dequant") -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of a [in, out] kernel."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)          # [out]
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale, mode=mode)


def quantize_params_int8(params: Any, min_size: int = 65536,
                         mode: str = "dequant", donate: bool = False) -> Any:
    """Swap every large 2-D non-LoRA kernel leaf for a QuantizedTensor.

    LoRA adapters stay fp32 (they are tiny and trained); embeddings stay
    full precision (gather, not matmul); norms/bias are 1-D and skipped.

    ``donate=True`` frees each source kernel's device buffer as soon as
    its int8 twin exists — without it, quantizing a 7B model needs
    bf16 + int8 resident simultaneously (13.5 + 6.8 GB), which does not
    fit a 16 GB chip. The caller's ``params`` tree is INVALID afterwards.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        # partitioning metadata boxes end the path with GetAttrKey('value');
        # the param NAME is the last dict key
        dict_keys = [str(p.key) for p in path if hasattr(p, "key")]
        name = "/".join(dict_keys)
        is_kernel = dict_keys and dict_keys[-1] in ("kernel", "lm_head")
        if (is_kernel and getattr(leaf, "ndim", 0) == 2
                and leaf.size >= min_size
                and "lora" not in name
                and "embed" not in name):
            q = quantize_int8(leaf, mode=mode)
            if donate and isinstance(leaf, jax.Array):
                jax.block_until_ready(q.data)  # q computed before source dies
                leaf.delete()
            out.append(q)
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# -- Pallas fused dequant-matmul (the decode-latency path) -----------------
#
# XLA lowers x @ convert(int8) by MATERIALIZING the converted bf16 weights
# (measured: int8 decode 7.1 ms vs bf16 4.5 ms at B8 — PERF_NOTES addendum
# 4), so weight-only int8 bought capacity but lost latency. This kernel
# does what the compiler wouldn't fuse: DMA the weight tile from HBM as
# int8 (half the bytes — decode is weight-bandwidth-bound), convert
# in-VMEM on the VPU, and feed the MXU in bf16. Scales fold into outputs.

try:  # pltpu only imports on TPU-enabled builds; interpret mode needs no TPU
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# VMEM budget for the weight tile: scoped vmem is 16 MB, and the tile
# shares it with x, the accumulator, and the output block
_TILE_BYTES = 6 * 1024 * 1024


def _pick_tiles(h: int, f: int):
    """(bh, bf) tile of the int8 weight: lane dims multiples of 128 that
    divide the axis, biggest f-block first, tile ≤ _TILE_BYTES."""
    def divisors(dim, cap):
        # 128-lane-aligned blocks only — Mosaic tiling needs them; a dim
        # with no 128-multiple divisor returns [] → caller falls back
        start = min(dim, cap) // 128 * 128
        return [b for b in range(start, 0, -128) if dim % b == 0]

    # narrow f-blocks (≤512) give the DMA/compute pipeline more grid
    # steps to overlap — measured faster than maximal tiles at B=8
    for bf in divisors(f, 512):
        for bh in divisors(h, 8192):
            if bh * bf <= _TILE_BYTES:
                return bh, bf
    return 0, 0


def _dequant_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref):
    ih = pl.program_id(1)  # reduction step (innermost grid dim)

    @pl.when(ih == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[...].astype(jnp.bfloat16)          # int8 → bf16 in VMEM
    acc_ref[...] += jnp.dot(x_ref[...], w,
                            preferred_element_type=jnp.float32)

    @pl.when(ih == pl.num_programs(1) - 1)
    def _done():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)
                      ).astype(o_ref.dtype)


def pallas_dequant_matmul(x, q, scale, dtype):
    """``(x @ dequant(q)) * scale`` with the convert fused into the tile
    load. x: [B, H] (or [..., H], flattened), q: int8 [H, F], scale [F]."""
    lead = x.shape[:-1]
    h, f = q.shape
    bh, bf = _pick_tiles(h, f)
    rows = int(np.prod(lead)) if lead else 1
    # The kernel exists for the weight-bandwidth-bound DECODE regime
    # (few rows). Prefill (rows ≫ 128) is MXU-bound — the weights
    # amortize over the rows, the x block would blow the VMEM budget
    # (rows × bh bf16), and XLA's dequant costs proportionally little.
    # The kernel's MXU dot runs on bf16 operands, so it is exact only for
    # bf16 compute — fp32 requests take the XLA dequant lowering instead
    # of silently truncating activations (ADVICE r4).
    if (bh == 0 or rows > 128 or pltpu is None
            or jnp.dtype(dtype) != jnp.dtype(jnp.bfloat16)):
        return (x.reshape(*lead, h) @ q.astype(dtype)) * scale.astype(dtype)
    x2 = x.reshape(-1, h).astype(jnp.bfloat16)
    b = x2.shape[0]
    out = pl.pallas_call(
        _dequant_matmul_kernel,
        grid=(f // bf, h // bh),
        in_specs=[
            pl.BlockSpec((b, bh), lambda j, i: (0, i)),
            pl.BlockSpec((bh, bf), lambda j, i: (i, j)),
            pl.BlockSpec((1, bf), lambda j, i: (0, j)),
        ],
        out_specs=pl.BlockSpec((b, bf), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, f), dtype),
        scratch_shapes=[pltpu.VMEM((b, bf), jnp.float32)],
        interpret=jax.devices()[0].platform != "tpu",  # CPU tests
    )(x2, q, scale.reshape(1, f))
    return out.reshape(*lead, f)


def matmul_maybe_quantized(x, w, dtype):
    """``x @ w`` that accepts either a plain kernel or a QuantizedTensor —
    the single dispatch point model code uses, so new quantized formats
    only need to be handled here."""
    if isinstance(w, QuantizedTensor):
        return w.matmul(x, dtype)
    return x @ w.astype(dtype)


def tree_bytes(params: Any) -> int:
    """Actual bytes a (possibly quantized) params tree occupies."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = int(np.prod(getattr(leaf, "shape", (0,)) or (0,)))
        total += n * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize
    return total
