"""Int8 and 4-bit weight-only quantization for TPU serving/training.

Quantizes 2-D kernels to per-output-channel int8 and swaps them into the
params pytree as :class:`QuantizedTensor` leaves; ``LoRADense`` / the lm
head consume them as ``(x @ q.astype(bf16)) * scale`` — mathematically
identical to dequantize-then-matmul with the scale folded into outputs.

:class:`QuantizedTensor4` is the 4-bit sibling (QLoRA, Dettmers et al.
2023): blockwise int4 or NF4 codes packed two per uint8 plus one f32
absmax scale per block — the same packing layout as the ``int4``/``nf4``
wire codec, so HBM holds exactly the wire bytes (~0.27× of bf16). The
dequant is fused into whatever program consumes the matmul: inside a
trace (the fused round, serving prefill/decode) the unpacked bf16 tile
is an XLA temporary, and the eager path routes through the cataloged
``quant/dequant_matmul`` program — a full-precision copy of the base is
never resident.

What it buys (measured on-chip, PERF_NOTES round-4 addendum): **HBM
residency halves** (2.25 GB → 1.13 GB for the 1.1B bench model) AND,
with the default Pallas fused dequant-matmul, **decode gets 1.7× faster**
(3.14 ms vs 5.38 ms bf16 at B8/ctx512 → 2548 vs 1486 tok/s). The fusion
XLA refuses — it materializes the int8→bf16 convert, which is why the
plain lowering measured *slower* than bf16 (8.0 ms) — is done by hand in
``pallas_dequant_matmul``: weight tiles stream from HBM as int8 and
convert in-VMEM. ``w8a8`` (int8×int8 MXU dot) also loses under XLA's
lowering (6.8 ms); the kernel wins on pure weight bandwidth.

No reference counterpart: the reference delegates quantized serving to
vLLM/Triton containers.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Per-output-channel symmetric int8 weight: ``w ≈ data * scale``.

    ``mode`` selects the matmul lowering:
      * ``"dequant"`` — x·dequant(W) in bf16 (exact w.r.t. the quantized
        weights; XLA materializes the int8→bf16 convert, so it buys HBM
        capacity but not decode latency);
      * ``"w8a8"``    — dynamic per-row activation quant + int8×int8 dot
        accumulated in int32 (``preferred_element_type``), MXU-native;
      * ``"pallas"``  — fused dequant-matmul kernel: weight tiles DMA'd
        from HBM as int8 and converted in-VMEM (half the weight
        bandwidth — the decode-latency path). bf16-activation-only:
        exact for bf16 compute; fp32 requests fall back to "dequant".
    """

    def __init__(self, data, scale, mode: str = "dequant"):
        self.data = data    # int8  [in, out]
        self.scale = scale  # f32   [out]
        self.mode = mode

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale), self.mode

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, mode=aux)

    # -- array-ish surface ----------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.data.shape

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def dequantize(self, dtype=jnp.float32):
        return self.data.astype(dtype) * self.scale.astype(dtype)[None, :]

    def matmul(self, x, dtype):
        """``x @ W`` under the tensor's mode (see class docstring)."""
        if self.mode == "w8a8":
            return self._matmul_w8a8(x, dtype)
        if self.mode == "pallas":
            return pallas_dequant_matmul(x, self.data, self.scale, dtype)
        return (x @ self.data.astype(dtype)) * self.scale.astype(dtype)

    def _matmul_w8a8(self, x, dtype):
        # dynamic symmetric per-row activation quant: rounding error only
        # (~0.4% rms for typical activations), standard W8A8 serving
        x32 = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
        xs = jnp.where(amax > 0, amax / 127.0, 1.0)
        xq = jnp.clip(jnp.round(x32 / xs), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, self.data, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return (acc.astype(jnp.float32) * xs * self.scale).astype(dtype)


def quantize_int8(w: Any, mode: str = "dequant") -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of a [in, out] kernel."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)          # [out]
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale, mode=mode)


def quantize_params_int8(params: Any, min_size: int = 65536,
                         mode: str = "dequant", donate: bool = False) -> Any:
    """Swap every large 2-D non-LoRA kernel leaf for a QuantizedTensor.

    LoRA adapters stay fp32 (they are tiny and trained); embeddings stay
    full precision (gather, not matmul); norms/bias are 1-D and skipped.

    ``donate=True`` frees each source kernel's device buffer as soon as
    its int8 twin exists — without it, quantizing a 7B model needs
    bf16 + int8 resident simultaneously (13.5 + 6.8 GB), which does not
    fit a 16 GB chip. The caller's ``params`` tree is INVALID afterwards.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        # partitioning metadata boxes end the path with GetAttrKey('value');
        # the param NAME is the last dict key
        dict_keys = [str(p.key) for p in path if hasattr(p, "key")]
        name = "/".join(dict_keys)
        is_kernel = dict_keys and dict_keys[-1] in ("kernel", "lm_head")
        if (is_kernel and getattr(leaf, "ndim", 0) == 2
                and leaf.size >= min_size
                and "lora" not in name
                and "embed" not in name):
            q = quantize_int8(leaf, mode=mode)
            if donate and isinstance(leaf, jax.Array):
                jax.block_until_ready(q.data)  # q computed before source dies
                leaf.delete()
            out.append(q)
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# -- 4-bit residency (QLoRA-style int4/NF4 base weights) -------------------
#
# Same packed layout as the int4/nf4 wire codec (two codes per uint8,
# per-block f32 absmax scale), so a staged wire payload and the resident
# base are byte-identical formats. Residency uses deterministic
# round-to-nearest — static weights are quantized ONCE, and there is no
# error-feedback loop to absorb stochastic-rounding noise like the wire
# path has, so nearest minimizes per-weight error.

DEFAULT_BLOCK4 = 64  # QLoRA convention for base-weight residency


def _unpack4(packed):
    """[..., k] uint8 → [..., 2k] int32 codes; element 2i is the low
    nibble of byte i (the wire codec's layout)."""
    lo = (packed & 0xF).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (2 * packed.shape[-1],))


def _codes_to_vals(codes, fmt: str):
    if fmt == "nf4":
        from fedml_tpu.compression.codecs import NF4_CODEBOOK
        return jnp.asarray(NF4_CODEBOOK)[codes]
    return codes.astype(jnp.float32) - 8.0


def _quantize4_blocks(w, fmt: str, block: int):
    """Flatten → pad to blocks → absmax scale → codes → packed nibbles."""
    flat = jnp.asarray(w, jnp.float32).reshape(-1)
    size = flat.shape[0]
    n_blocks = -(-size // block)
    pad = n_blocks * block - size
    if pad:
        # padding encodes to exact 0 in both formats (int4 code 8,
        # nf4 code 7) — it adds no mass and dequants to zero
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    xb = flat.reshape(n_blocks, block)
    amax = jnp.max(jnp.abs(xb), axis=1)
    if fmt == "nf4":
        from fedml_tpu.compression.codecs import _NF4_MIDPOINTS
        scale = jnp.where(amax > 0, amax, 1.0)
        codes = jnp.sum(
            (xb / scale[:, None])[..., None] > jnp.asarray(_NF4_MIDPOINTS),
            axis=-1).astype(jnp.int32)
    else:
        scale = jnp.where(amax > 0, amax / 7.0, 1.0)
        codes = (jnp.clip(jnp.round(xb / scale[:, None]), -7, 7)
                 .astype(jnp.int32) + 8)
    data = (codes[:, 0::2] | (codes[:, 1::2] << 4)).astype(jnp.uint8)
    return data, scale


@jax.tree_util.register_pytree_node_class
class QuantizedTensor4:
    """Blockwise 4-bit weight: ``w ≈ lookup(codes) * scale`` per block.

    ``data`` holds two codes per uint8 (``[n_blocks, block // 2]``),
    ``scale`` one f32 per block — 0.53125 bytes/element at block 64,
    ~0.27× of bf16. ``fmt`` is ``"int4"`` (uniform, codes−8) or ``"nf4"``
    (Dettmers et al. 2023 normal-float codebook; better for the
    zero-centered bell-shaped weight distributions of trained models).

    The dequantized matrix is never resident: :meth:`matmul` inlines the
    unpack → lookup → scale chain when tracing (the fused round / serving
    step fuses it as XLA temporaries), and routes eager calls through the
    cataloged ``quant/dequant_matmul`` program.
    """

    def __init__(self, data, scale, shape, fmt: str = "int4",
                 block: int = DEFAULT_BLOCK4):
        self.data = data              # uint8 [n_blocks, block // 2]
        self.scale = scale            # f32   [n_blocks]
        self.orig_shape = tuple(int(d) for d in shape)
        self.fmt = fmt
        self.block = int(block)

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale), (self.orig_shape, self.fmt,
                                         self.block)

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, fmt, block = aux
        return cls(children[0], children[1], shape, fmt=fmt, block=block)

    # -- array-ish surface ----------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.orig_shape

    @property
    def ndim(self) -> int:
        return len(self.orig_shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.orig_shape, dtype=np.int64)) \
            if self.orig_shape else 1

    def dequantize(self, dtype=jnp.float32):
        vals = _codes_to_vals(_unpack4(self.data), self.fmt)
        flat = (vals * self.scale.astype(jnp.float32)[:, None]).reshape(-1)
        return flat[:self.size].reshape(self.orig_shape).astype(dtype)

    def matmul(self, x, dtype):
        """``x @ dequant(W)`` with the dequant fused into the consumer."""
        if isinstance(x, jax.core.Tracer):
            # inside an enclosing trace (llm/fused_round, serving
            # prefill/decode): the dequantized tile is an XLA temporary
            # of THAT program — never call a CatalogedProgram on tracers
            return x @ self.dequantize(dtype)
        return _dequant4_matmul_program(
            self.fmt, self.orig_shape, jnp.dtype(dtype).name,
            x, self.data, self.scale)


def _pack4(fmt, block, w):
    return _quantize4_blocks(w, fmt, block)


def _dequant4_matmul(fmt, shape, dtype_name, x, data, scale):
    dt = jnp.dtype(dtype_name)
    vals = _codes_to_vals(_unpack4(data), fmt)
    flat = (vals * scale.astype(jnp.float32)[:, None]).reshape(-1)
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return x @ flat[:size].reshape(shape).astype(dt)


def quantize_int4(w: Any, fmt: str = "int4",
                  block: int = DEFAULT_BLOCK4) -> QuantizedTensor4:
    """Blockwise 4-bit quantization of a kernel (round-to-nearest)."""
    if fmt not in ("int4", "nf4"):
        raise ValueError(
            f"4-bit base format must be 'int4' or 'nf4', got {fmt!r}")
    block = int(block)
    if block < 2 or block > (1 << 20) or block & (block - 1):
        raise ValueError(
            f"4-bit block must be a power of two in [2, 2^20], got {block}")
    shape = tuple(int(d) for d in w.shape)
    data, scale = _pack4_program(fmt, block, jnp.asarray(w, jnp.float32))
    return QuantizedTensor4(data, scale, shape, fmt=fmt, block=block)


def quantize_params_int4(params: Any, fmt: str = "int4",
                         min_size: int = 65536,
                         block: int = DEFAULT_BLOCK4,
                         donate: bool = False) -> Any:
    """Swap every large 2-D non-LoRA kernel leaf for a QuantizedTensor4.

    Same leaf filter and ``donate`` contract as :func:`quantize_params_int8`
    (LoRA/embeddings/1-D stay full precision; ``donate=True`` frees each
    source buffer once its packed twin exists). Records the packed
    footprint in the ``quant/base_bytes`` gauge and bumps
    ``quant/packed_leaves`` so a round trace shows what is 4-bit-resident.
    """
    from fedml_tpu import telemetry

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out: list = []
    packed_bytes = 0
    n_packed = 0
    for path, leaf in flat:
        dict_keys = [str(p.key) for p in path if hasattr(p, "key")]
        name = "/".join(dict_keys)
        is_kernel = dict_keys and dict_keys[-1] in ("kernel", "lm_head")
        if (is_kernel and getattr(leaf, "ndim", 0) == 2
                and leaf.size >= min_size
                and "lora" not in name
                and "embed" not in name):
            q = quantize_int4(leaf, fmt=fmt, block=block)
            if donate and isinstance(leaf, jax.Array):
                jax.block_until_ready(q.data)  # q computed before source dies
                leaf.delete()
            packed_bytes += int(q.data.size) + 4 * int(q.scale.size)
            n_packed += 1
            out.append(q)
        else:
            out.append(leaf)
    reg = telemetry.get_registry()
    reg.gauge("quant/base_bytes").set(packed_bytes)
    if n_packed:
        reg.counter("quant/packed_leaves").inc(n_packed)
    return jax.tree_util.tree_unflatten(treedef, out)


# -- Pallas fused dequant-matmul (the decode-latency path) -----------------
#
# XLA lowers x @ convert(int8) by MATERIALIZING the converted bf16 weights
# (measured: int8 decode 7.1 ms vs bf16 4.5 ms at B8 — PERF_NOTES addendum
# 4), so weight-only int8 bought capacity but lost latency. This kernel
# does what the compiler wouldn't fuse: DMA the weight tile from HBM as
# int8 (half the bytes — decode is weight-bandwidth-bound), convert
# in-VMEM on the VPU, and feed the MXU in bf16. Scales fold into outputs.

try:  # pltpu only imports on TPU-enabled builds; interpret mode needs no TPU
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# VMEM budget for the weight tile: scoped vmem is 16 MB, and the tile
# shares it with x, the accumulator, and the output block
_TILE_BYTES = 6 * 1024 * 1024


def _pick_tiles(h: int, f: int):
    """(bh, bf) tile of the int8 weight: lane dims multiples of 128 that
    divide the axis, biggest f-block first, tile ≤ _TILE_BYTES."""
    def divisors(dim, cap):
        # 128-lane-aligned blocks only — Mosaic tiling needs them; a dim
        # with no 128-multiple divisor returns [] → caller falls back
        start = min(dim, cap) // 128 * 128
        return [b for b in range(start, 0, -128) if dim % b == 0]

    # narrow f-blocks (≤512) give the DMA/compute pipeline more grid
    # steps to overlap — measured faster than maximal tiles at B=8
    for bf in divisors(f, 512):
        for bh in divisors(h, 8192):
            if bh * bf <= _TILE_BYTES:
                return bh, bf
    return 0, 0


def _dequant_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref):
    ih = pl.program_id(1)  # reduction step (innermost grid dim)

    @pl.when(ih == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[...].astype(jnp.bfloat16)          # int8 → bf16 in VMEM
    acc_ref[...] += jnp.dot(x_ref[...], w,
                            preferred_element_type=jnp.float32)

    @pl.when(ih == pl.num_programs(1) - 1)
    def _done():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)
                      ).astype(o_ref.dtype)


def pallas_dequant_matmul(x, q, scale, dtype):
    """``(x @ dequant(q)) * scale`` with the convert fused into the tile
    load. x: [B, H] (or [..., H], flattened), q: int8 [H, F], scale [F]."""
    lead = x.shape[:-1]
    h, f = q.shape
    bh, bf = _pick_tiles(h, f)
    rows = int(np.prod(lead)) if lead else 1
    # The kernel exists for the weight-bandwidth-bound DECODE regime
    # (few rows). Prefill (rows ≫ 128) is MXU-bound — the weights
    # amortize over the rows, the x block would blow the VMEM budget
    # (rows × bh bf16), and XLA's dequant costs proportionally little.
    # The kernel's MXU dot runs on bf16 operands, so it is exact only for
    # bf16 compute — fp32 requests take the XLA dequant lowering instead
    # of silently truncating activations (ADVICE r4).
    if (bh == 0 or rows > 128 or pltpu is None
            or jnp.dtype(dtype) != jnp.dtype(jnp.bfloat16)):
        return (x.reshape(*lead, h) @ q.astype(dtype)) * scale.astype(dtype)
    x2 = x.reshape(-1, h).astype(jnp.bfloat16)
    b = x2.shape[0]
    out = pl.pallas_call(
        _dequant_matmul_kernel,
        grid=(f // bf, h // bh),
        in_specs=[
            pl.BlockSpec((b, bh), lambda j, i: (0, i)),
            pl.BlockSpec((bh, bf), lambda j, i: (i, j)),
            pl.BlockSpec((1, bf), lambda j, i: (0, j)),
        ],
        out_specs=pl.BlockSpec((b, bf), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, f), dtype),
        scratch_shapes=[pltpu.VMEM((b, bf), jnp.float32)],
        interpret=jax.devices()[0].platform != "tpu",  # CPU tests
    )(x2, q, scale.reshape(1, f))
    return out.reshape(*lead, f)


def matmul_maybe_quantized(x, w, dtype):
    """``x @ w`` that accepts either a plain kernel or a QuantizedTensor —
    the single dispatch point model code uses, so new quantized formats
    only need to be handled here."""
    if isinstance(w, (QuantizedTensor, QuantizedTensor4)):
        return w.matmul(x, dtype)
    return x @ w.astype(dtype)


def tree_bytes(params: Any) -> int:
    """Actual bytes a (possibly quantized) params tree occupies."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = int(np.prod(getattr(leaf, "shape", (0,)) or (0,)))
        total += n * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize
    return total


# cataloged at module bottom so every helper above exists; imported lazily
# enough that telemetry's own import graph is settled by now
from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_pack4_program = _wrap_jit(
    "quant/pack4", jax.jit(_pack4, static_argnums=(0, 1)),
    static_argnums=(0, 1), multi_shape=True)
_dequant4_matmul_program = _wrap_jit(
    "quant/dequant_matmul",
    jax.jit(_dequant4_matmul, static_argnums=(0, 1, 2)),
    static_argnums=(0, 1, 2), multi_shape=True)
