"""Fault-tolerance subsystem — the layer that *survives* a dying run.

PR 4's health layer diagnoses stragglers and anomalies; this package is
what keeps the federation making progress when clients crash, brokers
restart, and uploads stall — the standard partial-participation /
unreliable-client setting of production FL (FedAvg partial
participation; Bonawitz et al.'s cross-device system design):

- :mod:`policy` — jittered exponential backoff (:class:`RetryPolicy`)
  and the per-run :class:`ResilienceConfig` read off the args;
- :mod:`dedup` — receiver-side :class:`MessageDeduper` so idempotent
  resends can never double-apply an upload;
- :mod:`liveness` — :class:`PeerLiveness`, heartbeat-driven last-seen
  tracking with eviction windows;
- :mod:`quorum` — :class:`RoundDeadline` (static or straggler-EWMA
  adaptive per-round timer) + :func:`quorum_size`;
- :mod:`chaos` — :class:`ChaosInjector`, a seeded deterministic fault
  injector at the comm boundary (drop/delay/duplicate messages, kill a
  client for a round window, partition the broker), plus
  :class:`ServerKillWindow` (SIGKILL the server itself mid-round),
  exposed as ``fedml_tpu chaos``;
- :mod:`durability` — the write-ahead round journal
  (:class:`RoundJournal`) + replay that lets a killed server re-enter
  the interrupted round with every already-received upload salvaged.

Everything lands in the ``resilience/*`` metric namespace (one segment
after the prefix, entities in labels — lint-enforced) plus
``resilience_event`` records in ``health.jsonl`` and the flight
recorder, which is what ``telemetry doctor``'s connectivity section
reads.
"""
from fedml_tpu.resilience.chaos import (
    AgentKillWindow,
    ChaosInjector,
    CorruptUpdateWindow,
    NaNWindow,
    NodeDrain,
    ServerKillWindow,
    chaos_from_args,
    corrupt_model_payload,
    run_chaos_scenario,
)
from fedml_tpu.resilience.dedup import MessageDeduper
from fedml_tpu.resilience.durability import (
    RoundJournal,
    SalvagedRound,
    journal_from_args,
    salvage_round,
)
from fedml_tpu.resilience.liveness import PeerLiveness
from fedml_tpu.resilience.policy import (
    ResilienceConfig,
    RetryPolicy,
    transient_exceptions,
)
from fedml_tpu.resilience.quorum import (
    RoundDeadline,
    adaptive_deadline_s,
    quorum_size,
)

__all__ = [
    "AgentKillWindow",
    "ChaosInjector",
    "CorruptUpdateWindow",
    "NaNWindow",
    "NodeDrain",
    "ServerKillWindow",
    "chaos_from_args",
    "corrupt_model_payload",
    "run_chaos_scenario",
    "MessageDeduper",
    "RoundJournal",
    "SalvagedRound",
    "journal_from_args",
    "salvage_round",
    "PeerLiveness",
    "ResilienceConfig",
    "RetryPolicy",
    "transient_exceptions",
    "RoundDeadline",
    "adaptive_deadline_s",
    "quorum_size",
]
