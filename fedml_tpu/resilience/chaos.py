"""Seeded deterministic fault injection at the comm boundary.

Every recovery path in this subsystem is only trustworthy if its
failure is *reproducible*. The injector therefore never consults a
wall-clock RNG: probabilistic faults (drop/duplicate/delay) hash
``(seed, rank, receiver, msg_type, per-peer send sequence)`` — in a
deterministic FSM the k-th message a rank sends to a peer is the same
message every run — and windowed faults (kill a client, partition
ranks) trigger on the authoritative *round number*, not on time.

Spec (``args.chaos`` — dict or JSON string; ``args.chaos_seed``)::

    chaos:
      drop: 0.05            # P(drop) per sent message
      duplicate: 0.05       # P(send twice) — dedup's job to absorb
      delay_ms: 20          # hold the send thread this long
      delay: 0.1            # P(delay) per sent message
      kill:                 # crash client 2 for rounds [2, 3)
        rank: 2
        round: 2
        revive_round: 3
      partition:            # or: split arbitrary rank sets
        ranks: [1, 2]
        round: 1
        heal_round: 3

Faults are injected sender-side (deterministic sequence) except the
kill/partition window, which also filters inbound delivery so a "dead"
peer's in-flight messages cannot leak through. ``fedml_tpu chaos`` runs
a full in-proc cross-silo federation under a spec and prints one JSON
summary line (:func:`run_chaos_scenario`).

The **update-corruption family** (:class:`CorruptUpdateWindow`,
:class:`NaNWindow`) targets the MODEL instead of the transport: during
the window, the model payload a rank sends is mutated at the comm seam
— NaN poked into a block/scale, or every scale inflated by a factor —
exactly the damage a sick accelerator or a hostile client would land.
It exists to prove the integrity layer (``fedml_tpu/integrity``)
contains a bad *update* the way the rest of this package contains a bad
*process*::

    chaos:
      corrupt_update:           # list; per-rank windows
        - rank: 2
          round: 1              # [round, until)
          mode: nan             # nan | scale
          factor: 50.0          # scale mode only
"""
from __future__ import annotations

import json
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from fedml_tpu.resilience.policy import _unit_hash

logger = logging.getLogger(__name__)


class ChaosSpec:
    def __init__(self, spec: Optional[Dict] = None, seed: int = 0):
        spec = dict(spec or {})
        self.seed = int(seed)
        self.drop = float(spec.get("drop", 0.0))
        self.duplicate = float(spec.get("duplicate", 0.0))
        self.delay_ms = float(spec.get("delay_ms", 0.0))
        self.delay = float(spec.get("delay", 1.0 if self.delay_ms else 0.0))
        # kill is sugar for a single-rank partition
        partitions: List[Dict] = []
        kill = spec.get("kill")
        if kill:
            partitions.append({
                "ranks": [int(kill["rank"])],
                "round": int(kill.get("round", 0)),
                "heal_round": int(kill.get("revive_round",
                                           kill.get("heal_round", 1 << 30))),
            })
        part = spec.get("partition")
        if part:
            partitions.append({
                "ranks": [int(r) for r in part.get("ranks", [])],
                "round": int(part.get("round", 0)),
                "heal_round": int(part.get("heal_round", 1 << 30)),
            })
        self.partitions = partitions
        # update-corruption windows (a dict is a single window)
        corrupt = spec.get("corrupt_update") or []
        if isinstance(corrupt, dict):
            corrupt = [corrupt]
        self.corrupt_updates = [
            CorruptUpdateWindow(
                rank=int(c["rank"]), round=int(c.get("round", 0)),
                until=c.get("until"), mode=str(c.get("mode", "scale")),
                factor=float(c.get("factor", 50.0)))
            for c in corrupt
        ]

    @property
    def any_probabilistic(self) -> bool:
        return self.drop > 0 or self.duplicate > 0 or (
            self.delay > 0 and self.delay_ms > 0)

    @classmethod
    def parse(cls, raw: Any, seed: int = 0) -> Optional["ChaosSpec"]:
        if raw is None or raw == "" or raw is False:
            return None
        if isinstance(raw, str):
            raw = json.loads(raw)
        if not isinstance(raw, dict):
            raise ValueError(f"chaos spec must be a dict/JSON object, "
                             f"got {type(raw).__name__}")
        return cls(raw, seed=seed)


class CorruptUpdateWindow:
    """Corrupt rank ``rank``'s outbound MODEL payloads for rounds
    ``[round, until)`` (default: one round).

    ``mode='nan'`` pokes NaN into the first float block/scale — the
    classic sick-accelerator artifact; ``mode='scale'`` multiplies every
    scale (or leaf) by ``factor`` — the classic magnitude-poisoning
    attack. ``tier`` targets a node's uplink inside an aggregation tree
    (:class:`~fedml_tpu.hierarchy.runner.TreeRunner` consumes it); None
    means a flat federation rank at the comm-manager seam.
    """

    __slots__ = ("rank", "round", "until", "mode", "factor", "tier")

    def __init__(self, rank: int, round: int, until: Optional[int] = None,
                 mode: str = "scale", factor: float = 50.0,
                 tier: Optional[int] = None):
        if mode not in ("nan", "scale"):
            raise ValueError(
                f"corrupt_update mode must be nan|scale, got {mode!r}")
        self.rank = int(rank)
        self.round = int(round)
        self.until = int(until) if until is not None else self.round + 1
        self.mode = mode
        self.factor = float(factor)
        self.tier = int(tier) if tier is not None else None

    def active_at(self, rank: int, round_idx: Optional[int]) -> bool:
        return (round_idx is not None and self.rank == int(rank)
                and self.round <= int(round_idx) < self.until)


class NaNWindow(CorruptUpdateWindow):
    """Sugar: a :class:`CorruptUpdateWindow` that ships NaN — the
    non-finite-upload chaos the integrity screen exists to catch."""

    def __init__(self, rank: int, round: int, until: Optional[int] = None,
                 tier: Optional[int] = None):
        super().__init__(rank, round, until=until, mode="nan", tier=tier)


def corrupt_model_payload(payload: Any, mode: str,
                          factor: float = 50.0) -> Any:
    """Seeded-deterministic payload corruption (pure function of the
    payload — no RNG at all, so same-seed replays stay bit-identical).

    ``CompressedTree``: nan → the first float leaf's scale-like part
    becomes NaN (multi-part codecs) or its first element does
    (single-part); scale → every float part multiplies by ``factor``.
    Plain pytree: nan → first element of the first float leaf; scale →
    every float leaf multiplies. Always returns mutated HOST arrays —
    the corruption models what arrives off the wire.
    """
    import numpy as np

    from fedml_tpu.compression import CompressedTree
    from fedml_tpu.compression.codecs import _is_float_meta

    def _nan_first(a):
        a = np.array(a, copy=True)
        flat = a.reshape(-1)
        if flat.size:
            flat[0] = np.nan
        return a

    if isinstance(payload, CompressedTree):
        arrays = [[np.asarray(p) for p in parts] for parts in payload.arrays]
        for j, ((dt, _), parts) in enumerate(zip(payload.meta, arrays)):
            if not _is_float_meta(dt):
                continue
            if mode == "nan":
                k = 1 if len(parts) > 1 else 0
                arrays[j][k] = _nan_first(parts[k])
                break
            for k, p in enumerate(parts):
                if np.issubdtype(np.asarray(p).dtype, np.floating):
                    arrays[j][k] = np.asarray(p) * np.float32(factor)
        return CompressedTree(payload.codec, payload.version,
                              payload.is_delta, payload.raw_nbytes,
                              payload.meta, payload.structure, arrays,
                              sa=payload.sa)
    import jax

    leaves, treedef = jax.tree.flatten(payload)
    out = []
    done = False
    for leaf in leaves:
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            if mode == "nan" and not done:
                a = _nan_first(a)
                done = True
            elif mode == "scale":
                a = a * a.dtype.type(factor)
        out.append(a)
    return jax.tree.unflatten(treedef, out)


class ChaosInjector:
    """Per-manager injector consulted by ``FedMLCommManager`` on every
    send and delivery. ``round_provider`` supplies the authoritative
    round for windowed faults (the server's ``args.round_idx``; clients
    fall back to the message's own ``round`` header when present)."""

    def __init__(self, spec: ChaosSpec, rank: int,
                 round_provider: Optional[Callable[[], int]] = None):
        self.spec = spec
        self.rank = int(rank)
        self.round_provider = round_provider
        self._seq: Dict[Tuple[str, int], int] = {}
        from fedml_tpu.telemetry import get_registry

        self._m_injected = lambda action: get_registry().counter(
            "resilience/chaos_injections", labels={"action": action}).inc()

    # -- helpers -----------------------------------------------------------
    def _round_of(self, msg: Any) -> Optional[int]:
        rnd = msg.get("round")
        if rnd is None and self.round_provider is not None:
            try:
                rnd = self.round_provider()
            except Exception:  # pragma: no cover - provider is best-effort
                rnd = None
        try:
            return int(rnd) if rnd is not None else None
        except (TypeError, ValueError):
            return None

    def _partitioned(self, a: int, b: int, rnd: Optional[int]) -> bool:
        if rnd is None:
            return False
        for p in self.spec.partitions:
            if p["round"] <= rnd < p["heal_round"]:
                ranks = set(p["ranks"])
                if (a in ranks) != (b in ranks):  # across the cut
                    return True
        return False

    def _roll(self, kind: str, peer: int, seq: int) -> float:
        return _unit_hash(self.spec.seed, kind, self.rank, peer, seq)

    # -- comm-boundary hooks ----------------------------------------------
    def on_send(self, msg: Any) -> Tuple[int, float]:
        """Decide a send's fate: ``(copies, delay_s)`` — 0 copies = drop,
        2 = duplicate. Deterministic per (seed, peer, send sequence)."""
        peer = int(msg.get_receiver_id())
        seq = self._seq[("send", peer)] = self._seq.get(("send", peer), 0) + 1
        if self._partitioned(self.rank, peer, self._round_of(msg)):
            self._m_injected("partition_drop")
            return 0, 0.0
        copies, delay_s = 1, 0.0
        if self.spec.drop and self._roll("drop", peer, seq) < self.spec.drop:
            self._m_injected("drop")
            return 0, 0.0
        if self.spec.duplicate and (
                self._roll("dup", peer, seq) < self.spec.duplicate):
            self._m_injected("duplicate")
            copies = 2
        if self.spec.delay_ms and (
                self._roll("delay", peer, seq) < self.spec.delay):
            self._m_injected("delay")
            delay_s = self.spec.delay_ms / 1e3
        return copies, delay_s

    def on_deliver(self, msg: Any) -> bool:
        """Inbound filter: False = swallow (the sender was partitioned
        from us when this message would have crossed the cut)."""
        sender = int(msg.get_sender_id())
        if self._partitioned(self.rank, sender, self._round_of(msg)):
            self._m_injected("partition_drop")
            return False
        return True

    def corrupt_payload(self, msg: Any) -> None:
        """Mutate an outbound MODEL payload in place on the message when
        an update-corruption window is live for this rank — called by
        ``FedMLCommManager.send_message`` right before the transport, so
        the corruption lands exactly at the comm seam (after encode,
        before the wire) like real accelerator/DMA damage would."""
        if not self.spec.corrupt_updates:
            return
        from fedml_tpu.core.distributed.message import Message

        payload = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        if payload is None:
            return
        rnd = self._round_of(msg)
        for w in self.spec.corrupt_updates:
            if w.tier is None and w.active_at(self.rank, rnd):
                self._m_injected("corrupt_update")
                payload = corrupt_model_payload(payload, w.mode, w.factor)
                msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)


class ServerKillWindow:
    """Chaos for the process that matters most: SIGKILL the SERVER itself
    mid-round, once it has journaled ``after_uploads`` uploads of round
    ``round`` — the deterministic trigger the kill-the-server recovery
    tests and ``bench.py --recover`` key their MTTR measurement to.

    Spec rides ``args.chaos.kill_server`` or the ``FEDML_CHAOS_KILL_SERVER``
    env var (JSON: ``{"round": 2, "after_uploads": 1}``) — the env form is
    what the supervised restart runner passes to the FIRST server spawn
    only, so the respawned server cannot re-trigger its own death."""

    __slots__ = ("round", "after_uploads")

    def __init__(self, round: int, after_uploads: int = 1):
        self.round = int(round)
        self.after_uploads = max(1, int(after_uploads))

    @classmethod
    def from_args(cls, args: Any) -> Optional["ServerKillWindow"]:
        import os

        raw = os.environ.get("FEDML_CHAOS_KILL_SERVER")
        spec = None
        if raw:
            spec = json.loads(raw)
        else:
            chaos = getattr(args, "chaos", None)
            if isinstance(chaos, str) and chaos:
                chaos = json.loads(chaos)
            if isinstance(chaos, dict):
                spec = chaos.get("kill_server")
        if not spec:
            return None
        return cls(int(spec.get("round", 0)),
                   int(spec.get("after_uploads", 1)))

    def maybe_kill(self, round_idx: int, n_received: int) -> None:
        """SIGKILL this process — no cleanup, no atexit, no flush: the
        honest preemption the journal exists to survive."""
        if int(round_idx) == self.round and (
                int(n_received) >= self.after_uploads):
            import os
            import signal

            logger.warning(
                "chaos: SIGKILLing the server at round %d after %d "
                "upload(s)", round_idx, n_received)
            os.kill(os.getpid(), signal.SIGKILL)


class AgentKillWindow:
    """Scheduler-tier chaos: SIGKILL a NODE AGENT process (not its runs)
    after it has supervised ``after_s`` seconds — then restart it over
    the same workdir. The runs keep executing as orphans of the dead
    agent; the restarted agent must RE-ADOPT them from the persisted run
    table (pid + ``_pid_reused`` check) instead of abandoning them to the
    JobMonitor's FAILED sweep. Consumed by the preempt scenario runner
    (:mod:`fedml_tpu.scheduler.preempt`)."""

    __slots__ = ("node", "after_s", "restart_after_s")

    def __init__(self, node: str, after_s: float = 2.0,
                 restart_after_s: float = 0.5):
        self.node = str(node)
        self.after_s = float(after_s)
        self.restart_after_s = float(restart_after_s)


class NodeDrain:
    """Scheduler-tier chaos: a simulated preemptible-capacity reclaim
    notice — "node ``node`` is being reclaimed, you have ``grace_s``
    seconds". Triggered deterministically on journal evidence (round
    ``round`` has journaled ``after_uploads`` uploads), like
    :class:`ServerKillWindow`, so the preempt happens mid-round every
    run. ``via='master'`` drives :meth:`MasterAgent.drain_node`;
    ``via='reclaim'`` publishes the ``drain_node`` wire verb to the node
    agent itself (the master only sees the PREEMPTED statuses and must
    reschedule from those alone)."""

    __slots__ = ("node", "round", "after_uploads", "grace_s", "via")

    def __init__(self, node: str, round: int = 2, after_uploads: int = 1,
                 grace_s: float = 10.0, via: str = "master"):
        if via not in ("master", "reclaim"):
            raise ValueError(f"NodeDrain via must be master|reclaim, got {via!r}")
        self.node = str(node)
        self.round = int(round)
        self.after_uploads = max(1, int(after_uploads))
        self.grace_s = float(grace_s)
        self.via = via


def chaos_from_args(args: Any, rank: int,
                    round_provider: Optional[Callable[[], int]] = None
                    ) -> Optional[ChaosInjector]:
    """The comm manager's constructor hook: None unless ``args.chaos``
    is configured, so the production hot path stays a None-check."""
    spec = ChaosSpec.parse(getattr(args, "chaos", None),
                           seed=int(getattr(args, "chaos_seed", 0)))
    if spec is None:
        return None
    return ChaosInjector(spec, rank, round_provider=round_provider)


# -- the `fedml_tpu chaos` scenario runner ---------------------------------
def run_chaos_scenario(
    seed: int = 0,
    rounds: int = 5,
    clients: int = 3,
    kill_rank: Optional[int] = None,
    kill_round: int = 2,
    revive_round: Optional[int] = None,
    drop: float = 0.0,
    duplicate: float = 0.0,
    delay_ms: float = 0.0,
    compression: str = "",
    secagg: str = "",
    secagg_clip: float = 0.2,
    round_deadline_s: float = 30.0,
    round_quorum: float = 2.0 / 3.0,
    timeout: float = 300.0,
    corrupt_rank: Optional[int] = None,
    corrupt_round: int = 1,
    corrupt_mode: str = "nan",
    corrupt_factor: float = 50.0,
    integrity: bool = False,
    agg_robust: str = "",
) -> Dict:
    """Run an in-proc cross-silo federation under a chaos spec; return a
    JSON-safe summary (shared by the CLI and the recovery tests).

    ``corrupt_rank`` arms an update-corruption window (NaN or scaled
    payloads from that rank at ``corrupt_round``); pair it with
    ``integrity=True`` (screen + rollback) and/or ``agg_robust`` to
    prove containment — the summary's integrity counters show what was
    screened, quarantined and rolled back."""
    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.cross_silo.run_inproc import run_cross_silo_inproc
    from fedml_tpu.data import load_federated
    from fedml_tpu.telemetry import get_registry

    chaos: Dict[str, Any] = {}
    if kill_rank is not None:
        chaos["kill"] = {
            "rank": int(kill_rank), "round": int(kill_round),
            "revive_round": int(revive_round if revive_round is not None
                                else kill_round + 1)}
    if drop:
        chaos["drop"] = float(drop)
    if duplicate:
        chaos["duplicate"] = float(duplicate)
    if delay_ms:
        chaos["delay_ms"] = float(delay_ms)
    if corrupt_rank is not None:
        chaos["corrupt_update"] = [{
            "rank": int(corrupt_rank), "round": int(corrupt_round),
            "mode": str(corrupt_mode), "factor": float(corrupt_factor)}]
    cfg = {
        "common_args": {"training_type": "cross_silo", "random_seed": seed,
                        "run_id": f"chaos_{seed}"},
        "data_args": {"dataset": "synthetic", "train_size": 60 * clients,
                      "test_size": 60, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 32,
            "learning_rate": 0.3,
            "round_deadline_s": round_deadline_s,
            "round_quorum": round_quorum,
            "chaos": chaos, "chaos_seed": seed,
            **({"compression": compression} if compression else {}),
            **({"integrity": True} if integrity else {}),
            **({"agg_robust": agg_robust} if agg_robust else {}),
            **({"secagg": secagg, "secagg_clip": secagg_clip}
               if secagg else {}),
        },
    }
    args = fedml_tpu.init(load_arguments_from_dict(cfg))
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)
    reg = get_registry()

    def grab(name: str) -> float:
        total = 0.0
        for rec in reg.snapshot():
            if rec.get("name") == name:
                total += float(rec.get("value", rec.get("count", 0)) or 0)
        return total

    counter_names = [
        "resilience/quorum_rounds", "resilience/clients_evicted",
        "resilience/clients_rejoined", "resilience/stale_uploads",
        "resilience/duplicates_dropped", "resilience/chaos_injections"]
    if integrity or corrupt_rank is not None:
        counter_names += [
            "integrity/screened_uploads", "integrity/nonfinite_uploads",
            "integrity/norm_overflows", "integrity/z_outliers",
            "integrity/quarantined", "integrity/rollbacks",
            "integrity/nonfinite_wire"]
    if secagg:
        counter_names += ["secagg/rounds", "secagg/recoveries",
                          "secagg/seeds_revealed",
                          "secagg/recovery_failures"]
    before = {n: grab(n) for n in counter_names}
    t0 = time.time()
    result = run_cross_silo_inproc(args, ds, model, timeout=timeout)
    wall_s = time.time() - t0
    from fedml_tpu.telemetry import flush_run

    # land the registry snapshot in the run dir so `telemetry doctor`'s
    # connectivity section sees the resilience/* counters
    flush_run()
    return {
        "seed": int(seed), "rounds": int(rounds), "clients": int(clients),
        "chaos": chaos, "wall_s": round(wall_s, 3),
        "completed": result is not None,
        "result": {k: (round(float(v), 6) if isinstance(v, (int, float))
                       else v) for k, v in (result or {}).items()},
        "counters": {n.split("/")[1]: grab(n) - v
                     for n, v in before.items()},
    }
