"""Round deadlines + quorum math for partial-participation aggregation.

The server arms one :class:`RoundDeadline` per round after the
broadcast. The timeout is the static config ceiling until straggler
EWMAs exist (PR 4's health tracker), then tightens to
``multiplier x median-EWMA + grace`` — so round 0's compile wall can
never fire the timer early, while a steady-state run reclaims a dead
client's round in a couple of seconds.

A round completes when **all** expected uploads arrived (the legacy
path, deadline cancelled), or the deadline expired **and** at least
``quorum_size(expected, quorum)`` arrived — whichever happens first.
Reweighting for the missing cohort is aggregation-by-construction:
``FedMLAggOperator`` normalizes sample weights over the *received*
subset, so the quorum aggregate is the sample-weighted mean of exactly
the clients that reported.
"""
from __future__ import annotations

import logging
import math
import threading
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)


def quorum_size(expected: int, quorum_frac: float) -> int:
    """Minimum uploads to aggregate: ceil(frac * expected), >= 1."""
    return max(1, min(int(expected),
                      int(math.ceil(float(quorum_frac) * int(expected)))))


def adaptive_deadline_s(latency_ewma_s: Dict, multiplier: float,
                        grace_s: float, min_s: float,
                        static_ceiling_s: float) -> float:
    """Deadline for the next round given per-client latency EWMAs.

    No history -> the static ceiling (never fire early on a cold,
    compile-heavy round). With history -> multiplier x median EWMA +
    grace, clamped to [min_s, static ceiling].
    """
    vals = sorted(float(v) for v in latency_ewma_s.values())
    if not vals or static_ceiling_s <= 0:
        return static_ceiling_s
    mid = len(vals) // 2
    med = vals[mid] if len(vals) % 2 else 0.5 * (vals[mid - 1] + vals[mid])
    return max(min_s, min(static_ceiling_s,
                          multiplier * med + grace_s))


class RoundDeadline:
    """One re-armable timer; firing calls back with the armed round.

    The callback runs on the timer thread — the owner is responsible for
    taking its own round lock and for ignoring fires for rounds that
    already completed (``arm``/``cancel`` make the stale-fire window
    unavoidable; the round tag makes it harmless).
    """

    def __init__(self, on_expire: Callable[[int], None]):
        self._on_expire = on_expire
        self._timer: Optional[threading.Timer] = None
        self._armed_round: Optional[int] = None
        self._lock = threading.Lock()

    def arm(self, round_idx: int, timeout_s: float) -> None:
        if timeout_s <= 0:
            return
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._armed_round = int(round_idx)
            t = threading.Timer(float(timeout_s), self._fire, (int(round_idx),))
            t.daemon = True
            t.start()
            self._timer = t
        logger.debug("round %d deadline armed: %.2fs", round_idx, timeout_s)

    def _fire(self, round_idx: int) -> None:
        with self._lock:
            if self._armed_round != round_idx:
                return  # re-armed for a newer round; stale fire
            self._timer = None
        self._on_expire(round_idx)

    def cancel(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._armed_round = None
