"""Retry policy + resilience configuration.

The backoff schedule is *deterministically* jittered: the jitter for
attempt ``k`` is a hash of ``(seed, key, k)``, not a wall-clock RNG
draw, so a chaos run with a fixed seed replays the exact same retry
timeline — the property every recovery test in this subsystem leans on.
"""
from __future__ import annotations

import hashlib
import logging
import struct
import time
from typing import Any, Callable, Iterator, Optional, Tuple

logger = logging.getLogger(__name__)


def _unit_hash(*parts: Any) -> float:
    """Deterministic uniform-[0,1) from arbitrary parts (stable across
    processes — Python's ``hash()`` is salted, hashlib is not)."""
    h = hashlib.blake2b(
        "|".join(str(p) for p in parts).encode(), digest_size=8
    ).digest()
    (v,) = struct.unpack(">Q", h)
    return v / float(1 << 64)


def transient_exceptions() -> Tuple[type, ...]:
    """Exception types worth a resend: socket-level failures plus each
    optional transport's connectivity error (import-gated)."""
    types: Tuple[type, ...] = (ConnectionError, TimeoutError, OSError)
    try:  # pragma: no cover - environment-dependent
        import grpc

        types = types + (grpc.RpcError,)
    except ImportError:
        pass
    return types


class RetryPolicy:
    """Jittered exponential backoff: ``base * 2^k ± jitter``, capped.

    ``seed``/``key`` pin the jitter sequence; two policies with the same
    (seed, key) produce bit-identical delay schedules.
    """

    def __init__(self, max_attempts: int = 4, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, jitter: float = 0.5,
                 seed: int = 0, key: str = ""):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.key = str(key)

    def delays(self) -> Iterator[float]:
        """The backoff schedule AFTER each failed attempt (one shorter
        than ``max_attempts`` — the last failure is terminal)."""
        for k in range(self.max_attempts - 1):
            raw = min(self.base_delay_s * (2.0 ** k), self.max_delay_s)
            # jitter in [1-j, 1+j), deterministic per (seed, key, attempt)
            factor = 1.0 + self.jitter * (
                2.0 * _unit_hash(self.seed, self.key, k) - 1.0)
            yield max(0.0, raw * factor)

    def call(self, fn: Callable[[], Any],
             retry_on: Optional[Tuple[type, ...]] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             sleep: Callable[[float], None] = time.sleep) -> Any:
        """Run ``fn`` with backoff; re-raises the last failure."""
        retry_on = retry_on or transient_exceptions()
        delays = self.delays()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                attempt += 1
                try:
                    delay = next(delays)
                except StopIteration:
                    raise e  # budget exhausted: surface the LAST failure
                if on_retry is not None:
                    on_retry(attempt, e)
                logger.warning("transient send failure (attempt %d/%d): %r",
                               attempt, self.max_attempts, e)
                sleep(delay)


class ResilienceConfig:
    """The resilience knobs, read once off the flat args namespace.

    Defaults keep pre-subsystem behavior: dedup + bounded send retry are
    always on (both are no-ops on a healthy transport); round deadlines
    and quorum aggregation arm only when ``round_deadline_s`` or
    ``round_quorum`` is configured; client heartbeats only when
    ``heartbeat_interval_s`` > 0.
    """

    def __init__(self, args: Any = None):
        g = lambda k, d: getattr(args, k, d) if args is not None else d
        self.send_max_retries = int(g("send_max_retries", 4))
        self.retry_base_s = float(g("retry_base_s", 0.05))
        self.retry_max_s = float(g("retry_max_s", 2.0))
        self.seed = int(g("random_seed", 0))
        # round deadline: static ceiling; 0/None = wait forever (legacy)
        deadline = g("round_deadline_s", None)
        self.round_deadline_s = float(deadline) if deadline else 0.0
        quorum = g("round_quorum", None)
        self.round_quorum = float(quorum) if quorum is not None else (
            2.0 / 3.0 if self.round_deadline_s else 1.0)
        if not (0.0 < self.round_quorum <= 1.0):
            raise ValueError(
                f"round_quorum must be in (0, 1], got {self.round_quorum}")
        # adaptive deadline: once straggler EWMAs exist, tighten the
        # static ceiling to multiplier x median-EWMA + grace
        self.deadline_adaptive = bool(g("round_deadline_adaptive", True))
        self.deadline_multiplier = float(g("round_deadline_multiplier", 4.0))
        self.deadline_grace_s = float(g("round_deadline_grace_s", 0.5))
        self.deadline_min_s = float(g("round_deadline_min_s", 1.0))
        # below-quorum deadline extensions: how many times the deadline
        # re-arms while uploads are still under quorum before the server
        # aborts the federation loudly (a hang is the one outcome this
        # subsystem exists to prevent)
        self.deadline_extensions = int(g("round_deadline_extensions", 3))
        # client-side periodic heartbeat (0 = only piggybacked ones)
        self.heartbeat_interval_s = float(g("heartbeat_interval_s", 0.0))

    @property
    def deadline_enabled(self) -> bool:
        return self.round_deadline_s > 0.0

    def retry_policy(self, key: str = "") -> RetryPolicy:
        return RetryPolicy(
            max_attempts=self.send_max_retries,
            base_delay_s=self.retry_base_s,
            max_delay_s=self.retry_max_s,
            seed=self.seed, key=key)
