"""Receiver-side message dedup — the other half of idempotent resend.

A sender that retries a publish it *might* have delivered (socket died
mid-``sendall``, broker restarted between accept and fan-out) can only
be safe if the receiver drops the second copy. Every federation message
carries a ``msg_id`` header (stamped by ``FedMLCommManager``); this
bounded LRU set answers "seen it?" in O(1) without growing with run
length.
"""
from __future__ import annotations

import threading
from collections import OrderedDict


class MessageDeduper:
    """Bounded LRU membership set keyed by message id (thread-safe: the
    comm receive thread and transport callback threads both touch it)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = max(1, int(capacity))
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._lock = threading.Lock()
        self.duplicates = 0

    def seen(self, msg_id: str) -> bool:
        """Record ``msg_id``; True if it was already recorded (drop it)."""
        key = str(msg_id)
        with self._lock:
            if key in self._seen:
                self._seen.move_to_end(key)
                self.duplicates += 1
                return True
            self._seen[key] = None
            while len(self._seen) > self.capacity:
                self._seen.popitem(last=False)
            return False

    def __len__(self) -> int:
        with self._lock:
            return len(self._seen)
