"""Heartbeat-driven peer liveness — last-seen tracking with eviction.

Fed from two sources, both free of extra round-trips: every message a
peer sends (``FedMLCommManager.receive_message`` notes the sender) and
the periodic client heartbeat thread when ``heartbeat_interval_s`` is
configured. The server's dropout/rejoin FSM asks two questions: "who
went silent?" (eviction sweep) and "is this sender someone we evicted?"
(rejoin detection).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class PeerLiveness:
    """Last-seen timestamps per peer + an explicit evicted set.

    Eviction is *policy-driven by the caller* (missed round deadline, or
    a silent-window sweep) — this class only keeps the bookkeeping
    consistent under concurrent comm/timer threads.
    """

    def __init__(self, silent_after_s: float = 30.0):
        self.silent_after_s = float(silent_after_s)
        self._lock = threading.Lock()
        self._last_seen: Dict[Any, float] = {}
        self._evicted: Dict[Any, float] = {}  # peer -> evicted-at ts

    def note(self, peer: Any, now: Optional[float] = None) -> None:
        with self._lock:
            self._last_seen[peer] = time.time() if now is None else now

    def last_seen(self, peer: Any) -> Optional[float]:
        with self._lock:
            return self._last_seen.get(peer)

    def silent_peers(self, now: Optional[float] = None) -> List[Any]:
        """Peers seen at least once whose silence exceeds the window and
        that are not already evicted."""
        now = time.time() if now is None else now
        with self._lock:
            return sorted(
                p for p, ts in self._last_seen.items()
                if now - ts > self.silent_after_s and p not in self._evicted)

    # -- eviction / rejoin -------------------------------------------------
    def evict(self, peer: Any) -> bool:
        """Mark evicted; False if it already was."""
        with self._lock:
            if peer in self._evicted:
                return False
            self._evicted[peer] = time.time()
            return True

    def is_evicted(self, peer: Any) -> bool:
        with self._lock:
            return peer in self._evicted

    def readmit(self, peer: Any) -> bool:
        """Clear the evicted mark on reconnect; False if it wasn't set."""
        with self._lock:
            self._last_seen[peer] = time.time()
            return self._evicted.pop(peer, None) is not None

    def evicted(self) -> List[Any]:
        with self._lock:
            return sorted(self._evicted)
