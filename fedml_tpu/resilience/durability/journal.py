"""Write-ahead round journal — crash-anywhere durability for federations.

The orbax round checkpoints (PR 2) make round *boundaries* durable; this
journal makes the *inside* of a round durable. The server appends one
record per round-state transition to an append-only, fsync'd, CRC-framed
file colocated with the checkpoints:

- ``round_open``          — cohort, silo map, seed, codec spec, secagg flag
- ``upload_received``     — client id, msg_id, and the upload payload AS IT
  CROSSED THE WIRE (a delta-encoded :class:`CompressedTree` journals as
  its int8 blocks + scales, so journaling costs ~wire size, not f32 size)
- ``quorum_close``        — the round closed on quorum; missing positions
- ``aggregate_committed`` — the aggregate landed in a durable checkpoint;
  every earlier record is now obsolete and the journal resets
- ``round_rolled_back``   — the integrity layer REJECTED the round
  (``fedml_tpu/integrity``): its uploads must never be salvaged, so the
  record is terminal exactly like a commit

A killed server replays the journal at restart (:func:`salvage_round`) and
re-enters the interrupted round mid-flight: salvaged uploads rehydrate
into the aggregator (those clients never retrain; late duplicate
deliveries drop on the PR 5 msg-id dedup), and only the missing cohort is
re-broadcast. Masked (SecAgg) rounds are journaled but flagged
non-resumable — pairwise masks are irrecoverable without the in-memory
session, so replay aborts them cleanly to the last round boundary.

Framing (all little-endian)::

    record := b"RJ" | len(u32, payload bytes) | crc32(u32, of payload) | payload

``payload`` is :func:`~fedml_tpu.utils.serialization.safe_dumps` of the
record dict (pickle-free; numpy / CompressedTree payloads ride the
existing versioned wire format). A torn tail — short header, short
payload, or CRC mismatch from a crash mid-append — truncates the file at
the last valid record instead of failing the replay.
"""
from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["RoundJournal", "SalvagedRound", "journal_from_args",
           "parse_frames", "salvage_round", "scan_open_round"]

_MAGIC = b"RJ"
_HEADER = struct.Struct("<2sII")  # magic, payload len, crc32


def parse_frames(data: bytes):
    """``(records, valid_end)`` — side-effect-free scan of the RJ frame
    stream. Stops at a torn header/short payload/CRC hole; ``valid_end``
    is the byte offset after the last valid record. Shared by
    :meth:`RoundJournal.records` (which additionally truncates the file
    at ``valid_end``) and read-only spies on a LIVE journal (the
    scheduler's drain trigger) that must never mutate it."""
    from fedml_tpu.utils.serialization import safe_loads

    out: List[Dict] = []
    offset = 0
    valid_end = 0
    while offset + _HEADER.size <= len(data):
        magic, length, crc = _HEADER.unpack_from(data, offset)
        body_start = offset + _HEADER.size
        if magic != _MAGIC or body_start + length > len(data):
            break  # torn header or short payload
        payload = data[body_start:body_start + length]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break  # corrupt record: stop at the last good frame
        try:
            rec = safe_loads(payload)
        except ValueError:
            break
        if not isinstance(rec, dict):
            break
        out.append(rec)
        offset = body_start + length
        valid_end = offset
    return out, valid_end


class RoundJournal:
    """Append-only fsync'd CRC-framed record log.

    Thread-safety: appends land from the comm thread and the deadline
    timer thread; every file mutation happens under ``_lock``.
    ``fsync=False`` drops the per-record fsync (tests / benchmarks that
    measure the seam without it).
    """

    def __init__(self, path: str, fsync: bool = True):
        self.path = os.path.abspath(path)
        self.fsync = bool(fsync)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(self.path, "ab")

    # -- write path --------------------------------------------------------
    def append(self, kind: str, durable: bool = True,
               **fields: Any) -> None:
        """Append one record; with ``durable`` (the default) it returns
        only after the bytes are on disk (write + flush + fdatasync), so
        a crash at ANY later instant replays it.

        ``durable=False`` skips the sync for records whose loss replay
        can re-derive — a ``quorum_close``/``aggregate_committed`` marker
        lost to a crash just re-enters the round with all its (durable)
        uploads and re-closes deterministically. The next durable append
        syncs everything before it anyway (fdatasync is whole-file).
        """
        from fedml_tpu import telemetry
        from fedml_tpu.utils.serialization import safe_dumps

        payload = safe_dumps({"kind": str(kind), **fields})
        frame = _HEADER.pack(_MAGIC, len(payload),
                             zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with self._lock:
            self._fh.write(frame)
            self._fh.flush()
            if self.fsync and durable:
                self._sync()
        reg = telemetry.get_registry()
        reg.counter("resilience/journal_records").inc()
        reg.counter("resilience/journal_bytes").inc(len(frame))

    def _sync(self) -> None:
        # fdatasync where the platform has it: an append-only log needs
        # its DATA durable, not every metadata timestamp
        fileno = self._fh.fileno()
        if hasattr(os, "fdatasync"):
            os.fdatasync(fileno)
        else:  # pragma: no cover - non-POSIX fallback
            os.fsync(fileno)

    def reset(self) -> None:
        """Truncate to empty — called once a round's aggregate is durably
        checkpointed (every record before that boundary is obsolete).

        No sync here on purpose: if the truncate isn't durable at the
        next crash, replay sees the stale records of a round the
        checkpoint already covers and drops them (salvage_round's
        expected-round check) — correctness never depends on it, and the
        hot path saves one fdatasync per round."""
        with self._lock:
            self._fh.truncate(0)
            self._fh.seek(0)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:  # pragma: no cover - double close
                pass

    @property
    def nbytes(self) -> int:
        with self._lock:
            self._fh.flush()
            return os.path.getsize(self.path)

    # -- read path ---------------------------------------------------------
    def records(self) -> List[Dict]:
        """Scan every valid record (oldest first). A torn tail — the
        expected crash artifact of a mid-append kill — is TRUNCATED at
        the last valid record, so the next append continues a clean
        file; corruption inside a record drops it and everything after
        (a CRC hole breaks the frame stream)."""
        from fedml_tpu import telemetry

        with self._lock:
            self._fh.flush()
            with open(self.path, "rb") as f:
                data = f.read()
            out, valid_end = parse_frames(data)
            if valid_end < len(data):
                telemetry.get_registry().counter(
                    "resilience/journal_truncations").inc()
                logger.warning(
                    "round journal %s has a torn tail: truncating %d "
                    "byte(s) after the last valid record",
                    self.path, len(data) - valid_end)
                self._fh.truncate(valid_end)
                self._fh.seek(valid_end)
                self._fh.flush()
                if self.fsync:
                    self._sync()
            return out


class SalvagedRound:
    """What the journal says about the round interrupted by the crash."""

    __slots__ = ("round_idx", "cohort", "silo_index", "uploads", "closed",
                 "missing", "secagg")

    def __init__(self, round_idx: int, cohort: List[int],
                 silo_index: Dict[int, int], uploads: List[Dict],
                 closed: bool, missing: List[int], secagg: bool):
        self.round_idx = int(round_idx)
        self.cohort = [int(c) for c in cohort]
        self.silo_index = {int(k): int(v) for k, v in silo_index.items()}
        self.uploads = list(uploads)          # upload_received records
        self.closed = bool(closed)            # quorum_close was journaled
        self.missing = [int(m) for m in missing]
        self.secagg = bool(secagg)

    @property
    def uploaded_clients(self) -> List[int]:
        return [int(u["client"]) for u in self.uploads]


def scan_open_round(
    records: List[Dict],
    terminal_kinds: tuple = ("aggregate_committed", "round_rolled_back"),
    note_kinds: tuple = ("quorum_close",),
) -> tuple:
    """The ONE journal-replay state machine every consumer shares:
    latest ``round_open`` wins and resets the accumulation, records are
    scoped to the open round, a ``terminal`` kind closes the round
    (nothing left to salvage), ``note`` kinds are collected alongside
    the uploads. Returns ``(open_rec, uploads, notes)`` with
    ``open_rec`` None when no round is open."""
    open_rec: Optional[Dict] = None
    uploads: List[Dict] = []
    notes: List[Dict] = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "round_open":
            open_rec = rec
            uploads = []
            notes = []
        elif open_rec is None:
            continue
        elif int(rec.get("round", -1)) != int(open_rec["round"]):
            continue
        elif kind == "upload_received":
            uploads.append(rec)
        elif kind in note_kinds:
            notes.append(rec)
        elif kind in terminal_kinds:
            open_rec = None  # committed/closed: nothing to salvage
    if open_rec is None:
        return None, [], []
    return open_rec, uploads, notes


def salvage_round(records: List[Dict],
                  expected_round: int) -> Optional[SalvagedRound]:
    """Reconstruct the open (un-committed) round from a journal scan.

    Returns None when the journal holds nothing salvageable: empty, only
    committed rounds, or an open round that is not ``expected_round``
    (e.g. the crash landed between the checkpoint save and the journal
    reset — the checkpoint already covers those records)."""
    open_rec, uploads, notes = scan_open_round(records)
    closes = [n for n in notes if n.get("kind") == "quorum_close"]
    closed = bool(closes)
    missing = ([int(m) for m in closes[-1].get("missing") or []]
               if closes else [])
    if open_rec is None:
        return None
    if int(open_rec["round"]) != int(expected_round):
        logger.warning(
            "journal holds round %s but the checkpoint resumes at round "
            "%s — stale records dropped (crash between checkpoint save "
            "and journal reset)", open_rec["round"], expected_round)
        return None
    return SalvagedRound(
        round_idx=int(open_rec["round"]),
        cohort=open_rec.get("cohort") or [],
        silo_index=open_rec.get("silo_index") or {},
        uploads=uploads,
        closed=closed,
        missing=missing,
        secagg=bool(open_rec.get("secagg")),
    )


def journal_from_args(args: Any,
                      name: str = "server_round") -> Optional[RoundJournal]:
    """The engine constructor hook: a journal colocated with the orbax
    checkpoints when ``durability: true``, else None (the production hot
    path stays a None-check). Durability without a checkpoint_dir is a
    configuration error — mid-round replay is only meaningful relative
    to a durable round boundary."""
    if not bool(getattr(args, "durability", False)):
        return None
    ckpt_dir = getattr(args, "checkpoint_dir", None)
    if not ckpt_dir:
        raise ValueError(
            "durability: true needs checkpoint_dir — the round journal "
            "replays relative to the last durable round boundary")
    return RoundJournal(
        os.path.join(str(ckpt_dir), f"{name}.journal"),
        fsync=bool(getattr(args, "journal_fsync", True)))
