"""Supervised kill-the-server recovery: the chaos the journal exists for.

The PR 5 chaos harness can kill *clients*; this runner kills the process
that matters most. It spawns a REAL cross-silo federation as OS processes
over the in-tree broker, SIGKILLs the server mid-round (the
:class:`~fedml_tpu.resilience.chaos.ServerKillWindow` fires inside the
server after it has journaled ``after_uploads`` uploads), restarts it
with ``resume: true``, and supervises to completion — measuring:

- **MTTR** — wall seconds from the observed kill to the restarted server
  announcing its journal replay (``RESUMED`` marker);
- **salvaged uploads** — how many journaled uploads re-entered the
  aggregator without any client retraining them (each client prints a
  ``TRAINED <round>`` marker per local round, so retrains are visible);
- **bit-identity** — the final-params digest, comparable against an
  uninterrupted run of the same seed (identity codec ⇒ identical).

Exposed as ``fedml_tpu chaos --kill-server`` and measured by
``tools/recover_bench.py`` / ``bench.py --recover``.

This module doubles as the per-rank entrypoint::

    python -m fedml_tpu.resilience.durability.recover \
        --cf cfg.json --rank 0 --role server
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

__all__ = ["run_recover_scenario", "scenario_config"]


def _digest(params: Any) -> str:
    import jax
    import numpy as np

    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree.leaves(params):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def scenario_config(run_id: str, seed: int, rounds: int, clients: int,
                    broker_host: str, broker_port: int, tmp: str,
                    compression: str = "identity",
                    extra_train: Dict = None) -> Dict:
    """The one federation config both the supervisor and the ranks use."""
    return {
        "common_args": {"training_type": "cross_silo", "random_seed": seed,
                        "run_id": run_id,
                        "log_file_dir": os.path.join(tmp, "logs")},
        "data_args": {"dataset": "synthetic", "train_size": 80 * clients,
                      "test_size": 40, "class_num": 4, "feature_dim": 10},
        "model_args": {"model": "lr"},
        "train_args": {
            "federated_optimizer": "FedAvg",
            "comm_backend": "BROKER",
            "broker_host": broker_host, "broker_port": broker_port,
            "object_store_dir": os.path.join(tmp, "store"),
            "client_num_in_total": clients,
            "client_num_per_round": clients,
            "comm_round": rounds, "epochs": 1, "batch_size": 16,
            "learning_rate": 0.3,
            "durability": True, "resume": True,
            "checkpoint_dir": os.path.join(tmp, "ckpts"),
            **({"compression": compression} if compression else {}),
            **(extra_train or {}),
        },
    }


# -- per-rank entrypoint ----------------------------------------------------
def _rank_main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--cf", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--role", choices=("server", "client"), required=True)
    ns = ap.parse_args(argv)

    import fedml_tpu
    from fedml_tpu import models as models_mod
    from fedml_tpu.arguments import load_arguments_from_dict
    from fedml_tpu.data import load_federated

    with open(ns.cf) as f:
        cfg = json.load(f)
    args = load_arguments_from_dict(cfg)
    args.rank = ns.rank
    args = fedml_tpu.init(args)
    ds = load_federated(args)
    model = models_mod.create(args, ds.class_num)

    if ns.role == "server":
        from fedml_tpu.cross_silo.server.server import Server

        server = Server(args, None, ds, model)
        mgr = server.manager
        sal = getattr(mgr, "_salvaged", None)
        if sal is not None:
            # the supervisor's MTTR clock stops here: the restarted
            # server holds its salvaged round state and is accepting
            print("RESUMED " + json.dumps({  # noqa: T201 (rank protocol)
                "round": sal.round_idx,
                "salvaged": len(sal.uploads),
                "clients": sorted(sal.uploaded_clients),
            }), flush=True)
        result = server.run()
        # land the registry snapshot (resilience/journal_* counters) in
        # the run dir so `telemetry doctor` reads the recovery section
        from fedml_tpu.telemetry import flush_run

        flush_run()
        print("DIGEST " + _digest(  # noqa: T201 (rank protocol)
            mgr.aggregator.get_global_model_params()), flush=True)
        print("RESULT " + json.dumps(result, default=str),  # noqa: T201 (rank protocol)
              flush=True)
        return 0

    from fedml_tpu.cross_silo.client.client import Client

    client = Client(args, None, ds, model)
    adapter = client.manager.trainer_dist_adapter
    orig_train = adapter.train

    def train(round_idx, weights):
        # retrain visibility: the recovery gates assert a salvaged
        # client's journaled round is never trained twice
        print(f"TRAINED {int(round_idx)}", flush=True)  # noqa: T201 (rank protocol)
        return orig_train(round_idx, weights)

    adapter.train = train
    client.run()
    print("CLIENT DONE", flush=True)  # noqa: T201 (rank protocol)
    return 0


# -- the supervisor ---------------------------------------------------------
class _Pump(threading.Thread):
    """Stream a child's stdout into a timestamped line list."""

    def __init__(self, proc: subprocess.Popen, name: str):
        super().__init__(name=f"pump-{name}", daemon=True)
        self.proc = proc
        self.lines: List[tuple] = []  # (ts, line)
        self.start()

    def run(self) -> None:
        for raw in self.proc.stdout:
            self.lines.append((time.time(), raw.rstrip("\n")))

    def find(self, prefix: str) -> Optional[tuple]:
        for ts, line in self.lines:
            if line.startswith(prefix):
                return ts, line
        return None


def _spawn(role: str, rank: int, cfg_path: str,
           extra_env: Optional[Dict[str, str]] = None) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m",
         "fedml_tpu.resilience.durability.recover",
         "--cf", cfg_path, "--rank", str(rank), "--role", role],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)


def run_recover_scenario(
    seed: int = 0,
    rounds: int = 5,
    clients: int = 2,
    kill_round: int = 2,
    after_uploads: int = 1,
    compression: str = "identity",
    kill: bool = True,
    max_restarts: int = 2,
    restart_backoff_s: float = 0.25,
    timeout: float = 600.0,
    tmp_dir: Optional[str] = None,
    extra_train: Optional[Dict] = None,
) -> Dict:
    """Run one supervised federation; returns a JSON-safe summary.

    ``kill=False`` runs the uninterrupted baseline of the same seed —
    its ``digest`` is what the killed run must match bit-for-bit under
    the identity codec.

    The supervisor re-arms on ANY abnormal server exit — SIGKILL chaos,
    OOM, unhandled exception — through the job plane's shared
    :class:`~fedml_tpu.scheduler.supervision.RestartTracker` (exponential
    backoff, crash-loop containment), not just the chaos kill: a server
    that dies of a real bug gets the same bounded relaunch budget the
    agent gives its runs, and each relaunch counts under
    ``resilience/restarts``.
    """
    import shutil
    import tempfile

    from fedml_tpu.core.distributed.communication.broker import PubSubBroker

    tmp = tmp_dir or tempfile.mkdtemp(prefix="fedml_recover_")
    owns_tmp = tmp_dir is None
    broker = PubSubBroker(port=0).start()
    host, port = broker.address
    run_id = f"recover_{seed}_{'kill' if kill else 'base'}"
    cfg = scenario_config(run_id, seed, rounds, clients, host, port, tmp,
                          compression, extra_train=extra_train)
    cfg_path = os.path.join(tmp, f"{run_id}.json")
    os.makedirs(tmp, exist_ok=True)
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    t0 = time.time()
    restarts = 0
    mttr_s = None
    resumed: Dict = {}
    server_pumps: List[_Pump] = []
    client_procs = []
    client_pumps = []
    try:
        for r in range(1, clients + 1):
            p = _spawn("client", r, cfg_path)
            client_procs.append(p)
            client_pumps.append(_Pump(p, f"client{r}"))
        kill_env = None
        if kill:
            # the kill spec rides an env var passed to the FIRST server
            # spawn ONLY — the respawn must not re-trigger its own death
            kill_env = {"FEDML_CHAOS_KILL_SERVER": json.dumps(
                {"round": int(kill_round),
                 "after_uploads": int(after_uploads)})}
        from fedml_tpu.scheduler.supervision import (
            RestartPolicy,
            RestartTracker,
            describe_rc,
        )
        from fedml_tpu.telemetry import get_registry

        tracker = RestartTracker(RestartPolicy(
            max_restarts=max_restarts, backoff_s=restart_backoff_s,
            crash_loop_threshold=3, fast_fail_s=10.0, resume=True))
        give_up_reason = None
        server = _spawn("server", 0, cfg_path, extra_env=kill_env)
        pump = _Pump(server, "server")
        server_pumps.append(pump)
        spawned_at = time.time()
        t_kill = None
        deadline = time.time() + timeout
        while True:
            rc = server.poll()
            if rc is None:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"recover scenario did not finish in {timeout}s")
                time.sleep(0.05)
                continue
            if rc == 0:
                break
            # ANY abnormal exit (chaos SIGKILL, OOM, bad config, unhandled
            # exception) goes through the shared supervision policy — the
            # old runner silently never restarted a non-SIGKILL death
            action, detail = tracker.on_exit(rc, time.time() - spawned_at)
            if action != "restart":
                give_up_reason = detail
                break
            if t_kill is None:
                t_kill = time.time()
            restarts += 1
            get_registry().counter("resilience/restarts").inc()
            time.sleep(detail)  # deterministic backoff (no jitter)
            server = _spawn("server", 0, cfg_path)  # no kill env: resume
            pump = _Pump(server, "server")
            server_pumps.append(pump)
            spawned_at = time.time()
        # the pump may still be draining the dead process's pipe buffer —
        # join before reading lines or the tail markers can be missed
        pump.join(timeout=30)
        if server.returncode != 0:
            tail = "\n".join(line for _, line in pump.lines[-30:])
            raise RuntimeError(
                f"server exited {describe_rc(server.returncode)}"
                + (f" ({give_up_reason})" if give_up_reason else "")
                + f":\n{tail}")
        hit = pump.find("RESUMED ")
        if hit is not None:
            ts, line = hit
            resumed = json.loads(line[len("RESUMED "):])
            if t_kill is not None:
                mttr_s = ts - t_kill
        digest_line = pump.find("DIGEST ")
        result_line = pump.find("RESULT ")
        for p in client_procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
        for cp in client_pumps:
            cp.join(timeout=30)  # drain TRAINED markers before counting
        trained: Dict[str, List[int]] = {}
        for r, cp in enumerate(client_pumps, start=1):
            trained[str(r)] = [int(line.split()[1]) for _, line in cp.lines
                               if line.startswith("TRAINED ")]
        return {
            "completed": result_line is not None,
            "seed": int(seed), "rounds": int(rounds),
            "clients": int(clients), "kill": bool(kill),
            "compression": compression,
            "restarts": restarts,
            "mttr_s": round(mttr_s, 3) if mttr_s is not None else None,
            "salvaged_uploads": int(resumed.get("salvaged", 0)),
            "salvaged_clients": resumed.get("clients", []),
            "resumed_round": resumed.get("round"),
            "digest": (digest_line[1][len("DIGEST "):]
                       if digest_line else None),
            "result": (json.loads(result_line[1][len("RESULT "):])
                       if result_line else None),
            "trained": trained,
            "wall_s": round(time.time() - t0, 3),
        }
    finally:
        for p in client_procs + [
                sp.proc for sp in server_pumps]:
            if p.poll() is None:
                p.kill()
        broker.stop()
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(_rank_main(sys.argv[1:]))
