"""Crash-anywhere durability: write-ahead round journal + replay.

- :mod:`journal` — :class:`RoundJournal` (append-only, fsync'd,
  CRC-framed, torn tails truncate), :func:`salvage_round` replay, and the
  :func:`journal_from_args` constructor hook every engine shares.
- :mod:`recover` — the supervised auto-restart runner behind
  ``fedml_tpu chaos --kill-server`` and ``bench.py --recover``: spawns a
  real cross-silo federation as OS processes over a broker, SIGKILLs the
  server mid-round, restarts it with ``resume: true``, and measures MTTR
  + salvaged uploads + bit-identity against an uninterrupted run.

Wired into: the cross-silo sync server (mid-round re-entry), the async
server's FedBuff buffer (buffered contributions survive restart), and
the hierarchy runner's edge aggregators (per-tier recovery). Everything
lands under ``resilience/journal_*`` + ``resilience/restarts`` counters
and the doctor's recovery section.
"""
from fedml_tpu.resilience.durability.journal import (
    RoundJournal,
    SalvagedRound,
    journal_from_args,
    salvage_round,
)
from fedml_tpu.resilience.durability.recover import run_recover_scenario

__all__ = [
    "RoundJournal",
    "SalvagedRound",
    "journal_from_args",
    "run_recover_scenario",
    "salvage_round",
]
