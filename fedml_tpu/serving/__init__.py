"""Serving engine — Predictor ABC, HTTP inference runner, TPU
continuous-batching LLM engine, endpoint monitor.

Parity: reference ``serving/`` (``fedml_predictor.py``,
``fedml_inference_runner.py``) + the deploy plane's inference path
(``model_scheduler/device_model_inference.py``).
"""
from fedml_tpu.serving.inference_runner import FedMLInferenceRunner
from fedml_tpu.serving.live import (
    FederatedServingBridge,
    ModelSlots,
    ServingPublisher,
    SlotLease,
    attach_round_publisher,
)
from fedml_tpu.serving.llm_engine import ContinuousBatchingEngine, TokenStream
from fedml_tpu.serving.llm_predictor import LlamaPredictor
from fedml_tpu.serving.events import serving_event
from fedml_tpu.serving.monitor import EndpointMonitor, ServingSLO
from fedml_tpu.serving.predictor import FedMLPredictor

__all__ = [
    "FedMLPredictor",
    "FedMLInferenceRunner",
    "ContinuousBatchingEngine",
    "TokenStream",
    "LlamaPredictor",
    "EndpointMonitor",
    "ServingSLO",
    "serving_event",
    "ModelSlots",
    "SlotLease",
    "FederatedServingBridge",
    "ServingPublisher",
    "attach_round_publisher",
]
