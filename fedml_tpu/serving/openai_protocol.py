"""OpenAI-compatible serving protocol over the continuous-batching engine.

Parity target: ``serving/templates/hf_template/src/protocol/openai.py`` +
``main_openai.py`` in the reference (the de-facto client contract for an
LLM endpoint): ``/v1/completions`` and ``/v1/chat/completions``, JSON
responses shaped like the OpenAI API, and SSE streaming
(``data: {chunk}\\n\\n`` frames ending with ``data: [DONE]``).

The engine is tokenizer-agnostic; callers plug any ``encode/decode`` pair
(the deployed model's real tokenizer in production). ``ByteTokenizer``
is the dependency-free default: UTF-8 bytes shifted past the special ids,
reversible for any text, usable with any vocab ≥ 259.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

from fedml_tpu.serving.llm_engine import ContinuousBatchingEngine


class SSEStream:
    """Marker the HTTP runner turns into a text/event-stream response."""

    def __init__(self, events: Iterator[Any]):
        self.events = events  # dicts; the runner adds the `data:` framing


class ByteTokenizer:
    """Reversible text↔ids with zero vocabulary assets.

    ids 0..2 are pad/bos/eos; byte b maps to 3 + b.
    """

    bos_id = 1
    eos_id = 2
    vocab_size = 259

    def encode(self, text: str) -> List[int]:
        return [self.bos_id] + [3 + b for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        data = bytes(i - 3 for i in ids if 3 <= i < 259)
        return data.decode("utf-8", errors="replace")


class OpenAIServing:
    """Protocol adapter: OpenAI request dicts → engine calls → OpenAI
    response dicts / SSE chunk streams."""

    def __init__(self, engine: ContinuousBatchingEngine,
                 tokenizer: Any = None, model_name: str = "fedml-tpu-llm",
                 max_tokens_cap: Optional[int] = None):
        self.engine = engine
        self.tok = tokenizer or ByteTokenizer()
        self.model_name = model_name
        self.max_tokens_cap = max_tokens_cap
        engine.start()

    # -- live-plane observability ------------------------------------------
    def _model_tag(self, base: str, round_idx: Optional[int]) -> str:
        """Clients observe hot swaps end-to-end: when the endpoint serves
        a live federation, the model field names the round that actually
        served the request (``fedml-tpu/round-42``). Static deployments
        (no published round) keep the plain model name."""
        if round_idx is None:
            return base
        return f"{base}/round-{round_idx}"

    def models(self) -> Dict:
        """The ``/v1/models`` listing: the live slot's round + codec."""
        slots = getattr(self.engine, "model_slots", None)
        round_idx = slots.live_round if slots is not None else None
        return {
            "object": "list",
            "data": [{
                "id": self._model_tag(self.model_name, round_idx),
                "object": "model",
                "owned_by": "fedml-tpu",
                "round": round_idx,
                "codec": slots.live_codec if slots is not None else None,
            }],
        }

    # -- routing -----------------------------------------------------------
    def handle(self, path: str, request: Dict) -> Any:
        path = path.rstrip("/")
        if path.endswith("/chat/completions"):
            return self.chat_completions(request)
        if path.endswith("/completions"):
            return self.completions(request)
        raise ValueError(f"unknown OpenAI route {path!r}")

    # -- /v1/completions ---------------------------------------------------
    def completions(self, request: Dict) -> Any:
        prompt = request.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        return self._run(str(prompt), request, chat=False)

    # -- /v1/chat/completions ----------------------------------------------
    def chat_completions(self, request: Dict) -> Any:
        messages = request.get("messages") or []
        prompt = self._apply_chat_template(messages)
        return self._run(prompt, request, chat=True)

    @staticmethod
    def _apply_chat_template(messages: List[Dict]) -> str:
        # the hf_template's minimal chat format: role-tagged turns + the
        # assistant cue (a deployed model card can override the tokenizer
        # AND this template together)
        parts = [f"{m.get('role', 'user')}: {m.get('content', '')}"
                 for m in messages]
        parts.append("assistant:")
        return "\n".join(parts)

    # -- core --------------------------------------------------------------
    def _gen_params(self, request: Dict):
        max_tokens = int(request.get("max_tokens", 16))
        if self.max_tokens_cap:
            max_tokens = min(max_tokens, self.max_tokens_cap)
        temperature = float(request.get("temperature", 0.0))
        seed = int(request.get("seed", 0))
        return max_tokens, temperature, seed

    def _run(self, prompt: str, request: Dict, chat: bool) -> Any:
        max_tokens, temperature, seed = self._gen_params(request)
        prompt_ids = self.tok.encode(prompt)
        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        created = int(time.time())
        obj = "chat.completion" if chat else "text_completion"

        base_model = str(request.get("model", self.model_name))
        q = self.engine.submit(prompt_ids, max_tokens, temperature,
                               seed, eos_id=self.tok.eos_id)

        if request.get("stream"):
            def events():
                # the serving round is pinned at admission — wait for the
                # first token before framing any chunk, so every chunk of
                # the stream (preamble included) names the round that is
                # actually generating it
                tok = q.get()
                model = self._model_tag(base_model, q.round_idx)
                if chat:  # role preamble chunk, as the OpenAI API sends
                    yield self._chunk(rid, created, model,
                                      {"role": "assistant"}, None)
                while True:
                    if tok is None or tok == self.tok.eos_id:
                        if chat:
                            yield self._chunk(rid, created, model, {},
                                              "stop")
                        else:
                            yield self._text_chunk(rid, created, model, "",
                                                   "stop")
                        return
                    piece = self.tok.decode([tok])
                    if chat:
                        yield self._chunk(rid, created, model,
                                          {"content": piece}, None)
                    else:
                        yield self._text_chunk(rid, created, model, piece,
                                               None)
                    tok = q.get()

            return SSEStream(events())

        out_ids = []
        while True:
            tok = q.get()
            if tok is None:
                break
            out_ids.append(tok)
        text = self.tok.decode(out_ids)
        finish = "stop" if (out_ids and out_ids[-1] == self.tok.eos_id) \
            else "length"
        usage = {
            "prompt_tokens": len(prompt_ids),
            "completion_tokens": len(out_ids),
            "total_tokens": len(prompt_ids) + len(out_ids),
        }
        if chat:
            choice = {"index": 0, "finish_reason": finish,
                      "message": {"role": "assistant", "content": text}}
        else:
            choice = {"index": 0, "finish_reason": finish, "text": text,
                      "logprobs": None}
        return {"id": rid, "object": obj, "created": created,
                "model": self._model_tag(base_model, q.round_idx),
                "choices": [choice], "usage": usage}

    def _chunk(self, rid, created, model, delta, finish) -> Dict:
        return {"id": rid, "object": "chat.completion.chunk",
                "created": created, "model": model,
                "choices": [{"index": 0, "delta": delta,
                             "finish_reason": finish}]}

    def _text_chunk(self, rid, created, model, text, finish) -> Dict:
        return {"id": rid, "object": "text_completion", "created": created,
                "model": model,
                "choices": [{"index": 0, "text": text,
                             "finish_reason": finish, "logprobs": None}]}
