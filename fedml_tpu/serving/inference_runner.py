"""FedMLInferenceRunner — HTTP wrapper around a FedMLPredictor.

Parity target: ``serving/fedml_inference_runner.py:8-39`` (FastAPI app with
``/predict`` and ``/ready``). This environment ships no ASGI stack, so the
runner is a stdlib ``ThreadingHTTPServer`` with the same endpoint contract:

  POST /predict   body: JSON request → JSON response; if the predictor
                  returns an iterator, the response streams newline-
                  delimited JSON chunks (chunked transfer encoding)
  GET  /ready     {"ready": bool} — liveness for the deploy plane

When constructed with ``openai=OpenAIServing(...)`` the runner also
exposes the OpenAI-compatible surface (parity:
``templates/hf_template/src/main_openai.py``):

  POST /v1/completions        text completion (JSON or SSE stream)
  POST /v1/chat/completions   chat completion (JSON or SSE stream)
  GET  /v1/models             live slot's federation round + codec

Overload shedding: the threading server accepts one OS thread per
connection, but predictor work admission is bounded (``max_inflight``) —
a request that cannot get a work permit within ``queue_wait_s`` is shed
immediately with ``429`` + ``Retry-After``, so a load spike measures the
engine's queue policy instead of piling unbounded threads onto it.

Every request is recorded in the EndpointMonitor (latency, errors,
rejections), which mirrors the reference's endpoint monitoring into the
local metrics sink.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from fedml_tpu.serving.monitor import EndpointMonitor
from fedml_tpu.serving.predictor import FedMLPredictor
from fedml_tpu.utils.bounded_http import AdmissionGate


class FedMLInferenceRunner:
    def __init__(
        self,
        predictor: FedMLPredictor,
        host: str = "127.0.0.1",
        port: int = 0,
        monitor: Optional[EndpointMonitor] = None,
        openai=None,
        max_inflight: int = 64,
        queue_wait_s: float = 0.05,
    ):
        self.predictor = predictor
        self.monitor = monitor or EndpointMonitor()
        self.openai = openai  # OpenAIServing adapter (optional)
        # bounded admission: a permit per in-flight predictor request;
        # acquisition waits at most queue_wait_s before shedding with 429.
        # Queue waits feed the endpoint's serving/queue_wait_ms histogram;
        # sheds land as first-class serving_events with the queue depth.
        self._gate = AdmissionGate(
            max_inflight, queue_wait_s,
            on_wait=self._note_queue_wait, on_shed=self._note_shed)
        runner = self

        class Handler(BaseHTTPRequestHandler):
            # chunked transfer encoding (streaming responses) only exists
            # in HTTP/1.1 — the 1.0 default would make clients treat the
            # raw chunk framing as body bytes
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send_json(self, obj, status: int = 200) -> None:
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.rstrip("/")
                if path in ("", "/ready", "/health", "/healthz"):
                    self._send_json(
                        {"ready": bool(runner.predictor.ready()),
                         **runner.monitor.snapshot()})
                elif path == "/metrics":
                    # live scrape of this endpoint's own registry (the
                    # serving/* instruments the monitor maintains), so
                    # the endpoint is a first-class node of the live
                    # telemetry plane without a collector in between
                    from fedml_tpu.telemetry import get_registry

                    body = get_registry().export_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; "
                                     "version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/metrics.json":
                    # what `fedml_tpu telemetry watch URL` fetches: the
                    # endpoint's own registry in the collector-state shape
                    # (single node, no frame accounting — there is no
                    # collector in between)
                    from fedml_tpu.telemetry import get_registry

                    self._send_json({
                        "job": "serving", "nodes": 1, "frames": 0,
                        "seq_gaps": 0, "nodes_detail": {}, "alerts": [],
                        "metrics": get_registry().snapshot()})
                elif path == "/v1/models" and runner.openai is not None:
                    # clients observe hot swaps end-to-end: the listing
                    # names the live slot's federation round + codec
                    self._send_json(runner.openai.models())
                else:
                    self.send_error(404)

            def do_POST(self):
                path = self.path.rstrip("/")
                is_openai = runner.openai is not None and path.startswith("/v1/")
                if path != "/predict" and not is_openai:
                    self.send_error(404)
                    return
                if not runner._gate.admit(self):
                    # overload: the gate shed the request with 429 +
                    # Retry-After (body drained — keep-alive desync guard)
                    return
                try:
                    self._do_post_admitted(path, is_openai)
                finally:
                    runner._gate.release()

            def _do_post_admitted(self, path, is_openai):
                t0 = time.time()
                ok = True
                # distributed callers (gateway hops, federated serving)
                # propagate their trace via this header; the request span
                # then stitches into the caller's timeline
                from fedml_tpu import telemetry

                ctx = None
                raw_ctx = self.headers.get("X-Fedml-Trace")
                if raw_ctx:
                    try:
                        ctx = telemetry.TraceContext.from_dict(
                            json.loads(raw_ctx))
                    except (ValueError, KeyError):
                        ctx = None
                token = telemetry.activate_context(ctx)
                try:
                    # span() (not begin()): the request span must be the
                    # AMBIENT parent while the predictor runs, so
                    # engine.submit() captures it via current_context()
                    # and the per-request req/* lifecycle tree stitches
                    # underneath this HTTP span in `telemetry trace`
                    with telemetry.get_tracer().span(
                            "serving/request", path=path) as span:
                        ok = self._serve_post(path, is_openai)
                        span.attrs["ok"] = ok
                finally:
                    telemetry.deactivate_context(token)
                    runner.monitor.record_request(time.time() - t0, ok)

            def _serve_post(self, path, is_openai) -> bool:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    request = json.loads(self.rfile.read(n) or b"{}")
                    if is_openai:
                        result = runner.openai.handle(path, request)
                        from fedml_tpu.serving.openai_protocol import SSEStream

                        if isinstance(result, SSEStream):
                            self.send_response(200)
                            self.send_header("Content-Type",
                                             "text/event-stream")
                            self.send_header("Cache-Control", "no-cache")
                            self.send_header("Transfer-Encoding", "chunked")
                            self.end_headers()
                            for event in result.events:
                                data = (
                                    "data: " + json.dumps(event) + "\n\n"
                                ).encode()
                                self.wfile.write(
                                    f"{len(data):x}\r\n".encode() + data
                                    + b"\r\n")
                            done = b"data: [DONE]\n\n"
                            self.wfile.write(
                                f"{len(done):x}\r\n".encode() + done
                                + b"\r\n")
                            self.wfile.write(b"0\r\n\r\n")
                            return True
                    else:
                        result = runner.predictor.predict(request)
                    if hasattr(result, "__next__"):  # streaming
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "application/x-ndjson"
                        )
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        for chunk in result:
                            data = (json.dumps(chunk) + "\n").encode()
                            self.wfile.write(
                                f"{len(data):x}\r\n".encode() + data + b"\r\n"
                            )
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        body = json.dumps(result).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    return True
                except BrokenPipeError:
                    return False
                except Exception as e:  # predictor errors → 500 + message
                    try:
                        body = json.dumps({"error": str(e)}).encode()
                        self.send_response(500)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except BrokenPipeError:
                        pass
                    return False

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- admission-gate observers (best-effort by AdmissionGate contract) --
    def _note_queue_wait(self, wait_s: float) -> None:
        self.monitor.record_queue_wait(wait_s * 1e3)

    def _note_shed(self, depth: int, wait_s: float) -> None:
        self.monitor.record_rejected(queue_depth=depth)
        # the queue wait WAS this request's whole lifecycle: a backdated
        # req/request span (shed=True) makes overload visible in the same
        # trace timeline as the requests that made it through
        from fedml_tpu.telemetry.spans import get_tracer

        tracer = get_tracer()
        now = time.time()
        span = tracer.begin("req/request", shed=True,
                            queue_wait_ms=round(wait_s * 1e3, 3))
        span.started = now - wait_s
        tracer.end(span, ended=now)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "FedMLInferenceRunner":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def run(self) -> None:  # blocking variant (reference runner.run())
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
