"""LlamaPredictor — binds the continuous-batching engine to the serving
contract (the reference's hf_template chatbot predictor,
``serving/templates/hf_template/src/main_entry.py``, with vLLM swapped for
the TPU engine).

Request body:
  {"prompt_tokens": [int, ...],      # pre-tokenized prompt
   "max_new_tokens": 32,
   "temperature": 0.0,
   "seed": 0,
   "stream": false}

Response: {"tokens": [...]} — or, when ``stream`` is true, an iterator of
{"token": t} chunks followed by {"done": true} (the runner turns this into
an ndjson streaming response). Tokenization is deliberately external: the
engine is tokenizer-agnostic, callers bring their own vocab (the reference
similarly delegates to the HF tokenizer of the deployed model).
"""
from __future__ import annotations

from typing import Any

from fedml_tpu.serving.llm_engine import ContinuousBatchingEngine
from fedml_tpu.serving.predictor import FedMLPredictor


class LlamaPredictor(FedMLPredictor):
    def __init__(self, engine: ContinuousBatchingEngine):
        self.engine = engine
        engine.start()

    def ready(self) -> bool:
        return self.engine._thread is not None and self.engine._thread.is_alive()

    def predict(self, request: Any) -> Any:
        prompt = list(map(int, request.get("prompt_tokens", [])))
        if not prompt:
            raise ValueError("prompt_tokens is required and must be non-empty")
        max_new = int(request.get("max_new_tokens", 32))
        temperature = float(request.get("temperature", 0.0))
        seed = int(request.get("seed", 0))
        eos = request.get("eos_id")
        eos = None if eos is None else int(eos)
        if request.get("stream"):
            q = self.engine.submit(prompt, max_new, temperature, seed, eos)

            def stream():
                while True:
                    tok = q.get()
                    if tok is None:
                        yield {"done": True}
                        return
                    yield {"token": tok}

            return stream()
        toks = self.engine.generate(prompt, max_new, temperature, seed, eos)
        return {"tokens": toks}
