"""FedMLPredictor — the serving-side model operator.

Parity target: ``serving/fedml_predictor.py:4`` in the reference (an ABC
with a single ``predict`` the FastAPI runner wraps). Same contract here:
``predict(request)`` takes the decoded JSON request body and returns either
a JSON-serializable response or an *iterator* of JSON-serializable chunks
(streaming generation).
"""
from __future__ import annotations

import abc
from typing import Any


class FedMLPredictor(abc.ABC):
    """Subclass and implement :meth:`predict`; hand to FedMLInferenceRunner."""

    def ready(self) -> bool:
        """Liveness: the runner's /ready endpoint reports this."""
        return True

    @abc.abstractmethod
    def predict(self, request: Any) -> Any:
        """request (decoded JSON) → response (JSON-serializable) or an
        iterator of chunks for a streaming response."""
