"""Federated serving engine — distributed inference over the FSM.

Parity target: ``serving/{client,server}/`` in the reference (cross-silo
manager clones repurposed for inference jobs: the server syncs the model
to workers and drives them; ``serving/server/fedml_server_manager.py:15``,
``serving/client/fedml_client_master_manager.py``). TPU-native re-design:
after the same online-handshake + model sync, the server SCATTERS each
inference batch across live workers (row ranges), every worker runs its
shard through its local jitted apply, and the server GATHERS and
reorders the predictions — data-parallel inference where each worker can
itself be a TPU host/slice.

All managers ride the standard transports (LOCAL for tests, BROKER/GRPC
for deployments), so a federation of inference workers deploys exactly
like a cross-silo training federation.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict

import numpy as np

from fedml_tpu import constants
from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)


class InfMessage:
    MSG_TYPE_CONNECTION_IS_READY = "MSG_TYPE_CONNECTION_IS_READY"
    MSG_TYPE_S2C_CHECK_WORKER_STATUS = "inf.s2c.check_status"
    MSG_TYPE_C2S_WORKER_STATUS = "inf.c2s.status"
    MSG_TYPE_S2C_DEPLOY_MODEL = "inf.s2c.deploy"
    MSG_TYPE_S2C_INFER_REQUEST = "inf.s2c.request"
    MSG_TYPE_C2S_INFER_RESPONSE = "inf.c2s.response"
    MSG_TYPE_S2C_FINISH = "inf.s2c.finish"

    ARG_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
    ARG_REQ_ID = "req_id"
    ARG_SHARD = "shard"
    ARG_X = "x"
    ARG_PREDS = "preds"
    ARG_STATUS = "status"


class InferenceWorkerManager(FedMLCommManager):
    """One inference worker: holds the deployed params, answers shards."""

    def __init__(self, args: Any, apply_fn: Callable, comm=None,
                 rank: int = 1, size: int = 2,
                 backend: str = constants.COMM_BACKEND_LOCAL):
        super().__init__(args, comm, rank, size, backend)
        self.apply_fn = apply_fn
        self.params = None
        self._announced = False

    def register_message_receive_handlers(self) -> None:
        M = InfMessage
        self.register_message_receive_handler(
            M.MSG_TYPE_CONNECTION_IS_READY, self._handle_ready)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_CHECK_WORKER_STATUS, self._handle_check)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_DEPLOY_MODEL, self._handle_deploy)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_INFER_REQUEST, self._handle_request)
        self.register_message_receive_handler(
            M.MSG_TYPE_S2C_FINISH, lambda m: self.finish())

    def _send_status(self, receiver: int) -> None:
        m = Message(InfMessage.MSG_TYPE_C2S_WORKER_STATUS,
                    self.get_sender_id(), receiver)
        m.add_params(InfMessage.ARG_STATUS,
                     "READY" if self.params is not None else "IDLE")
        self.send_message(m)

    def _handle_ready(self, msg: Message) -> None:
        if not self._announced:
            self._announced = True
            self._send_status(0)

    def _handle_check(self, msg: Message) -> None:
        self._send_status(msg.get_sender_id())

    def _handle_deploy(self, msg: Message) -> None:
        self.params = msg.get(InfMessage.ARG_MODEL_PARAMS)
        logger.info("inference worker %d: model deployed", self.rank)
        self._send_status(msg.get_sender_id())

    def _handle_request(self, msg: Message) -> None:
        x = np.asarray(msg.get(InfMessage.ARG_X))
        preds = np.asarray(self.apply_fn(self.params, x))
        reply = Message(InfMessage.MSG_TYPE_C2S_INFER_RESPONSE,
                        self.get_sender_id(), msg.get_sender_id())
        reply.add_params(InfMessage.ARG_REQ_ID, msg.get(InfMessage.ARG_REQ_ID))
        reply.add_params(InfMessage.ARG_SHARD, msg.get(InfMessage.ARG_SHARD))
        reply.add_params(InfMessage.ARG_PREDS, preds)
        self.send_message(reply)


class InferenceServerManager(FedMLCommManager):
    """Deploys the model to workers, scatters batches, gathers preds."""

    def __init__(self, args: Any, params: Any, comm=None,
                 worker_num: int = 1,
                 backend: str = constants.COMM_BACKEND_LOCAL):
        super().__init__(args, comm, 0, worker_num + 1, backend)
        self.params = params
        self.worker_num = worker_num
        self.online: Dict[int, bool] = {}
        self.deployed: Dict[int, bool] = {}
        self.deploy_done = threading.Event()
        self._req_counter = 0
        self._pending: Dict[int, Dict] = {}
        self._lock = threading.Lock()

    def register_message_receive_handlers(self) -> None:
        M = InfMessage
        self.register_message_receive_handler(
            M.MSG_TYPE_CONNECTION_IS_READY, self._handle_ready)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_WORKER_STATUS, self._handle_status)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_INFER_RESPONSE, self._handle_response)

    # -- deployment --------------------------------------------------------
    def _handle_ready(self, msg: Message) -> None:
        for w in range(1, self.worker_num + 1):
            self.send_message(Message(
                InfMessage.MSG_TYPE_S2C_CHECK_WORKER_STATUS,
                self.get_sender_id(), w))

    def _handle_status(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        status = msg.get(InfMessage.ARG_STATUS)
        if status == "READY":
            self.deployed[sender] = True
            if all(self.deployed.get(w) for w in
                   range(1, self.worker_num + 1)):
                self.deploy_done.set()
            return
        if not self.online.get(sender):
            self.online[sender] = True
            m = Message(InfMessage.MSG_TYPE_S2C_DEPLOY_MODEL,
                        self.get_sender_id(), sender)
            m.add_params(InfMessage.ARG_MODEL_PARAMS, self.params)
            self.send_message(m)

    def wait_deployed(self, timeout: float = 60.0) -> None:
        if not self.deploy_done.wait(timeout):
            raise TimeoutError(
                f"only {sorted(self.deployed)} of {self.worker_num} "
                f"inference workers deployed")

    # -- scatter/gather ----------------------------------------------------
    def infer(self, x: np.ndarray, timeout: float = 120.0) -> np.ndarray:
        """Split rows of ``x`` across the workers; return reordered preds."""
        x = np.asarray(x)
        workers = sorted(w for w in self.deployed if self.deployed[w])
        if not workers:
            raise RuntimeError("no deployed inference workers")
        bounds = np.linspace(0, len(x), len(workers) + 1).astype(int)
        shards = [(i, w, slice(bounds[i], bounds[i + 1]))
                  for i, w in enumerate(workers)
                  if bounds[i] != bounds[i + 1]]
        with self._lock:
            self._req_counter += 1
            req_id = self._req_counter
            # n_parts is fixed BEFORE any send: a fast worker must not
            # race the accounting and strand the gather
            entry = {"event": threading.Event(), "parts": {},
                     "n_parts": len(shards)}
            self._pending[req_id] = entry
        for i, w, sl in shards:
            m = Message(InfMessage.MSG_TYPE_S2C_INFER_REQUEST,
                        self.get_sender_id(), w)
            m.add_params(InfMessage.ARG_REQ_ID, req_id)
            m.add_params(InfMessage.ARG_SHARD, i)
            m.add_params(InfMessage.ARG_X, x[sl])
            self.send_message(m)
        if not entry["event"].wait(timeout):
            with self._lock:  # drop the entry or stragglers leak into it
                self._pending.pop(req_id, None)
            raise TimeoutError(
                f"inference request {req_id}: "
                f"{len(entry['parts'])}/{len(shards)} shards returned")
        with self._lock:
            parts = self._pending.pop(req_id)["parts"]
        return np.concatenate([parts[i] for i in sorted(parts)])

    def _handle_response(self, msg: Message) -> None:
        req_id = int(msg.get(InfMessage.ARG_REQ_ID))
        with self._lock:
            entry = self._pending.get(req_id)
            if entry is None:
                return
            entry["parts"][int(msg.get(InfMessage.ARG_SHARD))] = np.asarray(
                msg.get(InfMessage.ARG_PREDS))
            if (entry["n_parts"] and
                    len(entry["parts"]) >= entry["n_parts"]):
                entry["event"].set()

    def shutdown(self) -> None:
        for w in range(1, self.worker_num + 1):
            self.send_message(Message(
                InfMessage.MSG_TYPE_S2C_FINISH, self.get_sender_id(), w))
        self.finish()
