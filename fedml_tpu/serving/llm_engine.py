"""TPU continuous-batching decode engine over the Llama KV cache.

Parity target: the reference serves LLMs by delegating to vLLM/Triton
containers (``model_scheduler/device_model_deployment.py:528``,
``serving/templates/hf_template`` vLLM backend). TPU-native re-design: the
engine owns a fixed pool of *batch slots*, each with its own row in a
shared [B, H_kv, S, D] KV cache, and runs

- a compiled **prefill** program per prompt-length bucket (one slot's rows
  are sliced out, the prompt runs in one forward pass, the filled rows are
  written back), and
- ONE compiled **decode** program for the whole pool — every active slot
  advances one token per step regardless of when its request arrived
  (continuous batching: slots are re-admitted the step after a sequence
  finishes, so the MXU always sees the full batch).

Per-slot cache positions ride the [B]-vector ``cache_len`` support in
``models/llm/llama.py``; sampling happens on host (logits are [B, V]).
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.serving.live.slots import ModelSlots, SlotLease

Pytree = Any


class TokenStream(queue.Queue):
    """The per-request token queue, annotated with the weight generation
    that served it (set at admission; ``None`` until then / for static
    deployments). Yields ints then a final ``None`` like a plain Queue."""

    round_idx: Optional[int] = None


@dataclass
class _Slot:
    request_id: int = -1
    out: Optional[TokenStream] = None
    last_token: int = 0
    generated: int = 0
    max_new: int = 0
    temperature: float = 0.0
    rng: Optional[np.random.Generator] = None
    eos_id: Optional[int] = None
    active: bool = False
    tokens: List[int] = field(default_factory=list)
    lease: Optional[SlotLease] = None
    # request-observability bookkeeping (cheap per-token raw timestamps;
    # spans/histograms are materialized once, at retirement)
    ctx: Any = None  # TraceContext captured at submit (HTTP request span)
    t_submit: float = 0.0  # wall clock at submit (span placement anchor)
    t_submit_mono: float = 0.0
    t_admit_mono: float = 0.0
    t_prefill_mono: float = 0.0
    tok_mono: List[float] = field(default_factory=list)
    stall_ms: float = 0.0  # swap stall attributed to THIS stream
    stall_end_mono: float = 0.0
    stall_round: Optional[int] = None


class ContinuousBatchingEngine:
    """Schedules generation requests onto a fixed slot pool.

    With ``quantize`` set, pass ``quantize_donate=True`` to CONSUME the
    given ``params`` tree — its device buffers are freed as the int8
    twins are built, which is the only way a 7B quantizes within a 16 GB
    chip (the serve CLI and deploy worker do this). Donation is opt-in
    (ADVICE r4): by default the caller's tree stays valid.
    """

    def __init__(
        self,
        model: Any,
        params: Pytree,
        batch_slots: int = 4,
        max_len: int = 256,
        min_prompt_bucket: int = 16,
        eos_id: Optional[int] = None,
        quantize: Optional[str] = None,
        quantize_donate: bool = False,
        quantize_min_size: int = 65536,
        initial_round: Optional[int] = None,
        request_obs: bool = True,
    ):
        self.model = model
        param_transform = None
        min_size = int(quantize_min_size)
        if quantize in ("int8", "int8_w8a8", "w8a8", "int8_pallas", "pallas",
                        "int8_dequant"):
            # int8 (default = fused pallas kernel): halves HBM residency
            # AND the decode weight-bandwidth; int8_w8a8 adds activation
            # quant (int8xint8 MXU dot); int8_dequant is the plain-XLA
            # lowering. Measured tradeoffs: ops/quant.py docstring.
            from fedml_tpu.ops.quant import quantize_params_int8

            if quantize.endswith("w8a8"):
                mode = "w8a8"
            elif quantize.endswith("dequant"):
                mode = "dequant"
            else:
                mode = "pallas"
            # donate: at 7B the bf16 source (13.5 GB) and the int8 twin
            # cannot be resident together — opt in to consume the
            # caller's params tree (class docstring)
            params = quantize_params_int8(params, mode=mode,
                                          min_size=min_size,
                                          donate=quantize_donate)
            # hot-swapped rounds must land in the same int8-resident
            # representation the compiled programs consume; staged trees
            # are fresh device copies, so donating them is always safe
            param_transform = lambda p: quantize_params_int8(  # noqa: E731
                p, mode=mode, min_size=min_size, donate=True)
        elif quantize in ("int4", "nf4"):
            # 4-bit residency (QLoRA packing: two codes per uint8 +
            # per-block absmax scale, ~0.27x of bf16): the dequant is
            # fused into the serving step's trace as an XLA temporary —
            # a full-precision base never materializes. nf4 fits the
            # bell-shaped weight distributions of trained models better
            # at identical wire/HBM cost.
            from fedml_tpu.ops.quant import quantize_params_int4

            fmt = quantize
            params = quantize_params_int4(params, fmt=fmt,
                                          min_size=min_size,
                                          donate=quantize_donate)
            param_transform = lambda p: quantize_params_int4(  # noqa: E731
                p, fmt=fmt, min_size=min_size, donate=True)
        elif quantize is not None:
            raise ValueError(f"unknown quantize mode: {quantize!r}")
        # live-weights indirection: the engine never holds "the params" —
        # every request leases the currently-published slot, so a
        # federation round can hot-swap weights under traffic without
        # touching in-flight generations (see serving/live/slots.py)
        self.model_slots = ModelSlots(params, round_idx=initial_round,
                                      transform=param_transform)
        self._round_in_use = self.model_slots.live_round
        self._last_step_end: Optional[float] = None
        self.n_slots = int(batch_slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        cfg = model.cfg
        shape = (self.n_slots, cfg.num_key_value_heads, self.max_len, cfg.head_dim)
        self.caches = [
            (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
            for _ in range(cfg.num_hidden_layers)
        ]
        self.lengths = np.zeros((self.n_slots,), np.int32)
        self.slots = [_Slot() for _ in range(self.n_slots)]
        self._buckets = []
        b = max(int(min_prompt_bucket), 8)
        while b < self.max_len:
            self._buckets.append(b)
            b *= 2
        self._buckets.append(self.max_len)
        self._requests: "queue.Queue" = queue.Queue()
        self._req_counter = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._prefill_cache: Dict[int, Any] = {}
        # prefills stall every active decode stream (the decode program
        # can't run concurrently with a prefill on one chip): admit at
        # most this many queued requests between decode steps so a burst
        # of arrivals can't starve in-flight generations. Measured in
        # tools/serving_load_bench.py; invariant-tested in
        # tests/test_serving_schedule.py.
        self.admit_per_step = 1
        self.oplog: deque = deque(maxlen=4096)  # ("prefill"|"decode", ...)

        # request observability: per-stream req/* span trees, TTFT/TPOT
        # attribution and engine saturation gauges. The per-token seam is
        # one perf_counter + list append; everything else happens at
        # admission/retirement (serve_bench gates the seam < 2% of a
        # decode step). Toggleable for the bench's on/off A/B.
        self.request_obs = bool(request_obs)
        from fedml_tpu.telemetry.registry import get_registry

        reg = get_registry()
        self._h_ttft = reg.histogram("serving/ttft_ms")
        self._h_tpot = reg.histogram("serving/tpot_ms")
        self._g_tps = reg.gauge("serving/tokens_per_s")
        # saturation accounting. KV names are deliberately allocator-
        # shaped: today "allocated" is the dense [B, H_kv, S, D] pool and
        # "in use" is the filled prefix of each active row; a paged-KV
        # allocator sets the same gauges from its block pool.
        self._g_occupancy = reg.gauge("serving/batch_occupancy")
        self._g_queue_depth = reg.gauge("serving/queue_depth")
        self._g_tokens_in_flight = reg.gauge("serving/tokens_in_flight")
        self._g_kv_used = reg.gauge("serving/kv_bytes_in_use")
        self._g_kv_alloc = reg.gauge("serving/kv_bytes_allocated")
        self._kv_alloc_bytes = float(sum(
            k.nbytes + v.nbytes for k, v in self.caches))
        self._g_kv_alloc.set(self._kv_alloc_bytes)

        model_apply = model.apply

        def prefill_fn(params, caches, tokens, slot, true_len):
            """tokens [1, P] (padded): fill slot's cache rows, return the
            next-token logits at the prompt's true end + its argmax (the
            greedy path never pulls the [V] logits to host)."""
            sub = [
                (
                    jax.lax.dynamic_slice_in_dim(k, slot, 1, axis=0),
                    jax.lax.dynamic_slice_in_dim(v, slot, 1, axis=0),
                    0,
                )
                for k, v in caches
            ]
            p_len = tokens.shape[1]
            logits, new_sub = model_apply(
                params, tokens, positions=jnp.arange(p_len)[None], kv_caches=sub
            )
            caches = [
                (
                    jax.lax.dynamic_update_slice_in_dim(k, nk, slot, axis=0),
                    jax.lax.dynamic_update_slice_in_dim(v, nv, slot, axis=0),
                )
                for (k, v), (nk, nv, _) in zip(caches, new_sub)
            ]
            last = logits[0, true_len - 1]
            return caches, last, jnp.argmax(last).astype(jnp.int32)

        def decode_fn(params, caches, last_tokens, lengths):
            """One token for every slot: [B] → [B, V] next-token logits
            plus the greedy argmax [B]. Greedy streams read back only the
            [B] int32 tokens — pulling the [B, V] logits to host every
            step costs ~1 MB/step of device→host traffic and dominated
            per-token latency in the load bench (PERF_NOTES)."""
            sub = [(k, v, lengths) for k, v in caches]
            logits, new_sub = model_apply(
                params,
                last_tokens[:, None],
                positions=lengths[:, None],
                kv_caches=sub,
            )
            caches = [(k, v) for k, v, _ in new_sub]
            logits = logits[:, 0, :]
            return caches, logits, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def decode_group_fn(params, caches, last_tokens, lengths, idx):
            """Advance only the slot rows in ``idx`` with THESE params —
            the swap-transition path, where in-flight streams pinned to
            the old weight generation and new streams on the fresh one
            must decode against different trees in the same step. Rows
            outside ``idx`` (the other generation's) are untouched."""
            idx_len = lengths[idx]
            sub = [(k[idx], v[idx], idx_len) for k, v in caches]
            logits, new_sub = model_apply(
                params,
                last_tokens[idx][:, None],
                positions=idx_len[:, None],
                kv_caches=sub,
            )
            caches = [
                (k.at[idx].set(nk), v.at[idx].set(nv))
                for (k, v), (nk, nv, _) in zip(caches, new_sub)
            ]
            logits = logits[:, 0, :]
            return caches, logits, jnp.argmax(logits, axis=-1).astype(
                jnp.int32)

        # cataloged (telemetry.profiling): the serving hot programs —
        # decode_group compiles one variant per group size by design
        # (warm_swap_paths pre-builds them all), hence multi_shape
        from fedml_tpu.telemetry.profiling import wrap_jit

        self._prefill = wrap_jit(
            "serve/prefill", jax.jit(prefill_fn, donate_argnums=(1,)),
            multi_shape=True)
        self._decode = wrap_jit(
            "serve/decode", jax.jit(decode_fn, donate_argnums=(1,)))
        self._decode_group = wrap_jit(
            "serve/decode_group",
            jax.jit(decode_group_fn, donate_argnums=(1,)),
            multi_shape=True)

    @property
    def params(self) -> Pytree:
        """The currently-published weight generation (live slot)."""
        return self.model_slots.live_params

    def warm_swap_paths(self) -> None:
        """Pre-compile the grouped (swap-transition) decode program for
        every group size. The first hot swap under traffic otherwise
        JIT-compiles ``_decode_group`` ON the engine thread, freezing
        every in-flight stream for the compile — exactly the stall the
        live plane exists to avoid. Call once at boot, before traffic
        (the serve CLI does when ``--live`` is set; idle-only: it runs
        the program, so active streams would read a garbage token)."""
        if self.active_slots:
            # must fail even under python -O: warming runs the decode
            # program over live KV rows and then resets the caches
            raise RuntimeError("warm_swap_paths needs an idle pool")
        params = self.model_slots.live_params
        last = jnp.zeros((self.n_slots,), jnp.int32)
        for k in range(1, self.n_slots + 1):
            # executing (not AOT-lowering) is what populates the jit
            # cache; caches are donated, so thread the result through
            self.caches, _, _ = self._decode_group(
                params, self.caches, last, jnp.asarray(self.lengths),
                jnp.arange(k, dtype=jnp.int32))
        # the warm steps wrote model output into cache position 0 of the
        # warmed rows; reset so the pool starts from a pristine state
        self.caches = [(jnp.zeros_like(c[0]), jnp.zeros_like(c[1]))
                       for c in self.caches]

    # -- public API -------------------------------------------------------
    def submit(
        self,
        prompt_tokens: List[int],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
    ) -> TokenStream:
        """Enqueue a generation request; returns the token stream queue.

        The queue yields ints (generated token ids) and a final ``None``;
        its ``round_idx`` attribute names the weight generation that
        served it once the request is admitted.
        """
        if len(prompt_tokens) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({len(prompt_tokens)}) + max_new({max_new_tokens}) "
                f"exceeds max_len={self.max_len}"
            )
        out = TokenStream()
        with self._lock:
            self._req_counter += 1
            rid = self._req_counter
        # capture the submitting thread's trace context (the HTTP
        # handler's serving/request span): the req/* span tree built at
        # retirement parents under it, stitching each request into the
        # caller's timeline next to the round swaps
        ctx = None
        if self.request_obs:
            from fedml_tpu.telemetry.spans import current_context

            ctx = current_context()
        self._requests.put(
            (rid, list(map(int, prompt_tokens)), int(max_new_tokens),
             float(temperature), int(seed),
             self.eos_id if eos_id is None else eos_id, out,
             ctx, time.time(), time.perf_counter())
        )
        return out

    def generate(self, prompt_tokens, max_new_tokens=32, temperature=0.0,
                 seed=0, eos_id=None) -> List[int]:
        """Blocking convenience wrapper: returns the full generation."""
        q = self.submit(prompt_tokens, max_new_tokens, temperature, seed, eos_id)
        toks = []
        while True:
            t = q.get()
            if t is None:
                return toks
            toks.append(t)

    def start(self) -> "ContinuousBatchingEngine":
        if self._thread is None:
            self._stopping.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    @property
    def active_slots(self) -> int:
        return sum(s.active for s in self.slots)

    # -- engine loop ------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self.max_len

    def _sample(self, slot: _Slot, logits: np.ndarray) -> int:
        if slot.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / slot.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(slot.rng.choice(len(p), p=p))

    def _note_slot_use(self, lease: SlotLease) -> None:
        """Swap-stall accounting: the first admission on a freshly-
        published slot reports the request-visible pause since the last
        device step (0 when the engine was idle at the flip)."""
        if lease.round_idx is None or lease.round_idx == self._round_in_use:
            return
        prev = self._round_in_use
        self._round_in_use = lease.round_idx
        if prev is None:
            return
        stall_ms = 0.0
        now = time.perf_counter()
        if self.active_slots and self._last_step_end is not None:
            stall_ms = max(0.0, (now - self._last_step_end) * 1e3)
        self.model_slots.record_swap_stall(lease.round_idx, stall_ms)
        if self.request_obs and stall_ms > 0.0:
            # pin the stall to the streams it actually paused: the ones
            # in flight at the transition — their decode group moves to
            # the partitioned gather/scatter program while the fresh
            # round's stream prefills. Each gets a req/stall child span
            # at retirement; the engine-wide histogram above keeps the
            # aggregate view.
            for s in self.slots:
                if s.active:
                    s.stall_ms += stall_ms
                    s.stall_end_mono = now
                    s.stall_round = lease.round_idx

    def _retire(self, slot: _Slot) -> None:
        if self.request_obs and slot.tok_mono:
            self._finish_request_obs(slot)
        slot.out.put(None)
        slot.active = False
        if slot.lease is not None:
            slot.lease.release()
            slot.lease = None
        if self.request_obs:
            self._sample_saturation()

    def _finish_request_obs(self, slot: _Slot) -> None:
        """Materialize one retired stream's observability: TTFT / TPOT /
        tokens-per-s into the registry (+ the endpoint monitor's labeled
        twins) and the req/* span tree — queue wait, prefill, decode,
        and the swap stall pinned to this stream if its decode group
        transitioned mid-flight. Runs once per request, off the
        per-token path; failures never kill the stream."""
        try:
            from fedml_tpu.telemetry.spans import get_tracer

            round_idx = slot.lease.round_idx if slot.lease else None
            first, last = slot.tok_mono[0], slot.tok_mono[-1]
            ttft_ms = (first - slot.t_admit_mono) * 1e3
            tpot_ms = [(b - a) * 1e3
                       for a, b in zip(slot.tok_mono, slot.tok_mono[1:])]
            gen_s = last - slot.t_admit_mono
            tps = len(slot.tok_mono) / gen_s if gen_s > 0 else 0.0
            self._h_ttft.observe(ttft_ms)
            for v in tpot_ms:
                self._h_tpot.observe(v)
            self._g_tps.set(round(tps, 3))
            monitor = getattr(self.model_slots, "monitor", None)
            if monitor is not None:
                monitor.record_stream(ttft_ms, tpot_ms, tps)

            # span tree, backfilled from the raw timestamps (explicit
            # `ended` takes the tracer's wall-math path). Wall placement
            # anchors on the submit wall clock + monotonic deltas, so an
            # NTP step mid-request cannot tear the tree apart.
            tracer = get_tracer()
            t0 = slot.t_submit_mono

            def wall(mono: float) -> float:
                return slot.t_submit + (mono - t0)

            root = tracer.begin(
                "req/request", rid=slot.request_id, round=round_idx,
                tokens=len(slot.tok_mono), ttft_ms=round(ttft_ms, 3),
                tokens_per_s=round(tps, 3))
            if slot.ctx is not None:
                root.trace_id = slot.ctx.trace_id
                root.parent_id = slot.ctx.span_id
            root.started = slot.t_submit

            def child(name: str, m0: float, m1: float, **attrs) -> None:
                sp = tracer.begin(name, **attrs)
                sp.trace_id = root.trace_id
                sp.parent_id = root.span_id
                sp.started = wall(m0)
                tracer.end(sp, ended=wall(m1))

            child("req/queue", t0, slot.t_admit_mono, round=round_idx)
            child("req/prefill", slot.t_admit_mono, slot.t_prefill_mono,
                  round=round_idx)
            child("req/decode", slot.t_prefill_mono, last, round=round_idx,
                  tokens=len(slot.tok_mono))
            if slot.stall_ms > 0.0:
                child("req/stall",
                      slot.stall_end_mono - slot.stall_ms / 1e3,
                      slot.stall_end_mono, round=round_idx,
                      round_to=slot.stall_round,
                      stall_ms=round(slot.stall_ms, 3))
                root.attrs["stall_ms"] = round(slot.stall_ms, 3)
            tracer.end(root, ended=wall(last))
        except Exception:  # noqa: BLE001 - observability must not kill
            pass

    def _sample_saturation(self) -> None:
        """Refresh the engine saturation gauges (occupancy, queue depth,
        tokens in flight, KV bytes). A few gauge sets per decode step —
        well under the profiling-bench noise floor."""
        active_tokens = 0
        n_active = 0
        for i, s in enumerate(self.slots):
            if s.active:
                n_active += 1
                active_tokens += int(self.lengths[i])
        self._g_occupancy.set(n_active / self.n_slots)
        self._g_queue_depth.set(float(self._requests.qsize()))
        self._g_tokens_in_flight.set(float(active_tokens))
        self._g_kv_used.set(self._kv_alloc_bytes * active_tokens
                            / (self.n_slots * self.max_len))

    def _admit(self, req) -> None:
        (rid, prompt, max_new, temp, seed, eos, out,
         ctx, t_wall, t_mono) = req
        slot_idx = next(i for i, s in enumerate(self.slots) if not s.active)
        t_admit_mono = time.perf_counter()  # queue wait ends here
        # pin the request to the CURRENT weight generation: every prefill
        # and decode step of this stream runs against the leased params,
        # so a mid-request hot swap can never mix rounds in one response
        lease = self.model_slots.acquire()
        self._note_slot_use(lease)
        p = self._bucket(len(prompt))
        self.oplog.append(("prefill", p, self.active_slots))
        padded = np.zeros((1, p), np.int32)
        padded[0, : len(prompt)] = prompt
        self.caches, last_logits, greedy = self._prefill(
            lease.params, self.caches, jnp.asarray(padded),
            jnp.int32(slot_idx), jnp.int32(len(prompt)),
        )
        self._last_step_end = time.perf_counter()
        slot = self.slots[slot_idx]
        slot.lease = lease
        out.round_idx = lease.round_idx
        slot.request_id = rid
        slot.out = out
        slot.generated = 0
        slot.max_new = max_new
        slot.temperature = temp
        slot.rng = np.random.default_rng(seed)
        slot.eos_id = eos
        slot.active = True
        slot.tokens = []
        slot.ctx = ctx
        slot.t_submit = t_wall
        slot.t_submit_mono = t_mono
        slot.t_admit_mono = t_admit_mono
        slot.t_prefill_mono = self._last_step_end
        slot.tok_mono = []
        slot.stall_ms = 0.0
        slot.stall_round = None
        self.lengths[slot_idx] = len(prompt)
        if self.request_obs:
            self._sample_saturation()
        if slot.temperature > 0.0:
            self._emit(slot_idx, logits=np.asarray(last_logits))
        else:
            self._emit(slot_idx, tok=int(greedy))

    def _emit(self, slot_idx: int, logits: Optional[np.ndarray] = None,
              tok: Optional[int] = None) -> None:
        """Stream one token for a slot (sampled from ``logits`` or the
        device-computed greedy ``tok``); retire on EOS/max."""
        slot = self.slots[slot_idx]
        if tok is None:
            tok = self._sample(slot, logits)
        slot.last_token = tok
        slot.generated += 1
        slot.tokens.append(tok)
        if self.request_obs:
            # the whole per-token observability seam: one clock read +
            # one append; TTFT/TPOT math runs once, at retirement
            slot.tok_mono.append(time.perf_counter())
        slot.out.put(tok)
        if (slot.eos_id is not None and tok == slot.eos_id) or (
            slot.generated >= slot.max_new
        ):
            self._retire(slot)

    def _loop(self) -> None:
        while not self._stopping.is_set():
            # Admit waiting requests into free slots — but when decodes
            # are in flight, at most admit_per_step per decode step: each
            # prefill stalls every active stream for a full prompt-length
            # forward pass, so draining a burst of arrivals here would
            # starve in-flight generations (measured: ~1 bucketed-prefill
            # stall per admitted request, tools/serving_load_bench.py).
            admitted = 0
            while self.active_slots < self.n_slots:
                if self.active_slots and admitted >= self.admit_per_step:
                    break
                try:
                    # never stall active decodes waiting for new arrivals
                    if self.active_slots:
                        req = self._requests.get_nowait()
                    else:
                        req = self._requests.get(timeout=0.2)
                except queue.Empty:
                    break
                self._admit(req)
                admitted += 1
            if self.active_slots == 0:
                continue
            self.step()

    def step(self) -> None:
        """One batched decode step for every active slot.

        Steady state (every active stream leases the same weight
        generation) runs the ONE whole-pool decode program. During a swap
        transition — old-round streams finishing while new-round streams
        start — the step partitions by generation and advances each group
        with its own params through the gather/scatter decode program, so
        no stream ever sees the other generation's weights.
        """
        active = [i for i, s in enumerate(self.slots) if s.active]
        if not active:
            return
        groups: Dict[int, List[int]] = {}
        leases: Dict[int, SlotLease] = {}
        for i in active:
            lease = self.slots[i].lease
            key = id(lease._slot)
            groups.setdefault(key, []).append(i)
            leases[key] = lease
        last = np.asarray([s.last_token for s in self.slots], np.int32)
        lengths = jnp.asarray(self.lengths)
        greedy_by: Dict[int, int] = {}
        logits_by: Dict[int, np.ndarray] = {}
        if len(groups) == 1:
            (key,) = groups
            self.oplog.append(("decode", len(active), 0))
            self.caches, logits_dev, greedy_dev = self._decode(
                leases[key].params, self.caches, jnp.asarray(last), lengths
            )
            # pull the [B, V] logits only if some active slot samples;
            # greedy streams need just the [B] int32 argmax
            need = any(self.slots[i].temperature > 0.0 for i in active)
            logits = np.asarray(logits_dev) if need else None
            greedy = np.asarray(greedy_dev)
            for i in active:
                greedy_by[i] = int(greedy[i])
                if logits is not None:
                    logits_by[i] = logits[i]
        else:
            # deterministic group order (oldest round first) so two runs
            # of the same swap schedule replay identically
            order = sorted(groups, key=lambda k: (
                -1 if leases[k].round_idx is None else leases[k].round_idx))
            last_dev = jnp.asarray(last)
            for key in order:
                idxs = groups[key]
                self.oplog.append(("decode_part", len(idxs), 0))
                self.caches, logits_dev, greedy_dev = self._decode_group(
                    leases[key].params, self.caches, last_dev, lengths,
                    jnp.asarray(np.asarray(idxs, np.int32)),
                )
                need = any(self.slots[i].temperature > 0.0 for i in idxs)
                logits = np.asarray(logits_dev) if need else None
                greedy = np.asarray(greedy_dev)
                for j, i in enumerate(idxs):
                    greedy_by[i] = int(greedy[j])
                    if logits is not None:
                        logits_by[i] = logits[j]
        for i in active:
            slot = self.slots[i]
            # this step wrote the slot's last token at position lengths[i]
            self.lengths[i] += 1
            if self.lengths[i] >= self.max_len:
                self._retire(slot)
                continue
            if slot.temperature > 0.0:
                self._emit(i, logits=logits_by[i])
            else:
                self._emit(i, tok=greedy_by[i])
        self._last_step_end = time.perf_counter()
        if self.request_obs:
            self._sample_saturation()
