"""Endpoint monitor — liveness + rolling latency/throughput stats.

Parity target: ``model_scheduler/device_model_monitor.py`` (the reference
samples endpoint health and replica metrics into its MLOps plane). Here the
monitor is an in-process stats aggregator the inference runner feeds;
latency rides a telemetry :class:`~fedml_tpu.telemetry.Histogram` so the
snapshot reports real p50/p95/p99 (the old sum/max pair could not answer
"what does a slow request look like"), and the snapshot lands in the JSONL
metrics sink (``core/mlops``) so the scheduler plane can poll endpoint
health without a hosted backend.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict

from fedml_tpu.telemetry import get_registry


class EndpointMonitor:
    def __init__(self, endpoint_id: str = "default", args: Any = None):
        self.endpoint_id = endpoint_id
        self._lock = threading.Lock()
        self._count = 0
        self._errors = 0
        self._lat_sum = 0.0
        self._lat_max = 0.0
        self._started = time.time()
        self._last_request = None
        self._metrics = None
        reg = get_registry()
        labels = {"endpoint": endpoint_id}
        self._hist = reg.histogram("serving/request_ms", labels=labels)
        self._m_requests = reg.counter("serving/requests", labels=labels)
        self._m_errors = reg.counter("serving/errors", labels=labels)
        if args is not None:
            try:
                from fedml_tpu.core.mlops.metrics import MLOpsMetrics

                self._metrics = MLOpsMetrics(args)
            except Exception:
                self._metrics = None

    def record_request(self, latency_s: float, ok: bool = True) -> None:
        with self._lock:
            self._count += 1
            if not ok:
                self._errors += 1
            self._lat_sum += latency_s
            self._lat_max = max(self._lat_max, latency_s)
            self._last_request = time.time()
        self._hist.observe(latency_s * 1e3)
        self._m_requests.inc()
        if not ok:
            self._m_errors.inc()

    def snapshot(self) -> Dict:
        hist = self._hist.snapshot()
        with self._lock:
            n = max(self._count, 1)
            snap = {
                "endpoint_id": self.endpoint_id,
                "requests": self._count,
                "errors": self._errors,
                "latency_avg_ms": round(1e3 * self._lat_sum / n, 3),
                "latency_max_ms": round(1e3 * self._lat_max, 3),
                "latency_p50_ms": round(hist["p50"], 3),
                "latency_p95_ms": round(hist["p95"], 3),
                "latency_p99_ms": round(hist["p99"], 3),
                "uptime_s": round(time.time() - self._started, 1),
                "last_request_ts": self._last_request,
            }
        if self._metrics is not None:
            try:
                self._metrics.log({"endpoint": snap})
            except Exception:
                pass
        return snap
