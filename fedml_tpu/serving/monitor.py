"""Endpoint monitor — liveness + rolling latency/throughput stats.

Parity target: ``model_scheduler/device_model_monitor.py`` (the reference
samples endpoint health and replica metrics into its MLOps plane). Every
stat lives in the telemetry registry — counters for request/error totals,
a histogram for latency (real p50/p95/p99), gauges for uptime and last
activity — so endpoint health appears in ``telemetry report`` /
``telemetry doctor`` and the Prometheus exposition without this object
keeping a private shadow copy; :meth:`snapshot` is just a read of those
instruments, plus the optional JSONL mirror for the scheduler plane.
"""
from __future__ import annotations

import time
from typing import Any, Dict

from fedml_tpu.telemetry import get_registry


class EndpointMonitor:
    def __init__(self, endpoint_id: str = "default", args: Any = None,
                 slo_ms: float = 0.0):
        self.endpoint_id = endpoint_id
        self._started = time.time()
        self._metrics = None
        reg = get_registry()
        labels = {"endpoint": endpoint_id}
        # exported so `telemetry doctor` can judge p99 against the
        # deployment's own latency objective (0 = no SLO declared)
        self._g_slo = reg.gauge("serving/slo_ms", labels=labels)
        # set unconditionally: the gauge is cumulative per process, so a
        # redeploy that declares NO SLO must clear the previous one
        self._g_slo.set(float(slo_ms or 0))
        self._hist = reg.histogram("serving/request_ms", labels=labels)
        self._m_requests = reg.counter("serving/requests", labels=labels)
        self._m_errors = reg.counter("serving/errors", labels=labels)
        self._g_uptime = reg.gauge("serving/uptime_s", labels=labels)
        self._g_uptime.set(0.0)  # fresh deployment starts its clock
        self._g_last_request = reg.gauge("serving/last_request_ts",
                                         labels=labels)
        # live serving plane: which federation round the endpoint serves,
        # how many hot swaps it absorbed, the request-visible stall each
        # one caused, and overload rejections from the bounded queue
        self._g_round = reg.gauge("serving/round_current", labels=labels)
        self._c_swaps = reg.counter("serving/swaps", labels=labels)
        self._h_swap_stall = reg.histogram("serving/swap_stall_ms",
                                           labels=labels)
        self._c_rejected = reg.counter("serving/rejected", labels=labels)
        self._base_rejected = self._c_rejected.value
        self._base_swaps = self._c_swaps.value
        # registry instruments are cumulative per (endpoint, process) —
        # a redeploy reuses them. Baselines make snapshot() report THIS
        # deployment's counts/average, consistent with its uptime.
        # (Percentiles/max stay process-lifetime: histogram buckets
        # cannot be differenced.)
        self._base_requests = self._m_requests.value
        self._base_errors = self._m_errors.value
        base = self._hist.snapshot()
        self._base_lat_sum = base["sum"]
        self._base_lat_count = base["count"]
        if args is not None:
            try:
                from fedml_tpu.core.mlops.metrics import MLOpsMetrics

                self._metrics = MLOpsMetrics(args)
            except Exception:
                self._metrics = None

    def record_request(self, latency_s: float, ok: bool = True) -> None:
        self._hist.observe(latency_s * 1e3)
        self._m_requests.inc()
        if not ok:
            self._m_errors.inc()
        now = time.time()
        self._g_last_request.set(now)
        # keep the exported gauge fresh under traffic even when nothing
        # polls snapshot() — a flush mid-serve must not report uptime 0
        self._g_uptime.set(round(now - self._started, 1))

    def record_swap(self, round_idx: int) -> None:
        """A new federation round was hot-swapped into the endpoint."""
        self._g_round.set(float(round_idx))
        self._c_swaps.inc()

    def record_swap_stall(self, round_idx: int, stall_ms: float) -> None:
        """Request-visible pause the engine attributed to one swap."""
        self._h_swap_stall.observe(float(stall_ms))

    def record_rejected(self) -> None:
        """A request was shed with 429 by the bounded request queue."""
        self._c_rejected.inc()
        self._g_last_request.set(time.time())

    def snapshot(self) -> Dict:
        hist = self._hist.snapshot()
        uptime = round(time.time() - self._started, 1)
        self._g_uptime.set(uptime)
        n = max(hist["count"] - self._base_lat_count, 1)
        last_ts = self._g_last_request.value
        snap = {
            "endpoint_id": self.endpoint_id,
            "requests": int(self._m_requests.value - self._base_requests),
            "errors": int(self._m_errors.value - self._base_errors),
            "latency_avg_ms": round(
                (hist["sum"] - self._base_lat_sum) / n, 3),
            "latency_max_ms": round(hist["max"], 3),
            "latency_p50_ms": round(hist["p50"], 3),
            "latency_p95_ms": round(hist["p95"], 3),
            "latency_p99_ms": round(hist["p99"], 3),
            "uptime_s": uptime,
            "last_request_ts": last_ts or None,
            "rejected": int(self._c_rejected.value - self._base_rejected),
            "swaps": int(self._c_swaps.value - self._base_swaps),
            "round_current": (int(self._g_round.value)
                              if self._c_swaps.value - self._base_swaps
                              else None),
        }
        stall = self._h_swap_stall.snapshot()
        if stall["count"]:
            snap["swap_stall_max_ms"] = round(stall["max"], 3)
        if self._metrics is not None:
            try:
                self._metrics.log({"endpoint": snap})
            except Exception:
                pass
        return snap
