"""Endpoint monitor — liveness + rolling latency/throughput stats.

Parity target: ``model_scheduler/device_model_monitor.py`` (the reference
samples endpoint health and replica metrics into its MLOps plane). Every
stat lives in the telemetry registry — counters for request/error totals,
a histogram for latency (real p50/p95/p99), gauges for uptime and last
activity — so endpoint health appears in ``telemetry report`` /
``telemetry doctor`` and the Prometheus exposition without this object
keeping a private shadow copy; :meth:`snapshot` is just a read of those
instruments, plus the optional JSONL mirror for the scheduler plane.

Request observability (token-latency + SLO): the engine attributes TTFT,
inter-token (TPOT) latency and tokens/s per stream and forwards them
here, where they aggregate per endpoint (``serving/ttft_ms`` /
``serving/tpot_ms`` histograms, ``serving/tokens_per_s`` gauge — the
labeled twins of the engine's unlabeled instruments, same split as
``serving/swap_stall_ms``). A :class:`ServingSLO` spec generalizes the
old scalar ``slo_ms`` into per-objective targets (TTFT / TPOT / e2e +
the objective fraction); every observation is also scored against its
target into cumulative ``serving/slo_total`` / ``serving/slo_breaches``
counters (labeled by objective), which is exactly the shape a
multi-window error-budget burn rate needs — the online doctor differences
them over its windows, and the post-hoc doctor reads the totals.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional, Tuple

from fedml_tpu.telemetry import get_registry


@dataclass
class ServingSLO:
    """Per-endpoint latency objectives: targets in ms (0 = undeclared)
    plus the objective fraction (0.99 → 1% error budget)."""

    ttft_ms: float = 0.0
    tpot_ms: float = 0.0
    e2e_ms: float = 0.0
    objective: float = 0.99

    def targets(self) -> Iterator[Tuple[str, float]]:
        """The declared (objective_name, target_ms) pairs."""
        for kind, target in (("ttft", self.ttft_ms), ("tpot", self.tpot_ms),
                             ("e2e", self.e2e_ms)):
            if target and target > 0:
                yield kind, float(target)

    def __bool__(self) -> bool:
        return any(True for _ in self.targets())

    @classmethod
    def from_spec(cls, path: str) -> "ServingSLO":
        """Load a yaml/json spec: ``{ttft_ms:, tpot_ms:, e2e_ms:,
        objective:}`` (unknown keys ignored, all optional)."""
        import json

        with open(path) as f:
            text = f.read()
        try:
            import yaml

            raw = yaml.safe_load(text) or {}
        except ImportError:  # pragma: no cover - yaml is in-tree
            raw = json.loads(text)
        return cls(
            ttft_ms=float(raw.get("ttft_ms", 0) or 0),
            tpot_ms=float(raw.get("tpot_ms", 0) or 0),
            e2e_ms=float(raw.get("e2e_ms", 0) or 0),
            objective=float(raw.get("objective", 0.99) or 0.99),
        )


class EndpointMonitor:
    def __init__(self, endpoint_id: str = "default", args: Any = None,
                 slo_ms: float = 0.0, slo: Optional[ServingSLO] = None):
        self.endpoint_id = endpoint_id
        # back-compat: the scalar slo_ms is the e2e target of the spec
        self.slo = slo if slo is not None else ServingSLO(
            e2e_ms=float(slo_ms or 0))
        self._started = time.time()
        self._metrics = None
        reg = get_registry()
        labels = {"endpoint": endpoint_id}
        # exported so `telemetry doctor` can judge p99 against the
        # deployment's own latency objective (0 = no SLO declared)
        self._g_slo = reg.gauge("serving/slo_ms", labels=labels)
        # set unconditionally: the gauge is cumulative per process, so a
        # redeploy that declares NO SLO must clear the previous one
        self._g_slo.set(float(self.slo.e2e_ms or 0))
        # the full spec, exported for burn-rate math: per-objective
        # targets + the objective fraction (budget = 1 - objective)
        self._g_slo_objective = reg.gauge("serving/slo_objective",
                                          labels=labels)
        self._g_slo_objective.set(float(self.slo.objective))
        self._slo_counters: Dict[str, Tuple] = {}
        for kind, target in self.slo.targets():
            klabels = {**labels, "objective": kind}
            reg.gauge("serving/slo_target_ms", labels=klabels).set(target)
            self._slo_counters[kind] = (
                target,
                reg.counter("serving/slo_total", labels=klabels),
                reg.counter("serving/slo_breaches", labels=klabels),
            )
        self._hist = reg.histogram("serving/request_ms", labels=labels)
        self._m_requests = reg.counter("serving/requests", labels=labels)
        self._m_errors = reg.counter("serving/errors", labels=labels)
        self._g_uptime = reg.gauge("serving/uptime_s", labels=labels)
        self._g_uptime.set(0.0)  # fresh deployment starts its clock
        self._g_last_request = reg.gauge("serving/last_request_ts",
                                         labels=labels)
        # live serving plane: which federation round the endpoint serves,
        # how many hot swaps it absorbed, the request-visible stall each
        # one caused, and overload rejections from the bounded queue
        self._g_round = reg.gauge("serving/round_current", labels=labels)
        self._c_swaps = reg.counter("serving/swaps", labels=labels)
        self._h_swap_stall = reg.histogram("serving/swap_stall_ms",
                                           labels=labels)
        self._c_rejected = reg.counter("serving/rejected", labels=labels)
        # token-latency attribution (per-endpoint aggregate of the
        # engine's per-stream readings) + admission queue wait
        self._h_ttft = reg.histogram("serving/ttft_ms", labels=labels)
        self._h_tpot = reg.histogram("serving/tpot_ms", labels=labels)
        self._g_tps = reg.gauge("serving/tokens_per_s", labels=labels)
        self._h_queue_wait = reg.histogram("serving/queue_wait_ms",
                                           labels=labels)
        self._base_rejected = self._c_rejected.value
        self._base_swaps = self._c_swaps.value
        # registry instruments are cumulative per (endpoint, process) —
        # a redeploy reuses them. Baselines make snapshot() report THIS
        # deployment's counts/average, consistent with its uptime.
        # (Percentiles/max stay process-lifetime: histogram buckets
        # cannot be differenced.)
        self._base_requests = self._m_requests.value
        self._base_errors = self._m_errors.value
        base = self._hist.snapshot()
        self._base_lat_sum = base["sum"]
        self._base_lat_count = base["count"]
        if args is not None:
            try:
                from fedml_tpu.core.mlops.metrics import MLOpsMetrics

                self._metrics = MLOpsMetrics(args)
            except Exception:
                self._metrics = None

    def _note_slo(self, kind: str, value_ms: float) -> None:
        """Score one observation against its objective's target."""
        entry = self._slo_counters.get(kind)
        if entry is None:
            return
        target, c_total, c_bad = entry
        c_total.inc()
        if value_ms > target:
            c_bad.inc()

    def record_request(self, latency_s: float, ok: bool = True) -> None:
        self._hist.observe(latency_s * 1e3)
        self._m_requests.inc()
        if not ok:
            self._m_errors.inc()
        self._note_slo("e2e", latency_s * 1e3)
        now = time.time()
        self._g_last_request.set(now)
        # keep the exported gauge fresh under traffic even when nothing
        # polls snapshot() — a flush mid-serve must not report uptime 0
        self._g_uptime.set(round(now - self._started, 1))

    def record_stream(self, ttft_ms: float, tpot_ms, tokens_per_s: float,
                      ) -> None:
        """One finished generation stream's token-latency attribution:
        TTFT, its inter-token intervals, and its decode rate (the engine
        computes these once per stream at retirement, off the per-token
        path)."""
        self._h_ttft.observe(float(ttft_ms))
        self._note_slo("ttft", float(ttft_ms))
        for v in tpot_ms:
            self._h_tpot.observe(float(v))
            self._note_slo("tpot", float(v))
        self._g_tps.set(round(float(tokens_per_s), 3))

    def record_queue_wait(self, wait_ms: float) -> None:
        """How long a request queued for an admission permit (shed or
        admitted — the shed ones waited the full timeout)."""
        self._h_queue_wait.observe(float(wait_ms))

    def record_swap(self, round_idx: int) -> None:
        """A new federation round was hot-swapped into the endpoint."""
        self._g_round.set(float(round_idx))
        self._c_swaps.inc()

    def record_swap_stall(self, round_idx: int, stall_ms: float) -> None:
        """Request-visible pause the engine attributed to one swap."""
        self._h_swap_stall.observe(float(stall_ms))

    def record_rejected(self, queue_depth: Optional[int] = None) -> None:
        """A request was shed with 429 by the bounded request queue.

        Beyond the counter bump, the start of a shed burst lands as a
        first-class ``serving_event`` (telemetry.jsonl + flight
        recorder) carrying the admission queue depth at trip time — the
        capacity datum overload triage needs.
        """
        self._c_rejected.inc()
        self._g_last_request.set(time.time())
        from fedml_tpu.serving.events import serving_event

        serving_event(
            "shed_burst", dedupe_key=self.endpoint_id,
            endpoint=self.endpoint_id,
            queue_depth=int(queue_depth or 0),
            rejected_total=int(self._c_rejected.value - self._base_rejected))

    def snapshot(self) -> Dict:
        hist = self._hist.snapshot()
        uptime = round(time.time() - self._started, 1)
        self._g_uptime.set(uptime)
        n = max(hist["count"] - self._base_lat_count, 1)
        last_ts = self._g_last_request.value
        snap = {
            "endpoint_id": self.endpoint_id,
            "requests": int(self._m_requests.value - self._base_requests),
            "errors": int(self._m_errors.value - self._base_errors),
            "latency_avg_ms": round(
                (hist["sum"] - self._base_lat_sum) / n, 3),
            "latency_max_ms": round(hist["max"], 3),
            "latency_p50_ms": round(hist["p50"], 3),
            "latency_p95_ms": round(hist["p95"], 3),
            "latency_p99_ms": round(hist["p99"], 3),
            "uptime_s": uptime,
            "last_request_ts": last_ts or None,
            "rejected": int(self._c_rejected.value - self._base_rejected),
            "swaps": int(self._c_swaps.value - self._base_swaps),
            "round_current": (int(self._g_round.value)
                              if self._c_swaps.value - self._base_swaps
                              else None),
        }
        stall = self._h_swap_stall.snapshot()
        if stall["count"]:
            snap["swap_stall_max_ms"] = round(stall["max"], 3)
        ttft = self._h_ttft.snapshot()
        if ttft["count"]:
            tpot = self._h_tpot.snapshot()
            snap["ttft_p95_ms"] = round(ttft["p95"], 3)
            snap["tpot_p95_ms"] = round(tpot["p95"], 3)
            snap["tokens_per_s"] = self._g_tps.value
        if self._slo_counters:
            slo: Dict[str, Dict] = {}
            for kind, (target, c_total, c_bad) in self._slo_counters.items():
                slo[kind] = {"target_ms": target,
                             "total": int(c_total.value),
                             "breaches": int(c_bad.value)}
            snap["slo"] = slo
        if self._metrics is not None:
            try:
                self._metrics.log({"endpoint": snap})
            except Exception:
                pass
        return snap
