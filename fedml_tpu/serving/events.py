"""serving_event — first-class serving-plane events for crash/doctor triage.

The job plane has ``sched_event`` and secagg has ``_secagg_event``; the
serving plane's overload signals were until now only counter bumps
(``serving/rejected``), invisible in crash context and post-hoc triage.
A ``serving_event`` lands in the three places an operator looks:

- ``<run_dir>/telemetry.jsonl`` — the same stream the online doctor's
  alerts ride, so ``telemetry doctor`` can surface shed bursts next to
  the registry snapshots that explain them;
- the flight-recorder ring — a crash dump shows the overload that
  preceded death;
- a ``serving/events`` counter (labeled by event) on the live plane.

Events are **burst-deduped**: a load spike sheds hundreds of requests in
seconds, and one event per 429 would bury the signal (and the ring).
Within ``burst_window_s`` of the last emission of the same
``(event, dedupe_key)`` the call is a cheap no-op returning False — the
first shed of a burst carries the queue depth at trip time, which is
the capacity datum the fleet item needs.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

__all__ = ["serving_event", "reset_serving_events"]

_last_emit: Dict[Tuple, float] = {}
_lock = threading.Lock()


def serving_event(event: str, dedupe_key: Optional[str] = None,
                  burst_window_s: float = 2.0, **fields: Any) -> bool:
    """Land one serving-plane event everywhere triage looks; returns
    False when the event falls inside the previous burst's window."""
    key = (event, dedupe_key)
    now = time.time()
    with _lock:
        if now - _last_emit.get(key, -1e18) < burst_window_s:
            return False
        _last_emit[key] = now
    get_registry().counter("serving/events", labels={"event": event}).inc()
    flight_recorder.record("serving_event", event=event, **fields)
    from fedml_tpu.telemetry.spans import get_tracer

    run_dir = get_tracer().sink_dir
    if run_dir is not None:
        rec = {"ts": now, "kind": "serving_event", "event": event, **fields}
        try:
            os.makedirs(run_dir, exist_ok=True)
            with open(os.path.join(run_dir, "telemetry.jsonl"), "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        except OSError:  # pragma: no cover - sink dir gone
            pass
    return True


def reset_serving_events() -> None:
    """Forget burst state (test isolation)."""
    with _lock:
        _last_emit.clear()
