"""Double-buffered live model slots — hot-swap weights under traffic.

The serving engine never reads "the params"; it *acquires a lease* on the
currently-published slot. A federation round publishes its new aggregate
by staging it into the shadow slot (``device_put`` + any on-device dequant
happen OFF the request path, on the publisher/bridge thread) and flipping
the live pointer atomically. Requests that acquired the old slot finish on
it — a generation never mixes two rounds' weights — and the old slot's
device buffers are reclaimed only when its lease refcount drains to zero.

Int8-native weight path: a :class:`~fedml_tpu.compression.CompressedTree`
aggregate (the cross-silo server's / tree root's wire format) is staged by
``device_put``-ing the compressed blocks (int8 q + f32 scales — the only
thing that crosses host→device) and decoding INSIDE one jitted on-device
program; a host-side f32 tree is never materialized. When the engine runs
int8-resident weights, its quantize transform chains onto the same staging
program, so the slot holds int8 blocks end to end.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

Pytree = Any


class _Slot:
    """One weight generation: params + round identity + lease refcount."""

    __slots__ = ("params", "round_idx", "codec_spec", "refs", "retired",
                 "reclaimed")

    def __init__(self, params: Pytree, round_idx: Optional[int],
                 codec_spec: Optional[str]):
        self.params = params
        self.round_idx = round_idx
        self.codec_spec = codec_spec
        self.refs = 0
        self.retired = False
        self.reclaimed = threading.Event()


class SlotLease:
    """A refcounted handle on one slot; ``release`` exactly once.

    The params behind a held lease are guaranteed stable: the slot cannot
    be reclaimed (its device buffers freed) until every lease on it is
    released, even after a newer round is published.
    """

    __slots__ = ("_slots", "_slot", "_released")

    def __init__(self, slots: "ModelSlots", slot: _Slot):
        self._slots = slots
        self._slot = slot
        self._released = False

    @property
    def params(self) -> Pytree:
        return self._slot.params

    @property
    def round_idx(self) -> Optional[int]:
        return self._slot.round_idx

    @property
    def codec_spec(self) -> Optional[str]:
        return self._slot.codec_spec

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._slots._release(self._slot)

    def __enter__(self) -> "SlotLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class ModelSlots:
    """Atomic-flip holder for the endpoint's live weights.

    ``round_idx=None`` marks a static deployment (a frozen checkpoint that
    no federation ever updates) — protocol layers then keep their legacy
    model naming. The first :meth:`publish` makes the deployment live.

    ``transform`` (optional) is a device-side post-stage hook — the engine
    installs its int8 weight-quantization here so published aggregates
    land in the same representation its compiled programs consume.
    """

    def __init__(self, params: Pytree, round_idx: Optional[int] = None,
                 codec_spec: Optional[str] = None,
                 transform: Optional[Callable[[Pytree], Pytree]] = None,
                 monitor: Any = None):
        self._lock = threading.Lock()
        self._live = _Slot(params, round_idx, codec_spec)
        self.transform = transform
        self.monitor = monitor
        self.swap_count = 0
        self.stale_drops = 0
        from fedml_tpu.telemetry import get_registry

        self._reg = get_registry()
        self._g_round = self._reg.gauge("serving/round_current")
        self._c_swaps = self._reg.counter("serving/swaps")
        self._c_stale = self._reg.counter("serving/swaps_stale")
        self._c_reclaimed = self._reg.counter("serving/slots_reclaimed")
        self._h_stall = self._reg.histogram("serving/swap_stall_ms")
        self._g_wire = self._reg.gauge("serving/stage_wire_bytes")
        if round_idx is not None:
            self._g_round.set(float(round_idx))

    # -- read side (request path) -----------------------------------------
    @property
    def live_params(self) -> Pytree:
        return self._live.params

    @property
    def live_round(self) -> Optional[int]:
        return self._live.round_idx

    @property
    def live_codec(self) -> Optional[str]:
        return self._live.codec_spec

    def acquire(self) -> SlotLease:
        with self._lock:
            slot = self._live
            slot.refs += 1
            return SlotLease(self, slot)

    def _release(self, slot: _Slot) -> None:
        with self._lock:
            slot.refs -= 1
            reclaim = slot.retired and slot.refs <= 0
        if reclaim:
            self._reclaim(slot)

    def _reclaim(self, slot: _Slot) -> None:
        # dropping the reference is the reclamation: jax frees the old
        # generation's device buffers once nothing points at them
        slot.params = None
        slot.reclaimed.set()
        self._c_reclaimed.inc()

    # -- write side (publisher/bridge thread, off the request path) -------
    def stage(self, payload: Pytree, codec_spec: Optional[str] = None):
        """Move one aggregate onto the device, decode + transform there.

        ``payload`` is either a plain pytree or a ``CompressedTree``; the
        return value is the ready-to-serve params tree (still on device).
        Only the payload's wire representation crosses host→device — for
        int8 that is the blocks + scales, ~4x smaller than the f32 tree
        it decodes to, and the decode itself is one jitted program whose
        output stays on device (no host f32 round trip).
        """
        import jax

        from fedml_tpu import telemetry
        from fedml_tpu.compression import CompressedTree, get_codec
        from fedml_tpu.utils.serialization import tree_nbytes

        t0 = time.perf_counter()
        wire_nbytes = tree_nbytes(payload)
        with telemetry.get_tracer().span(
                "serve/stage",
                codec=(payload.codec if isinstance(payload, CompressedTree)
                       else "plain"), wire_nbytes=wire_nbytes):
            if isinstance(payload, CompressedTree):
                ct = jax.device_put(payload)  # compressed blocks only
                codec = get_codec(codec_spec or ct.codec)
                params = codec.decode(ct)  # one jitted on-device program
            else:
                params = jax.device_put(payload)
                if self.transform is not None:
                    # device_put is a NO-COPY for arrays already on the
                    # target device, and the transform may donate
                    # (delete) its input — an in-process publisher's
                    # retained resync payload / the training loop's own
                    # params must never lose their buffers. Copy exactly
                    # the aliased leaves.
                    import jax.numpy as jnp

                    params = jax.tree.map(
                        lambda orig, staged: (jnp.copy(staged)
                                              if staged is orig
                                              else staged),
                        payload, params)
            if self.transform is not None:
                params = self.transform(params)
        self._g_wire.set(float(wire_nbytes))
        telemetry.sample_now("serve_stage")
        logger.debug("staged %d wire bytes in %.1f ms", wire_nbytes,
                     (time.perf_counter() - t0) * 1e3)
        return params

    def publish(self, params: Pytree, round_idx: int,
                codec_spec: Optional[str] = None) -> bool:
        """Atomic pointer flip to already-staged ``params``.

        Monotonic in ``round_idx``: a duplicate or late-arriving older
        round is dropped (counted), so transport resends and reordering
        can never roll the endpoint backwards.
        """
        from fedml_tpu import telemetry

        round_idx = int(round_idx)
        with telemetry.get_tracer().span("serve/swap", round=round_idx), \
                self._lock:
            cur = self._live.round_idx
            if cur is not None and round_idx <= cur:
                self.stale_drops += 1
                self._c_stale.inc()
                return False
            old = self._live
            self._live = _Slot(params, round_idx, codec_spec)
            old.retired = True
            reclaim_now = old.refs <= 0
            self.swap_count += 1
        if reclaim_now:
            self._reclaim(old)
        self._g_round.set(float(round_idx))
        self._c_swaps.inc()
        if self.monitor is not None:
            try:
                self.monitor.record_swap(round_idx)
            except Exception:  # pragma: no cover - telemetry must not kill
                logger.exception("swap monitor record failed")
        return True

    def publish_payload(self, payload: Pytree, round_idx: int,
                        codec_spec: Optional[str] = None) -> bool:
        """Stage (device_put + on-device decode) then flip — the one call
        the federation bridge makes per round."""
        with self._lock:
            cur = self._live.round_idx
        if cur is not None and int(round_idx) <= cur:
            # don't pay device staging for a round that can't win the flip
            self.stale_drops += 1
            self._c_stale.inc()
            return False
        params = self.stage(payload, codec_spec)
        return self.publish(params, round_idx, codec_spec)

    def record_swap_stall(self, round_idx: int, stall_ms: float) -> None:
        """The serving engine reports the request-visible pause it saw at
        its first step on a freshly-published slot (0 when it was idle)."""
        self._h_stall.observe(float(stall_ms))
        if self.monitor is not None:
            try:
                self.monitor.record_swap_stall(round_idx, stall_ms)
            except Exception:  # pragma: no cover
                logger.exception("swap stall record failed")
