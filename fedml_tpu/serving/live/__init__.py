"""Live serving plane — each federation round hot-swaps into the running
endpoint without dropping requests.

- :mod:`~fedml_tpu.serving.live.slots`: double-buffered
  :class:`ModelSlots` with lease refcounting and an atomic pointer flip;
  compressed aggregates stage via ``device_put`` of the int8 blocks +
  one jitted on-device decode (no host-side f32 tree).
- :mod:`~fedml_tpu.serving.live.bridge`: :class:`ServingPublisher` /
  :class:`FederatedServingBridge` — round-close → swap message → slot
  staging over the standard transports with PR 5 retry/dedup semantics.

See ``docs/serving.md`` ("Live serving plane") for the slot lifecycle.
"""
from fedml_tpu.serving.live.bridge import (
    FederatedServingBridge,
    ServeMessage,
    ServingPublisher,
    attach_round_publisher,
    serve_namespace,
)
from fedml_tpu.serving.live.slots import ModelSlots, SlotLease

__all__ = [
    "ModelSlots",
    "SlotLease",
    "FederatedServingBridge",
    "ServingPublisher",
    "ServeMessage",
    "attach_round_publisher",
    "serve_namespace",
]
