"""Federated serving bridge — round-close → live endpoint hot swap.

Two small FSMs over the standard federation transports (LOCAL for tests
and single-host, BROKER/GRPC/TRPC for deployments), riding the PR 5
resilience layer for free (msg-id stamping + receiver dedup, jittered
retry, auto-reconnect):

- :class:`ServingPublisher` (rank 0) lives next to the training plane. It
  is attached to the cross-silo server (``attach_round_publisher``) or
  the hierarchy :class:`~fedml_tpu.hierarchy.TreeRunner` (``on_round=``)
  and, each time a global round closes, encodes the aggregate ONCE with
  the serving codec and sends a ``serve.p2s.swap`` message.
- :class:`FederatedServingBridge` (rank 1) lives in the serving process.
  Each swap message is staged into the endpoint's shadow
  :class:`~fedml_tpu.serving.live.ModelSlots` slot and published with an
  atomic flip.

Loss semantics: every swap message carries the FULL aggregate for its
round (never a delta against the previous swap), so a lost round r is
simply superseded by r+1 — the endpoint can lag but can never wedge on a
stale round. The bridge additionally announces itself (``serve.s2p.hello``)
on startup and once per failed swap, and the publisher answers with a
fresh copy of its latest round; duplicates are dropped by the comm-layer
deduper and by the slots' round monotonicity.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from fedml_tpu import constants
from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.message import Message
from fedml_tpu.serving.live.slots import ModelSlots

logger = logging.getLogger(__name__)

Pytree = Any


class ServeMessage:
    MSG_TYPE_CONNECTION_IS_READY = "MSG_TYPE_CONNECTION_IS_READY"
    MSG_TYPE_P2S_SWAP = "serve.p2s.swap"
    MSG_TYPE_S2P_HELLO = "serve.s2p.hello"
    MSG_TYPE_S2P_TELEMETRY = "serve.s2p.telemetry"
    MSG_TYPE_P2S_FINISH = "serve.p2s.finish"

    ARG_MODEL_PARAMS = Message.MSG_ARG_KEY_MODEL_PARAMS
    ARG_ROUND = "round"
    ARG_CODEC = Message.MSG_ARG_KEY_COMPRESSION


def serve_namespace(run_id: str) -> str:
    """The serving plane's comm namespace for a federation ``run_id``."""
    return f"{run_id}/serve"


class _BridgeArgs:
    """Serving-plane comm namespace derived from the caller's args.

    The publisher/bridge pair must NOT share the training federation's
    (run_id, rank) channels: the publisher is rank 0, so it would share
    the real server's LOCAL inbox (messages stolen nondeterministically),
    its broker topics (every client upload fanned out to the serving
    plane and every full-model swap to training client 1), and its
    GRPC/TRPC port (bind error). The pair talks on ``<run_id>/serve``
    with its own port block, inheriting every other transport/resilience
    setting from the caller's args.
    """

    PORT_OFFSET = 32  # past any federation's rank range on this host

    def __init__(self, args: Any, run_id: Optional[str]):
        if args is not None:
            try:
                self.__dict__.update(vars(args))
            except TypeError:  # args without __dict__ (mocks, slots)
                pass
        base = run_id if run_id is not None else str(
            getattr(args, "run_id", "serve"))
        self.run_id = serve_namespace(str(base))
        self.grpc_base_port = int(
            getattr(args, "grpc_base_port", 8890)) + self.PORT_OFFSET
        self.trpc_master_port = int(
            getattr(args, "trpc_master_port", 29500)) + self.PORT_OFFSET


class ServingPublisher(FedMLCommManager):
    """Training-side half: publish each closed round to the endpoint.

    ``codec`` names the wire codec for swap payloads (e.g. ``int8``);
    upload-only codecs (topk sparsifies a FULL model into a different
    model) and ``None`` ship the aggregate plain.
    """

    def __init__(self, args: Any = None, run_id: Optional[str] = None,
                 codec: Optional[str] = None, seed: int = 0,
                 backend: str = constants.COMM_BACKEND_LOCAL):
        super().__init__(_BridgeArgs(args, run_id), None, 0, 2, backend)
        from fedml_tpu.compression import get_codec

        self._codec = get_codec(codec) if isinstance(codec, str) else codec
        if self._codec is not None and not self._codec.broadcast_safe:
            logger.warning(
                "serving codec %s is upload-only; swap payloads ship plain",
                self._codec.spec)
            self._codec = None
        self.seed = int(seed)
        self._latest_lock = threading.Lock()
        self._latest = None  # (round_idx, payload, spec)
        from fedml_tpu.telemetry import get_registry

        self._g_published = get_registry().gauge("serving/round_published")

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            ServeMessage.MSG_TYPE_S2P_HELLO, self._handle_hello)
        # the endpoint's dedicated metric frames: the frame itself is
        # merged into this process's LivePlane by the comm receive seam
        # before dispatch, so the handler has nothing left to do — it
        # exists to keep the frame carrier off the no-handler warning path
        self.register_message_receive_handler(
            ServeMessage.MSG_TYPE_S2P_TELEMETRY, lambda m: None)

    def publish(self, round_idx: int, global_params: Pytree) -> None:
        """Encode once, remember as latest, send to the serving rank."""
        from fedml_tpu import telemetry
        from fedml_tpu.compression import derive_key

        round_idx = int(round_idx)
        with telemetry.get_tracer().span("serve/publish", round=round_idx):
            if self._codec is not None:
                payload = self._codec.encode(
                    global_params,
                    key=derive_key(self.seed, round_idx, 0))
                spec = self._codec.spec
            else:
                payload, spec = global_params, None
        with self._latest_lock:
            self._latest = (round_idx, payload, spec)
        self._g_published.set(float(round_idx))
        self._send_swap(round_idx, payload, spec)

    def _send_swap(self, round_idx: int, payload, spec) -> None:
        m = Message(ServeMessage.MSG_TYPE_P2S_SWAP, self.get_sender_id(), 1)
        m.add_params(ServeMessage.ARG_MODEL_PARAMS, payload)
        m.add_params(ServeMessage.ARG_ROUND, round_idx)
        if spec is not None:
            m.add_params(ServeMessage.ARG_CODEC, spec)
        self.send_message(m)

    def _handle_hello(self, msg: Message) -> None:
        """A (re)connecting endpoint asks for the latest round: resend it.
        The bridge's slots drop it if it already landed — idempotent."""
        with self._latest_lock:
            latest = self._latest
        if latest is not None:
            self._send_swap(*latest)

    def finish(self) -> None:
        try:
            self.send_message(Message(ServeMessage.MSG_TYPE_P2S_FINISH,
                                      self.get_sender_id(), 1))
        except Exception:  # pragma: no cover - peer may already be gone
            logger.debug("serving finish notify failed", exc_info=True)
        super().finish()


class FederatedServingBridge(FedMLCommManager):
    """Serving-side half: swap messages → slot staging → atomic flip."""

    def __init__(self, slots: ModelSlots, args: Any = None,
                 run_id: Optional[str] = None,
                 backend: str = constants.COMM_BACKEND_LOCAL):
        super().__init__(_BridgeArgs(args, run_id), None, 1, 2, backend)
        self.slots = slots
        self.round_published: Optional[int] = None
        self.swap_errors = 0
        self._failed_rounds: set = set()
        from fedml_tpu.telemetry import get_registry

        self._g_published = get_registry().gauge("serving/round_published")
        # live telemetry: the serving process streams its serving/*
        # instruments back to the training-side collector. An endpoint has
        # no per-round traffic to piggyback on (it SENDS only a boot-time
        # hello plus one resync per failed swap), so piggybacking would
        # freeze serving/round_current at the collector and trip a false
        # stale_serving_round alert — this is the dedicated-carrier case:
        # the streamer's off-thread loop delivers its own low-frequency
        # frame messages (delta-filtered, so an idle endpoint sends
        # nothing). Own-process only, like the cross-silo client: never on
        # the shared-registry LOCAL path.
        self._telemetry_streamer = None
        self._span_streamer = None
        if (bool(getattr(args, "live_telemetry", False))
                and str(backend).upper() != constants.COMM_BACKEND_LOCAL):
            from fedml_tpu.telemetry.live import MetricStreamer

            self._telemetry_streamer = MetricStreamer(
                # same falsy-run_id normalization as LivePlane.from_args:
                # a "None"/"" job would fail the collector's job gate and
                # silently drop every frame this endpoint sends
                "serve",
                job=str(getattr(args, "run_id", None) or run_id or "0"),
                interval_s=float(getattr(args, "live_interval_s", 1.0)),
                send_cb=self._send_telemetry_frame,
            ).start()
            # causal tracing: the endpoint's serve/swap spans ride their
            # own dedicated carrier too, so the assembled round timeline
            # extends through the serving hot-swap
            if bool(getattr(args, "trace_streaming", True)):
                from fedml_tpu.telemetry.tracing import SpanStreamer

                self._span_streamer = SpanStreamer(
                    "serve",
                    job=str(getattr(args, "run_id", None) or run_id or "0"),
                    interval_s=float(getattr(args, "live_interval_s", 1.0)),
                    send_cb=self._send_trace_frame,
                ).start()

    def run_async(self):
        """Start the receive loop AND announce ourselves: on distributed
        backends the startup hello/resync must fire here too — ``run()``
        self-delivers CONNECTION_IS_READY but ``run_async`` (the serve
        CLI path) does not, and without it an endpoint booted
        mid-federation would serve its boot checkpoint until the next
        round happens to close. LOCAL keeps its explicit test kick."""
        t = super().run_async()
        self._notify_connection_ready()
        return t

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            ServeMessage.MSG_TYPE_CONNECTION_IS_READY, self._handle_ready)
        self.register_message_receive_handler(
            ServeMessage.MSG_TYPE_P2S_SWAP, self._handle_swap)
        self.register_message_receive_handler(
            ServeMessage.MSG_TYPE_P2S_FINISH, lambda m: self.finish())

    def _handle_ready(self, msg: Message) -> None:
        self.request_resync()

    def request_resync(self) -> None:
        """Ask the publisher for its latest round (startup / lag heal)."""
        self.send_message(Message(ServeMessage.MSG_TYPE_S2P_HELLO,
                                  self.get_sender_id(), 0))

    def _send_telemetry_frame(self, frame: dict) -> None:
        """Dedicated carrier for the streamer's off-thread loop: one small
        message per emitted frame to the publisher, whose process hosts
        the run's LivePlane (the comm receive seam merges the frame)."""
        m = Message(ServeMessage.MSG_TYPE_S2P_TELEMETRY,
                    self.get_sender_id(), 0)
        m.add_params(Message.MSG_ARG_KEY_TELEMETRY, frame)
        self.send_message(m)

    def _send_trace_frame(self, frame: dict) -> None:
        """Dedicated carrier for span-batch frames: same route as the
        metric frames, under the trace param key."""
        m = Message(ServeMessage.MSG_TYPE_S2P_TELEMETRY,
                    self.get_sender_id(), 0)
        m.add_params(Message.MSG_ARG_KEY_TRACE, frame)
        self.send_message(m)

    def finish(self) -> None:
        for attr in ("_telemetry_streamer", "_span_streamer"):
            streamer = getattr(self, attr, None)
            if streamer is None:
                continue
            # stream close while the transport is still up: the final FULL
            # frame makes the collector's totals for this node exact
            setattr(self, attr, None)
            try:
                streamer.close()
            except Exception:  # pragma: no cover - transport already down
                logger.debug("final serving telemetry flush failed",
                             exc_info=True)
        super().finish()

    @property
    def lag(self) -> int:
        """Rounds the endpoint trails the newest round it has SEEN."""
        cur = self.slots.live_round
        if self.round_published is None or cur is None:
            return 0
        return max(0, self.round_published - cur)

    def _handle_swap(self, msg: Message) -> None:
        round_idx = int(msg.get(ServeMessage.ARG_ROUND))
        payload = msg.get(ServeMessage.ARG_MODEL_PARAMS)
        spec = msg.get(ServeMessage.ARG_CODEC)
        if self.round_published is None or round_idx > self.round_published:
            self.round_published = round_idx
            self._g_published.set(float(round_idx))
        # serve --trace-rounds seam: an armed round captures its swap
        # window (staging + decode + flip) through the one TraceController
        from fedml_tpu.telemetry.profiling import get_trace_controller

        tc = get_trace_controller()
        tracing = tc.on_round_start(round_idx)
        try:
            swapped = self._apply_swap(round_idx, payload, spec)
        finally:
            if tracing:
                tc.on_round_end(round_idx)
        if swapped:
            logger.info("endpoint hot-swapped to round %d%s", round_idx,
                        f" ({spec})" if spec else "")

    def _apply_swap(self, round_idx: int, payload, spec) -> bool:
        try:
            swapped = self.slots.publish_payload(payload, round_idx, spec)
        except Exception:
            # a corrupt payload must not wedge the endpoint: keep serving
            # the current round, count the failure, and re-request the
            # latest state — but only ONCE per failing round. A payload
            # that fails deterministically (unknown codec spec, shape
            # mismatch) would otherwise livelock: hello → identical
            # resend → same failure, a full model per iteration. After
            # one retry the round is written off; the next round's
            # publish supersedes it.
            self.swap_errors += 1
            logger.exception("swap for round %d failed; endpoint stays on "
                             "round %s", round_idx, self.slots.live_round)
            if round_idx not in self._failed_rounds:
                self._failed_rounds.add(round_idx)
                self._failed_rounds = {
                    r for r in self._failed_rounds if r > round_idx - 128}
                self.request_resync()
            return False
        return swapped


def attach_round_publisher(server_manager: Any,
                           publisher: ServingPublisher) -> None:
    """Wire a cross-silo server's round close to the serving publisher.

    Uses the server manager's round-listener hook; the publisher's send
    path (encode + comm) runs on the server's round-advance thread but is
    guarded there so a serving-plane failure can never break training.
    """
    server_manager.add_round_listener(publisher.publish)
