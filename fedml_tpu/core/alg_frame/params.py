"""Typed parameter bag + global context singleton.

Parity with ``core/alg_frame/params.py`` / ``context.py`` in the reference.
"""
from __future__ import annotations

from typing import Any


class Params:
    """Arbitrary keyed parameters passed through algorithm hooks."""

    def __init__(self, **kwargs: Any):
        for k, v in kwargs.items():
            setattr(self, k, v)

    def add(self, name: str, value: Any) -> "Params":
        setattr(self, name, value)
        return self

    def get(self, name: str, default: Any = None) -> Any:
        return getattr(self, name, default)

    def __contains__(self, name: str) -> bool:
        return hasattr(self, name)


class Context(Params):
    """Process-wide singleton context shared across algorithm hooks.

    Reference: ``core/alg_frame/context.py`` — e.g. the per-round client list
    ``KEY_CLIENT_ID_LIST_IN_THIS_ROUND`` consumed by defenses and the
    contribution assessor.
    """

    KEY_TEST_DATA = "test_data"
    KEY_CLIENT_ID_LIST_IN_THIS_ROUND = "client_id_list_in_this_round"
    KEY_CLIENT_NUM_IN_THIS_ROUND = "client_num_in_this_round"
    KEY_METRICS_ON_AGGREGATED_MODEL = "metrics_on_aggregated_model"
    KEY_METRICS_ON_LAST_ROUND = "metrics_on_last_round"
    KEY_CLIENT_CONTRIBUTIONS = "client_contributions"

    _instance: "Context | None" = None

    def __new__(cls) -> "Context":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
