"""ClientTrainer — the client-side training operator.

Parity target: ``core/alg_frame/client_trainer.py:8-85`` in the reference,
re-designed functionally for XLA. The reference doctrine — "the operator does
not cache state" — becomes literal here: model parameters are an explicit
pytree argument and return value, and the hot path (``train_step``) is a pure
function so the engine can ``jit``/``shard_map`` it across a device mesh.

Security/DP hooks keep the reference's shape: ``on_before_local_training``
runs data poisoning (attack CI) and FHE decrypt; ``on_after_local_training``
runs local-DP noise and FHE encrypt.
"""
from __future__ import annotations

import abc
from typing import Any, Tuple

Pytree = Any


class ClientTrainer(abc.ABC):
    """Abstract client training operator (params in → params out)."""

    def __init__(self, model: Any = None, args: Any = None):
        self.model = model  # model *definition* (apply fn / module), never weights
        self.args = args
        self.id = 0
        self.local_sample_number = 0

    def set_id(self, trainer_id: int) -> None:
        self.id = trainer_id

    # engine-contract hooks (overridden where meaningful; no-ops otherwise)
    def set_pad_to_batches(self, n) -> None:
        """Share one compiled shape across heterogeneous clients."""

    def set_round(self, round_idx: int) -> None:
        """Give the trainer the round index (per-round data shuffling)."""

    def set_data_sharding(self, sharding) -> None:
        """In-silo parallelism: shard local batches over a silo mesh."""

    def set_server_state(self, server_state: dict) -> None:
        """Round-scoped algorithm state pushed by the server/engine
        (SCAFFOLD's c_global, Mime's server momentum s)."""

    # ---- parameter plumbing (pytree, not state_dict) --------------------
    def get_model_params(self) -> Pytree:
        raise NotImplementedError(
            "functional trainers carry no implicit params; pass them to train()"
        )

    def set_model_params(self, model_parameters: Pytree) -> None:
        raise NotImplementedError(
            "functional trainers carry no implicit params; pass them to train()"
        )

    # ---- hooks ----------------------------------------------------------
    def on_before_local_training(
        self, params: Pytree, train_data: Any, device: Any, args: Any
    ) -> Tuple[Pytree, Any]:
        """Attack (data poisoning) + FHE-decrypt hook.

        Reference: ``client_trainer.py:59-69``.
        """
        from fedml_tpu.core.security.attacker import FedMLAttacker

        attacker = FedMLAttacker.get_instance()
        if attacker.is_data_poisoning_attack() and attacker.is_to_poison_data():
            train_data = attacker.poison_data(train_data)
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE

        if FedMLFHE.get_instance().is_fhe_enabled():
            params = FedMLFHE.get_instance().fhe_dec(params)
        return params, train_data

    def on_after_local_training(
        self, params: Pytree, train_data: Any, device: Any, args: Any
    ) -> Pytree:
        """Local-DP noise + FHE-encrypt hook (reference ``:71-85``)."""
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )

        dp = FedMLDifferentialPrivacy.get_instance()
        if dp.is_local_dp_enabled():
            params = dp.add_local_noise(params)
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE

        if FedMLFHE.get_instance().is_fhe_enabled():
            params = FedMLFHE.get_instance().fhe_enc(params)
        return params

    # ---- the work -------------------------------------------------------
    @abc.abstractmethod
    def train(
        self, params: Pytree, train_data: Any, device: Any, args: Any
    ) -> Tuple[Pytree, dict]:
        """Run local training; return (new_params, metrics)."""

    def test(self, params: Pytree, test_data: Any, device: Any, args: Any) -> dict:
        return {}

    # Full pipeline the engines call.
    def run_local_training(
        self, params: Pytree, train_data: Any, device: Any, args: Any
    ) -> Tuple[Pytree, dict]:
        params, train_data = self.on_before_local_training(
            params, train_data, device, args
        )
        new_params, metrics = self.train(params, train_data, device, args)
        new_params = self.on_after_local_training(new_params, train_data, device, args)
        return new_params, metrics
