"""ServerAggregator — the server-side aggregation operator.

Parity target: ``core/alg_frame/server_aggregator.py:14-141``. Hook order is
identical to the reference:

  on_before_aggregation:  FHE path short-circuits; else global-DP clip →
                          model-poisoning attack injection (CI) → defense
                          (before_agg / malicious-client filtering)
  aggregate:              defense-wrapped FedMLAggOperator (one jitted
                          weighted tree-reduce) or FHE additive aggregation
  on_after_aggregation:   FHE passthrough; else central-DP noise →
                          contribution assessment (Shapley)
"""
from __future__ import annotations

import abc
import logging
from typing import Any, Dict, List, Tuple

from fedml_tpu.core.alg_frame.params import Context

Pytree = Any


class ServerAggregator(abc.ABC):
    def __init__(self, model: Any = None, args: Any = None):
        self.model = model
        self.args = args
        self.id = 0
        self.is_enabled_test = True

    def set_id(self, aggregator_id: int) -> None:
        self.id = aggregator_id

    # ---- hooks ----------------------------------------------------------
    def on_before_aggregation(
        self, raw_client_model_list: List[Tuple[int, Pytree]]
    ) -> Tuple[List[Tuple[int, Pytree]], List[int]]:
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
        from fedml_tpu.core.security.attacker import FedMLAttacker
        from fedml_tpu.core.security.defender import FedMLDefender

        client_idxs = list(range(len(raw_client_model_list)))
        if FedMLFHE.get_instance().is_fhe_enabled():
            return raw_client_model_list, client_idxs

        dp = FedMLDifferentialPrivacy.get_instance()
        if dp.is_global_dp_enabled() and dp.is_clipping():
            raw_client_model_list = dp.global_clip(raw_client_model_list)

        attacker = FedMLAttacker.get_instance()
        if attacker.is_model_attack():
            raw_client_model_list = attacker.attack_model(
                raw_client_grad_list=raw_client_model_list,
                extra_auxiliary_info=None,
            )

        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled():
            raw_client_model_list = defender.defend_before_aggregation(
                raw_client_grad_list=raw_client_model_list,
                extra_auxiliary_info=self.get_defense_aux(),
            )
            client_idxs = list(range(len(raw_client_model_list)))
        return raw_client_model_list, client_idxs

    def aggregate(self, raw_client_model_list: List[Tuple[int, Pytree]]) -> Pytree:
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
        from fedml_tpu.core.security.defender import FedMLDefender
        from fedml_tpu.ml.aggregator.agg_operator import FedMLAggOperator

        if FedMLFHE.get_instance().is_fhe_enabled():
            return FedMLFHE.get_instance().fhe_fedavg(raw_client_model_list)
        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled():
            return defender.defend_on_aggregation(
                raw_client_grad_list=raw_client_model_list,
                base_aggregation_func=FedMLAggOperator.agg,
                extra_auxiliary_info=self.get_defense_aux(),
            )
        return FedMLAggOperator.agg(self.args, raw_client_model_list)

    def on_after_aggregation(self, aggregated_params: Pytree) -> Pytree:
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
        from fedml_tpu.core.security.defender import FedMLDefender

        if FedMLFHE.get_instance().is_fhe_enabled():
            return aggregated_params
        dp = FedMLDifferentialPrivacy.get_instance()
        if dp.is_central_dp_enabled():
            logging.info("-----add central DP noise ----")
            aggregated_params = dp.add_global_noise(aggregated_params)
        defender = FedMLDefender.get_instance()
        if defender.is_defense_enabled():
            aggregated_params = defender.defend_after_aggregation(aggregated_params)
        return aggregated_params

    def get_defense_aux(self) -> Any:
        """Extra info defenses may need (global model, val data) via Context."""
        return Context().get(Context.KEY_METRICS_ON_LAST_ROUND)

    # ---- work -----------------------------------------------------------
    @abc.abstractmethod
    def test(self, params: Pytree, test_data: Any, device: Any, args: Any) -> Dict:
        """Evaluate the aggregated model."""

    def test_all(
        self, params: Pytree, train_data_local_dict, test_data_local_dict, device, args
    ) -> bool:
        return True
