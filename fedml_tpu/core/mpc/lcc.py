"""Lagrange coded computing (LCC) — the coding core of LightSecAgg.

Parity target: ``core/mpc/lightsecagg.py`` (``gen_Lagrange_coeffs`` :59,
``LCC_encoding_with_points`` :41, ``LCC_decoding_with_points`` :50) and the
native twin ``android/.../LightSecAgg.cpp``. Design changes:

- coefficients + encode/decode are *matrix* ops over int64 field vectors
  (the reference loops per entry in Python);
- the hot path dispatches to the C++ kernel (``native/lcc.cpp`` via
  ctypes, built by ``make -C native``; auto-built on first use when a
  compiler is present) with a vectorised numpy fallback — both are
  parity-tested against each other.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

from fedml_tpu.core.mpc.finite import DEFAULT_PRIME, mulmod

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "liblcc.so")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if not os.path.exists(_LIB_PATH):
        try:  # build on demand; fine to fail (numpy fallback)
            # target only the LCC library: a broker.cpp build failure on a
            # non-epoll platform must not take down the ctypes path
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "liblcc.so"], check=True,
                capture_output=True, timeout=120,
            )
        except Exception as e:  # pragma: no cover
            logger.info("native lcc build unavailable (%s); using numpy", e)
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        lib.lcc_lagrange_coeffs.restype = ctypes.c_int
        lib.lcc_lagrange_coeffs.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.lcc_field_matmul.restype = None
        lib.lcc_field_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
    except OSError as e:  # pragma: no cover
        logger.info("native lcc load failed (%s); using numpy", e)
        _lib = None
    return _lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def native_available() -> bool:
    return _load_native() is not None


# -- coefficients -----------------------------------------------------------

def gen_lagrange_coeffs(eval_pts: np.ndarray, target_pts: np.ndarray,
                        p: int = DEFAULT_PRIME,
                        use_native: Optional[bool] = None) -> np.ndarray:
    """U[i, j] = L_j(target_i) over GF(p): interpolate from eval_pts to
    target_pts. Columns are Lagrange basis polynomials at the eval points."""
    eval_pts = np.mod(np.asarray(eval_pts, np.int64), p)
    target_pts = np.mod(np.asarray(target_pts, np.int64), p)
    if len(np.unique(eval_pts)) != len(eval_pts):
        raise ValueError("evaluation points must be distinct mod p")
    lib = _load_native() if use_native in (None, True) else None
    if lib is not None and use_native is not False:
        out = np.zeros((len(target_pts), len(eval_pts)), np.int64)
        rc = lib.lcc_lagrange_coeffs(
            _ptr(np.ascontiguousarray(eval_pts)), len(eval_pts),
            _ptr(np.ascontiguousarray(target_pts)), len(target_pts),
            p, _ptr(out),
        )
        if rc != 0:
            raise ValueError("zero denominator in Lagrange coefficients")
        return out
    # numpy fallback — vectorised over targets, loop over eval points
    n_e, n_t = len(eval_pts), len(target_pts)
    out = np.zeros((n_t, n_e), np.int64)
    for j in range(n_e):
        num = np.ones(n_t, np.int64)
        den = np.int64(1)
        for l in range(n_e):
            if l == j:
                continue
            num = mulmod(num, (target_pts - eval_pts[l]) % p, p)
            den = int(mulmod(np.int64(den),
                             (eval_pts[j] - eval_pts[l]) % p, p))
        inv = pow(int(den) % p, p - 2, p)
        out[:, j] = mulmod(num, np.int64(inv), p)
    return out


def field_matmul(coeffs: np.ndarray, X: np.ndarray, p: int = DEFAULT_PRIME,
                 use_native: Optional[bool] = None) -> np.ndarray:
    """coeffs [n_out, n_in] × X [n_in, dim] over GF(p)."""
    coeffs = np.mod(np.asarray(coeffs, np.int64), p)
    X = np.mod(np.asarray(X, np.int64), p)
    n_out, n_in = coeffs.shape
    dim = X.shape[1]
    lib = _load_native() if use_native in (None, True) else None
    if lib is not None and use_native is not False:
        out = np.zeros((n_out, dim), np.int64)
        lib.lcc_field_matmul(
            _ptr(np.ascontiguousarray(coeffs)),
            _ptr(np.ascontiguousarray(X)),
            n_out, n_in, dim, p, _ptr(out),
        )
        return out
    # numpy fallback: accumulate row-by-row with incremental reduction
    out = np.zeros((n_out, dim), np.int64)
    for j in range(n_in):
        out = (out + mulmod(np.broadcast_to(coeffs[:, j:j + 1], (n_out, dim)),
                            X[j], p)) % p
    return out


# -- LCC encode/decode (reference-compatible shapes) -------------------------

def lcc_encode(X: np.ndarray, eval_pts: np.ndarray, target_pts: np.ndarray,
               p: int = DEFAULT_PRIME, use_native: Optional[bool] = None
               ) -> np.ndarray:
    """Encode rows of X (defined at ``eval_pts``) to ``target_pts``.

    X: [K(+T), dim] data(+noise) rows; returns [N, dim] coded rows.
    Reference: ``LCC_encoding_with_points`` (lightsecagg.py:41).
    """
    U = gen_lagrange_coeffs(eval_pts, target_pts, p, use_native)
    return field_matmul(U, X, p, use_native)


def lcc_decode(evals: np.ndarray, eval_pts: np.ndarray, target_pts: np.ndarray,
               p: int = DEFAULT_PRIME, use_native: Optional[bool] = None
               ) -> np.ndarray:
    """Recover values at ``target_pts`` from evaluations at ``eval_pts``.

    Reference: ``LCC_decoding_with_points`` (lightsecagg.py:50).
    """
    U = gen_lagrange_coeffs(eval_pts, target_pts, p, use_native)
    return field_matmul(U, evals, p, use_native)
