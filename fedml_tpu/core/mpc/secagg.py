"""Secure aggregation primitives (Bonawitz-style SecAgg).

Parity target: ``core/mpc/secagg.py`` (395 LoC: BGW/Shamir share generation
:164-212, additive shares :316, DH key agreement :329-343, PRG masks +
model masking :83-163). TPU-era re-design:

- shares/masks are vectorised int64 field vectors (one flat vector per
  model, from ``finite.tree_to_finite``) instead of per-layer dict loops;
- Shamir reconstruct reuses the LCC Lagrange kernel (C++ or numpy) —
  reconstruction at 0 is interpolation to target point 0;
- PRG masks come from ``numpy.random.Philox`` keyed by the X25519-agreed
  secret, so pairwise masks are reproducible on both endpoints without
  shipping them.

Key exchange is real X25519 (via ``cryptography``), NOT finite-field DH
over the aggregation prime: the adversary SecAgg defends against is the
aggregation *server* itself, which relays all public keys, so the key
agreement must resist the server, not just the network (TLS covers only
the latter). Secrets default to OS entropy; deterministic seeding exists
solely for reproducible tests.

The protocol dance (round-trip messages) lives in
``cross_silo/secagg``; this module is the math, unit-testable without any
transport.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Sequence, Tuple

import numpy as np
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)

from fedml_tpu.core.mpc.finite import DEFAULT_PRIME
from fedml_tpu.core.mpc.lcc import field_matmul, gen_lagrange_coeffs


# -- Shamir secret sharing ---------------------------------------------------

def shamir_share(secret: np.ndarray, n_shares: int, threshold: int,
                 p: int = DEFAULT_PRIME, rng: np.random.Generator = None
                 ) -> np.ndarray:
    """Split ``secret`` [dim] into n shares, any ``threshold+1`` reconstruct.

    Polynomial of degree ``threshold`` with the secret at x=0, evaluated at
    x = 1..n (reference: ``BGW_encoding`` :164).
    Returns [n_shares, dim].
    """
    rng = rng or np.random.default_rng()
    secret = np.mod(np.asarray(secret, np.int64), p)
    dim = secret.shape[0]
    coeffs = np.concatenate(
        [secret[None], rng.integers(0, p, size=(threshold, dim)).astype(np.int64)]
    )  # [deg+1, dim]
    xs = np.arange(1, n_shares + 1, dtype=np.int64)
    # Vandermonde [n, deg+1] times coeffs mod p
    V = np.ones((n_shares, threshold + 1), np.int64)
    for k in range(1, threshold + 1):
        V[:, k] = (V[:, k - 1] * xs) % p
    return field_matmul(V, coeffs, p)


def shamir_reconstruct(shares: np.ndarray, idxs: Sequence[int],
                       p: int = DEFAULT_PRIME) -> np.ndarray:
    """Reconstruct the secret from shares at 1-based points ``idxs``.

    Reference: ``BGW_decoding`` :192 — here it is one Lagrange
    interpolation to x=0 through the shared LCC kernel.
    """
    pts = np.asarray(idxs, np.int64)
    U = gen_lagrange_coeffs(pts, np.zeros(1, np.int64), p)  # [1, k]
    return field_matmul(U, np.asarray(shares, np.int64), p)[0]


# -- additive shares (reference: Gen_Additive_SS :316) -----------------------

def additive_share(secret: np.ndarray, n_out: int, p: int = DEFAULT_PRIME,
                   rng: np.random.Generator = None) -> np.ndarray:
    rng = rng or np.random.default_rng()
    secret = np.mod(np.asarray(secret, np.int64), p)
    parts = rng.integers(0, p, size=(n_out - 1, secret.shape[0])).astype(np.int64)
    last = np.mod(secret - parts.sum(axis=0), p)
    return np.concatenate([parts, last[None]])


# -- key exchange (reference: my_pk_gen :329, my_key_agreement :337 — which
# use toy finite-field DH; here it is X25519, see module docstring)

def kx_keygen(rng: np.random.Generator = None) -> Tuple[X25519PrivateKey, bytes]:
    """Generate an X25519 keypair → (private key, 32-byte public key).

    ``rng`` seeds the private scalar deterministically (tests only);
    default is OS entropy via ``X25519PrivateKey.generate``.
    """
    if rng is None:
        sk = X25519PrivateKey.generate()
    else:
        sk = X25519PrivateKey.from_private_bytes(rng.bytes(32))
    return sk, sk.public_key().public_bytes_raw()


def kx_agree(my_sk: X25519PrivateKey, their_pk: bytes) -> int:
    """Shared secret → 128-bit PRG seed (SHA-256 of the raw exchange)."""
    secret = my_sk.exchange(X25519PublicKey.from_public_bytes(bytes(their_pk)))
    return int.from_bytes(hashlib.sha256(secret).digest()[:16], "little")


# -- PRG masks ---------------------------------------------------------------

def prg_mask(seed: int, dim: int, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Deterministic field vector from a shared seed (Philox counter PRG)."""
    bits = np.random.Generator(np.random.Philox(key=seed & ((1 << 128) - 1)))
    return bits.integers(0, p, size=dim).astype(np.int64)


# -- the SecAgg math, endpoint by endpoint ----------------------------------

class SecAggClient:
    """Client-side state: pairwise + self masks over one round.

    Masking (reference ``model_masking`` :83):
        y_i = x_i + b_i + Σ_{j: i<j} s_ij − Σ_{j: j<i} s_ij   (mod p)
    where s_ij = PRG(DH(i,j)) cancels pairwise, and b_i = PRG(self seed) is
    removed by the server after clients reveal Shamir shares of b-seeds for
    *survivors* (dropout tolerance: pairwise seeds are revealed for the
    dropped instead).
    """

    def __init__(self, client_id: int, n_clients: int, threshold: int,
                 dim: int, p: int = DEFAULT_PRIME, seed: int = None):
        self.id = int(client_id)
        self.n = int(n_clients)
        self.t = int(threshold)
        self.dim = int(dim)
        self.p = int(p)
        # OS entropy by default; a seed is accepted only so tests reproduce
        self.rng = (np.random.default_rng() if seed is None
                    else np.random.default_rng(seed * 7919 + self.id))
        self.sk, self.pk = kx_keygen(None if seed is None else self.rng)
        # drawn in [0, p): the seed is Shamir-shared over GF(p), so it must
        # survive the mod-p round trip bit-exactly
        self.self_seed = int(self.rng.integers(0, self.p))
        self.pairwise: Dict[int, int] = {}

    # round 0: advertise pk; round 1: agree with every peer
    def set_peer_keys(self, pks: Dict[int, bytes]) -> None:
        for j, pk in pks.items():
            if j != self.id:
                self.pairwise[j] = kx_agree(self.sk, pk)

    def self_seed_shares(self) -> np.ndarray:
        """Shamir shares of the self-mask seed, one per client."""
        return shamir_share(
            np.array([self.self_seed % self.p], np.int64),
            self.n, self.t, self.p, self.rng,
        )

    def mask(self, x_finite: np.ndarray) -> np.ndarray:
        y = np.mod(x_finite + prg_mask(self.self_seed, self.dim, self.p), self.p)
        for j, key in self.pairwise.items():
            s = prg_mask(key, self.dim, self.p)
            y = np.mod(y + s if self.id < j else y - s, self.p)
        return y

    def pairwise_seed(self, j: int) -> int:
        return self.pairwise[j]


class SecAggServer:
    """Server-side unmasking given survivors' seed shares / dropout keys."""

    def __init__(self, n_clients: int, threshold: int, dim: int,
                 p: int = DEFAULT_PRIME):
        self.n, self.t, self.dim, self.p = n_clients, threshold, dim, p

    def aggregate(
        self,
        masked: Dict[int, np.ndarray],
        self_seed_shares: Dict[int, Dict[int, np.ndarray]],
        dropped_pairwise: Dict[int, Dict[int, int]] = None,
    ) -> np.ndarray:
        """Sum survivors' masked vectors and strip masks.

        masked: {client_id: y_i} — the survivors.
        self_seed_shares: {owner_id: {holder_id: share_row}} for survivors
          (holders reveal their share of each survivor's b-seed).
        dropped_pairwise: {dropped_id: {survivor_id: pairwise_seed}} —
          revealed so half-cancelled pairwise masks can be removed.
        """
        survivors = sorted(masked)
        agg = np.zeros(self.dim, np.int64)
        for i in survivors:
            agg = np.mod(agg + masked[i], self.p)
        # strip self masks: reconstruct each survivor's seed from shares
        for i in survivors:
            holders = sorted(self_seed_shares[i])[: self.t + 1]
            shares = np.stack([self_seed_shares[i][h] for h in holders])
            seed = int(shamir_reconstruct(shares, [h + 1 for h in holders],
                                          self.p)[0])
            agg = np.mod(agg - prg_mask(seed, self.dim, self.p), self.p)
        # strip half-cancelled pairwise masks of dropped clients
        for d, seeds in (dropped_pairwise or {}).items():
            for i in survivors:
                s = prg_mask(seeds[i], self.dim, self.p)
                # survivor i applied +s if i<d else -s; remove it
                agg = np.mod(agg - s if i < d else agg + s, self.p)
        return agg
