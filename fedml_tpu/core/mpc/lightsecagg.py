"""LightSecAgg — one-shot aggregate-mask reconstruction via LCC.

Parity target: ``core/mpc/lightsecagg.py`` (205 LoC: ``mask_encoding`` :97,
``compute_aggregate_encoded_mask`` :126, masking :83) and the native twin
``android/.../LightSecAggForMNN.cpp``. Protocol sketch:

1. every client draws a random mask z_i [d], pads it to K equal chunks,
   appends T noise rows, and LCC-encodes the K+T rows to N points — the
   j-th coded row goes to client j (offline phase);
2. upload: client sends x_i + z_i (mod p);
3. each *surviving* client sums the coded rows it received from survivors
   → ONE point of the aggregate-mask polynomial — a single scalar-vector
   message instead of SecAgg's per-pair unmasking round;
4. server interpolates any K+T such points back to the K data chunks,
   concatenates → Σ z_i, and subtracts from Σ (x_i + z_i).

Dropout tolerance: any ≥ K+T survivors reconstruct; ≤ T colluders learn
nothing about an individual z_i (the noise rows).
"""
from __future__ import annotations

import math
from typing import Dict, List, Sequence

import numpy as np

from fedml_tpu.core.mpc.lcc import lcc_decode, lcc_encode

Pytree = dict


def _points(n: int, k: int, t: int, p: int):
    """Evaluation geometry: betas (data+noise anchors) then alphas (clients),
    all distinct mod p. Reference uses the same 1..K+T / K+T+1..K+T+N split."""
    betas = np.arange(1, k + t + 1, dtype=np.int64)
    alphas = np.arange(k + t + 1, k + t + 1 + n, dtype=np.int64)
    return betas % p, alphas % p


def mask_encoding(dim: int, n_clients: int, targeted_number_active_clients: int,
                  privacy_guarantee: int, prime_number: int,
                  local_mask: np.ndarray,
                  rng: np.random.Generator = None) -> Dict[int, np.ndarray]:
    """Encode one client's mask into N coded rows (one per receiving client).

    Arg names follow the reference's ``mask_encoding`` (:97): U =
    ``targeted_number_active_clients`` survivors needed, T =
    ``privacy_guarantee`` colluders tolerated, K = U - T data chunks.
    Returns {receiver_id: coded_row [ceil(d/K)]}.
    """
    p = int(prime_number)
    n, u, t = int(n_clients), int(targeted_number_active_clients), int(privacy_guarantee)
    k = u - t
    if k <= 0:
        raise ValueError("need targeted_active > privacy_guarantee")
    rng = rng or np.random.default_rng()
    chunk = math.ceil(dim / k)
    z = np.mod(np.asarray(local_mask, np.int64), p)
    padded = np.zeros(chunk * k, np.int64)
    padded[:dim] = z
    rows = padded.reshape(k, chunk)
    noise = rng.integers(0, p, size=(t, chunk)).astype(np.int64)
    X = np.concatenate([rows, noise])  # [K+T, chunk]
    betas, alphas = _points(n, k, t, p)
    coded = lcc_encode(X, betas, alphas, p)  # [N, chunk]
    return {j: coded[j] for j in range(n)}


def compute_aggregate_encoded_mask(encoded_mask_dict: Dict[int, np.ndarray],
                                   p: int, active_clients: Sequence[int]
                                   ) -> np.ndarray:
    """One client's message in the one-shot round: Σ over surviving senders
    of the coded rows it holds (reference :126)."""
    agg = np.zeros_like(next(iter(encoded_mask_dict.values())))
    for cid in active_clients:
        agg = np.mod(agg + encoded_mask_dict[cid], p)
    return agg.astype(np.int64)


def decode_aggregate_mask(agg_encoded: Dict[int, np.ndarray], dim: int,
                          n_clients: int, targeted_number_active_clients: int,
                          privacy_guarantee: int, prime_number: int
                          ) -> np.ndarray:
    """Server: interpolate U survivors' aggregate points → Σ z_i [dim]."""
    p = int(prime_number)
    u, t = int(targeted_number_active_clients), int(privacy_guarantee)
    k = u - t
    betas, alphas = _points(int(n_clients), k, t, p)
    holders = sorted(agg_encoded)[:u]
    evals = np.stack([agg_encoded[h] for h in holders])
    rec = lcc_decode(evals, alphas[holders], betas[:k], p)  # [K, chunk]
    return rec.reshape(-1)[:dim]


def model_masking(x_finite: np.ndarray, local_mask: np.ndarray,
                  prime_number: int) -> np.ndarray:
    """Upload payload: x + z mod p (reference ``model_masking`` :83)."""
    return np.mod(np.asarray(x_finite, np.int64) + local_mask, prime_number)


def aggregate_models_in_finite(masked: List[np.ndarray],
                               prime_number: int) -> np.ndarray:
    agg = np.zeros_like(masked[0])
    for m in masked:
        agg = np.mod(agg + m, prime_number)
    return agg
