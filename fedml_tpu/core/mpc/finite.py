"""Finite-field arithmetic + fixed-point quantization for secure aggregation.

Parity target: the field/quantization layer of ``core/mpc/secagg.py``
(``modular_inv`` :8, ``my_q``/``my_q_inv`` :344-365,
``transform_tensor_to_finite``/``..._to_tensor`` :351-384) — re-designed
vectorised: everything operates on int64 numpy arrays (or whole pytrees),
with Fermat inverses instead of the reference's iterative extended-Euclid
loop.

Default prime is 2^31 - 1 (Mersenne): products of two residues fit int64
exactly via Python/object fallback-free ``%`` on uint64 intermediates.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import numpy as np

Pytree = Any

DEFAULT_PRIME = (1 << 31) - 1  # 2147483647, Mersenne prime


def modular_inv(a: int, p: int = DEFAULT_PRIME) -> int:
    """a^-1 mod p for prime p (Fermat)."""
    return pow(int(a) % p, p - 2, p)


def mod_inv_vec(a: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    return np.array([pow(int(x) % p, p - 2, p) for x in np.ravel(a)],
                    dtype=np.int64).reshape(np.shape(a))


def mulmod(a: np.ndarray, b: np.ndarray, p: int = DEFAULT_PRIME) -> np.ndarray:
    """(a*b) mod p elementwise without overflow (p < 2^31 ⇒ fits uint64)."""
    return ((a.astype(np.uint64) * (np.asarray(b, np.int64) % p).astype(np.uint64))
            % np.uint64(p)).astype(np.int64)


def quantize(x: np.ndarray, q_bits: int = 16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Fixed-point → field element; negatives map to the top of the field.

    Semantics match the reference's ``my_q`` (:344): round(x·2^q), negatives
    represented as p - |v|.
    """
    scaled = np.round(np.asarray(x, np.float64) * (1 << q_bits)).astype(np.int64)
    return np.mod(scaled, p).astype(np.int64)


def dequantize(xq: np.ndarray, q_bits: int = 16, p: int = DEFAULT_PRIME,
               n_summands: int = 1) -> np.ndarray:
    """Field element → float via the symmetric half-field split (matching
    the reference's ``my_q_inv`` :359).

    Overflow bound: decoding is correct iff the true (summed) value v
    satisfies ``|v| * 2^q_bits < p/2`` — the symmetric window is already
    the maximal unambiguous range, and no runtime check can detect a wrap
    (a wrapped sum is indistinguishable from a legitimate value of the
    other sign). Summing n clients therefore requires the CALLER to size
    ``q_bits``/``p`` such that n · max|x| · 2^q_bits < p/2; at the
    defaults that is |sum| < 2^14 = 16384. ``n_summands`` is accepted so
    call sites document how many values were summed.
    """
    xq = np.mod(np.asarray(xq, np.int64), p)
    del n_summands
    neg = xq > (p - 1) // 2
    signed = np.where(neg, xq.astype(np.float64) - p, xq.astype(np.float64))
    return (signed / (1 << q_bits)).astype(np.float32)


# -- pytree <-> flat finite vector ------------------------------------------

def tree_to_finite(tree: Pytree, q_bits: int = 16,
                   p: int = DEFAULT_PRIME) -> Tuple[np.ndarray, Pytree]:
    """Flatten a pytree to one int64 field vector (+ the abstract template).

    The reference quantizes per-layer dicts (``transform_tensor_to_finite``);
    flattening to one vector lets masking/coding be a single vector op.
    """
    leaves = [np.asarray(jax.device_get(x)) for x in jax.tree.leaves(tree)]
    flat = np.concatenate([quantize(l, q_bits, p).ravel() for l in leaves]) \
        if leaves else np.zeros(0, np.int64)
    return flat, tree


def finite_to_tree(flat: np.ndarray, tree_like: Pytree, q_bits: int = 16,
                   p: int = DEFAULT_PRIME, n_summands: int = 1) -> Pytree:
    leaves, treedef = jax.tree.flatten(tree_like)
    out, off = [], 0
    for leaf in leaves:
        arr = np.asarray(leaf)
        n = arr.size
        vals = dequantize(flat[off: off + n], q_bits, p, n_summands)
        out.append(vals.reshape(arr.shape).astype(np.float32))
        off += n
    return jax.tree.unflatten(treedef, out)
