"""First-class round-state checkpointing for the FL engines.

SURVEY §5 flags this as a required improvement over the reference, which
has NO round-level checkpointing in its FL engines (restart ⇒ round 0;
only the LLM path saves per-round adapters,
``spotlight_prj/fedllm/run_fedllm.py:152-244``). Here every engine can
persist {global params, algorithm state, server-optimizer state, DP RNG
counter, round index} after each round and resume bit-exactly: engines
derive all per-round randomness (client sampling, shuffling, noise keys)
from ``random_seed × round × client``, so params + counters ARE the full
state.

Storage is orbax (async-barrier'd, atomic renames); enable with

    train_args:
      checkpoint_dir: ./ckpts
      checkpoint_frequency: 1        # rounds between saves
      resume: true                   # pick up the latest round state
"""
from __future__ import annotations

import logging
import os
import re
import shutil
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_ROUND_RE = re.compile(r"^round_(\d+)$")


class RoundCheckpointer:
    """Saves one pytree-dict per round under ``<dir>/round_<idx>``."""

    def __init__(self, ckpt_dir: str, keep: int = 3):
        self.dir = os.path.abspath(ckpt_dir)
        self.keep = int(keep)
        os.makedirs(self.dir, exist_ok=True)

    # -- save -------------------------------------------------------------
    def save(self, round_idx: int, state: Dict[str, Any]) -> str:
        import orbax.checkpoint as ocp

        path = os.path.join(self.dir, f"round_{int(round_idx)}")
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, state, force=True)
        ckptr.wait_until_finished()
        self._prune()
        return path

    def _prune(self) -> None:
        rounds = sorted(self.saved_rounds())
        for r in rounds[: max(0, len(rounds) - self.keep)]:
            shutil.rmtree(os.path.join(self.dir, f"round_{r}"),
                          ignore_errors=True)

    # -- restore ----------------------------------------------------------
    def saved_rounds(self):
        if not os.path.isdir(self.dir):
            return []
        out = []
        for name in os.listdir(self.dir):
            m = _ROUND_RE.match(name)
            if m and os.path.isdir(os.path.join(self.dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_round(self) -> Optional[int]:
        rounds = self.saved_rounds()
        return rounds[-1] if rounds else None

    def restore(self, round_idx: int, template: Dict[str, Any]) -> Dict[str, Any]:
        import jax
        import orbax.checkpoint as ocp

        path = os.path.join(self.dir, f"round_{int(round_idx)}")
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree.map(np.asarray, template)
        return ckptr.restore(path, abstract)

    def restore_latest(
        self, template: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Restore the newest *restorable* round.

        A crash mid-save is a normal event for a preemptible server: it
        leaves orphaned orbax tmp dirs (the atomic-rename staging area)
        and, on non-atomic filesystems, a half-written ``round_<n>``.
        Both are pruned here — tmp dirs unconditionally, a corrupt
        latest round after its restore fails — and the walk falls back
        to the next-newest round instead of raising on the wreckage.
        """
        self._prune_orphaned_tmp()
        rounds = sorted(self.saved_rounds(), reverse=True)
        failed_round: Optional[int] = None
        for i, r in enumerate(rounds):
            try:
                state = self.restore(r, template)
            except Exception as e:  # orbax raises backend-specific types
                if i > 0:
                    # saves are sequential, so a crash corrupts at most
                    # the NEWEST round — a second unrestorable round is a
                    # template/config mismatch, not crash damage
                    raise
                failed_round = r
                logger.warning(
                    "round checkpoint %d is unrestorable (%s: %s) — "
                    "falling back to the previous round", r,
                    type(e).__name__, e)
                continue
            if failed_round is not None:
                # prune the newest round only AFTER an older one restored
                # against the same template: that proves the template is
                # fine and the newest save is genuinely half-written. A
                # template/config mismatch (every round fails) must never
                # destroy a good checkpoint.
                from fedml_tpu import telemetry

                telemetry.get_registry().counter(
                    "resilience/checkpoints_pruned").inc()
                logger.warning(
                    "pruning half-written round checkpoint %d (round %d "
                    "restored cleanly against the same template)",
                    failed_round, r)
                shutil.rmtree(
                    os.path.join(self.dir, f"round_{failed_round}"),
                    ignore_errors=True)
            logger.info("resumed round checkpoint %d from %s", r, self.dir)
            return r, state
        if failed_round is not None:
            # the ONLY checkpoint failed: crash damage and template
            # mismatch are indistinguishable here — keep the directory
            # for forensics and let the caller start fresh, loudly
            logger.error(
                "no restorable round checkpoint under %s (round %d kept "
                "on disk unrestorable — half-written first save, or a "
                "changed model template)", self.dir, failed_round)
        return None

    def _prune_orphaned_tmp(self) -> None:
        """Remove orbax atomic-rename staging dirs a crash left behind
        (``*.orbax-checkpoint-tmp-*`` and the older ``<name>.tmp.*``
        layouts) — they are never restorable and their presence breaks a
        later save of the same round on some orbax versions."""
        if not os.path.isdir(self.dir):
            return
        for name in os.listdir(self.dir):
            if "orbax-checkpoint-tmp" in name or ".tmp" in name:
                path = os.path.join(self.dir, name)
                logger.warning("pruning orphaned checkpoint tmp dir %s "
                               "(crash mid-save)", path)
                shutil.rmtree(path, ignore_errors=True)


def pack_round_state(
    global_params: Any,
    server_opt: Any = None,
    next_round: int = 0,
    extra: Optional[Dict[str, Any]] = None,
    dp_counter: Optional[int] = None,
) -> Dict[str, Any]:
    """The ONE saved-state contract every engine shares: global params,
    server-optimizer state, DP RNG counter, next round — plus engine
    extras (e.g. sp's SCAFFOLD/Mime server trees).

    ``dp_counter`` overrides the live singleton counter: an engine whose
    prefetch worker has already drawn the NEXT round's keys must save the
    counter as it stood when the round being checkpointed was staged,
    otherwise resume replays the wrong key sequence.
    """
    from fedml_tpu.core.dp.fedml_differential_privacy import (
        FedMLDifferentialPrivacy,
    )

    if dp_counter is None:
        dp_counter = FedMLDifferentialPrivacy.get_instance()._rng_counter
    state = {
        "global_params": global_params,
        "server_opt": (
            server_opt.get_state(global_params) if server_opt is not None else {}
        ),
        # 0-d arrays, not numpy scalars: orbax's standard handler rejects
        # np.generic leaves
        "dp_counter": np.asarray(dp_counter, np.int32),
        "next_round": np.asarray(next_round, np.int32),
    }
    if extra:
        state.update(extra)
    return state


def apply_round_state(state: Dict[str, Any], server_opt: Any = None) -> int:
    """Restore the shared fields; returns next_round. Engine extras and
    ``state['global_params']`` are the caller's to consume."""
    from fedml_tpu.core.dp.fedml_differential_privacy import (
        FedMLDifferentialPrivacy,
    )

    if server_opt is not None:
        server_opt.set_state(state["server_opt"])
    FedMLDifferentialPrivacy.get_instance()._rng_counter = int(
        state["dp_counter"]
    )
    return int(state["next_round"])


def engine_checkpointer(args: Any) -> Optional[RoundCheckpointer]:
    ckpt_dir = getattr(args, "checkpoint_dir", None)
    if not ckpt_dir:
        return None
    return RoundCheckpointer(
        ckpt_dir, keep=int(getattr(args, "checkpoint_keep", 3))
    )


def should_save(args: Any, round_idx: int) -> bool:
    freq = int(getattr(args, "checkpoint_frequency", 1) or 1)
    return round_idx % max(freq, 1) == 0
