"""Contribution assessment — Shapley-style data valuation.

Parity: reference ``core/contribution/`` (GTG-Shapley, leave-one-out,
``contribution_assessor_manager.py:9``).
"""
from fedml_tpu.core.contribution.contribution_assessor_manager import (
    ContributionAssessorManager,
)
from fedml_tpu.core.contribution.gtg_shapley import gtg_shapley, leave_one_out

__all__ = ["ContributionAssessorManager", "gtg_shapley", "leave_one_out"]
