"""ContributionAssessorManager — per-round participant valuation.

Parity: ``core/contribution/contribution_assessor_manager.py:9`` — invoked
from the server after aggregation with the round's client models; the
utility of a coalition is the validation metric of that coalition's
count-weighted aggregate. Accumulated values land in the Context and the
metrics sink so the MLOps plane can show per-client contribution.

Config:
  contribution_args:
    enable_contribution: true
    contribution_method: gtg_shapley | mr_shapley | leave_one_out
    contribution_round_trunc: 0.01   # MR: skip rounds that moved utility
                                     # by less than this (ref eps)
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Sequence, Tuple


from fedml_tpu.core.alg_frame.params import Context
from fedml_tpu.core.contribution.gtg_shapley import (
    gtg_shapley,
    leave_one_out,
    mr_shapley,
)

Pytree = Any

logger = logging.getLogger(__name__)


class ContributionAssessorManager:
    def __init__(self, args: Any):
        self.args = args
        self.enabled = bool(getattr(args, "enable_contribution", False))
        self.method = str(
            getattr(args, "contribution_method", "gtg_shapley")
        ).lower()
        self.max_permutations = int(getattr(args, "contribution_max_perms", 32))
        self.eps = float(getattr(args, "contribution_trunc_eps", 1e-3))
        self.round_trunc = float(
            getattr(args, "contribution_round_trunc", 0.01))
        self.accumulated: Dict[int, float] = {}

    def is_enabled(self) -> bool:
        return self.enabled

    def run(
        self,
        client_ids: Sequence[int],
        w_locals: List[Tuple[int, Pytree]],
        utility_of_params: Callable[[Pytree], float],
        utility_empty: float,
        round_idx: int = 0,
    ) -> Dict[int, float]:
        """w_locals: the round's [(n_samples, params)] in client_ids order."""
        from fedml_tpu.ml.aggregator.agg_operator import FedMLAggOperator

        def utility(subset: Sequence[int]) -> float:
            if not len(subset):
                return utility_empty
            agg = FedMLAggOperator.agg(
                self.args, [w_locals[i] for i in subset]
            )
            return float(utility_of_params(agg))

        n = len(w_locals)
        if self.method == "leave_one_out":
            phi = leave_one_out(n, utility)
        elif self.method in ("mr", "mr_shapley"):
            # MR round truncation (ref mr_shapley_value.py
            # round_trunc_threshold): a round that barely moved the
            # utility contributes ~0 to everyone — skip the 2^n sweep
            v_full = utility(list(range(n)))
            if abs(v_full - utility_empty) < self.round_trunc:
                logger.info("round %d: utility moved %.4f < %.4f — "
                            "MR-Shapley round truncated", round_idx,
                            abs(v_full - utility_empty), self.round_trunc)
                phi = [0.0] * n
            else:
                phi = mr_shapley(n, utility, utility_empty)
        else:
            phi = gtg_shapley(
                n, utility, utility_empty,
                max_permutations=self.max_permutations, eps=self.eps,
                seed=int(getattr(self.args, "random_seed", 0)) + round_idx,
            )
        values = {int(cid): float(phi[i]) for i, cid in enumerate(client_ids)}
        for cid, val in values.items():
            self.accumulated[cid] = self.accumulated.get(cid, 0.0) + val
        Context().add(Context.KEY_CLIENT_CONTRIBUTIONS, dict(self.accumulated))
        from fedml_tpu.core.mlops import metrics as mlops

        mlops.log({"round": round_idx, "contributions": values})
        logger.info("round %d contributions: %s", round_idx, values)
        return values
