"""GTG-Shapley — within-round truncated Monte-Carlo Shapley values.

Parity: ``core/contribution/gtg_shapley_value.py`` (Liu et al., "GTG-
Shapley: Efficient and Accurate Participant Contribution Evaluation in
Federated Learning"). The estimator samples permutations of the round's
participants, walks each permutation accumulating marginal utilities of
the *aggregated prefix model*, and truncates a permutation early once the
prefix utility is within ``eps`` of the full-coalition utility (the
"guided truncation"). For small cohorts (≤ ``exact_threshold``) it
enumerates every permutation — the exact Shapley value.

``utility_fn(subset_idxs) -> float`` is the round utility (e.g. validation
accuracy of the subset's aggregate); ``utility_empty`` is v(∅) — the
previous round's global model utility.
"""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Sequence

import numpy as np


def gtg_shapley(
    n: int,
    utility_fn: Callable[[Sequence[int]], float],
    utility_empty: float,
    max_permutations: int = 64,
    eps: float = 1e-3,
    convergence_tol: float = 1e-3,
    exact_threshold: int = 5,
    seed: int = 0,
) -> np.ndarray:
    """Shapley value per participant index 0..n-1."""
    cache: Dict[frozenset, float] = {frozenset(): float(utility_empty)}

    def v(subset: Sequence[int]) -> float:
        key = frozenset(subset)
        if key not in cache:
            cache[key] = float(utility_fn(sorted(subset)))
        return cache[key]

    phi = np.zeros(n, np.float64)
    if n == 0:
        return phi
    v_full = v(range(n))

    if n <= exact_threshold:
        perms = list(itertools.permutations(range(n)))
    else:
        rng = np.random.default_rng(seed)
        perms = [rng.permutation(n) for _ in range(max_permutations)]

    count = 0
    prev_mean = None
    for perm in perms:
        v_prev = cache[frozenset()]
        prefix: List[int] = []
        for c in perm:
            prefix.append(int(c))
            if abs(v_full - v_prev) < eps:
                # guided truncation: the remaining marginals are ~0
                v_cur = v_prev
            else:
                v_cur = v(prefix)
            phi[int(c)] += v_cur - v_prev
            v_prev = v_cur
        count += 1
        # convergence check on the running estimate (MC mode only)
        if n > exact_threshold and count >= 8 and count % 4 == 0:
            mean = phi / count
            if prev_mean is not None and np.max(
                np.abs(mean - prev_mean)
            ) < convergence_tol:
                break
            prev_mean = mean
    return phi / count


def leave_one_out(
    n: int,
    utility_fn: Callable[[Sequence[int]], float],
) -> np.ndarray:
    """phi_i = v(N) − v(N \\ {i}) (parity: the reference's LOO assessor)."""
    v_full = float(utility_fn(list(range(n))))
    out = np.zeros(n, np.float64)
    for i in range(n):
        rest = [j for j in range(n) if j != i]
        out[i] = v_full - float(utility_fn(rest))
    return out


def mr_shapley(
    n: int,
    utility_fn: Callable[[Sequence[int]], float],
    utility_empty: float,
) -> np.ndarray:
    """Exact per-round Shapley over the full power set.

    Parity: ``core/contribution/mr_shapley_value.py`` (the "MR" assessor
    enumerates every coalition each round and sums the exact values
    across rounds; the cross-round summation lives in the manager).
    φ_i = Σ_{S ∌ i} |S|!·(n−|S|−1)!/n! · [v(S∪{i}) − v(S)].
    """
    import math

    members = list(range(n))
    v: Dict[frozenset, float] = {frozenset(): float(utility_empty)}
    for r in range(1, n + 1):
        for subset in itertools.combinations(members, r):
            v[frozenset(subset)] = float(utility_fn(list(subset)))
    fact = [math.factorial(k) for k in range(n + 1)]
    out = np.zeros(n, np.float64)
    for i in members:
        others = [j for j in members if j != i]
        for r in range(0, n):
            w = fact[r] * fact[n - r - 1] / fact[n]
            for subset in itertools.combinations(others, r):
                s = frozenset(subset)
                out[i] += w * (v[s | {i}] - v[s])
    return out
