"""CKKS homomorphic encryption (additive subset) — numpy implementation.

Parity target: the reference's TenSEAL CKKS backend
(``core/fhe/fhe_agg.py:10``). TenSEAL is unavailable here, so this module
implements the actual RLWE/CKKS algebra from scratch:

- ring R_q = Z_q[X]/(X^N + 1), negacyclic polynomial arithmetic done as
  an exact integer matmul with 16-bit limb splitting (no NTT needed at
  these sizes, and the matmul form vectorizes in numpy);
- canonical-embedding encode/decode via FFT (slots = N/2 real values,
  fixed-point scale Δ);
- RLWE keygen (ternary secret, discrete-gaussian noise), public-key
  encryption, decryption, and ciphertext + ciphertext / ciphertext +
  plaintext addition — everything encrypted FedAvg needs. (Ciphertext
  multiplication/rescaling is deliberately out of scope: aggregation is
  additive.)

Parameters default to demo scale (N=1024, one 31-bit prime q, Δ=2^19):
correct CKKS algebra with a real noise term, sized so exact arithmetic
fits int64. Production deployments would use RNS-CKKS with N ≥ 8192 and
a chain of primes; the API is parameter-compatible.

Correctness bound: coefficient noise |e| ≈ a few hundred spreads over
slots by ≈ √N at decode, so slot error ≈ √N·e/Δ ≈ 6e-3 at the defaults,
and slot values must satisfy Δ·max|x| < q/2 — |x| < 2048 at Δ=2^19
(``encode`` raises beyond it). Summing K ciphertexts scales both the
value range and the noise by K.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

DEFAULT_N = 1024
DEFAULT_Q = (1 << 31) - 1  # Mersenne prime, same field as core/mpc
DEFAULT_DELTA = 1 << 19
_NOISE_SIGMA = 3.2
_SECRET_HAMMING = 64  # sparse ternary secret/ephemeral → small noise


def _negacyclic_matrix(a: np.ndarray, q: int) -> np.ndarray:
    """M such that M @ b == a * b mod (X^N + 1), entries in [0, q)."""
    n = a.shape[0]
    idx = np.arange(n)
    # row k, col j: +a[k-j] for j<=k, -a[n+k-j] for j>k
    diff = idx[:, None] - idx[None, :]
    m = a[diff % n].astype(np.int64)
    m = np.where(diff < 0, (-m) % q, m % q)
    return m


def polymul(a: np.ndarray, b: np.ndarray, q: int) -> np.ndarray:
    """Exact (a*b mod X^N+1 mod q) via limb-split integer matmul.

    Entries < q < 2^31; split the matrix into 16-bit limbs so every
    partial matmul accumulates within int64 (2^15·2^31·N ≤ 2^57 for
    N ≤ 2^11).
    """
    m = _negacyclic_matrix(np.mod(a, q), q)
    b = np.mod(b, q).astype(np.int64)
    hi, lo = m >> 16, m & 0xFFFF
    part_hi = (hi @ b) % q
    part_lo = (lo @ b) % q
    return ((part_hi << 16) + part_lo) % q


def _center(x: np.ndarray, q: int) -> np.ndarray:
    x = np.mod(x, q)
    return np.where(x > q // 2, x - q, x).astype(np.float64)


class CKKSCiphertext:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: np.ndarray, c1: np.ndarray):
        self.c0 = c0
        self.c1 = c1


class CKKSContext:
    def __init__(self, n: int = DEFAULT_N, q: int = DEFAULT_Q,
                 delta: int = DEFAULT_DELTA, seed: Optional[int] = None):
        if n & (n - 1):
            raise ValueError("ring degree n must be a power of two")
        self.n = int(n)
        self.q = int(q)
        self.delta = int(delta)
        self.slots = self.n // 2
        self._rng = np.random.default_rng(seed)
        # canonical-embedding twist: evaluation at odd powers of the
        # 2N-th root ζ reduces to an FFT of (a_k · ζ^k)
        k = np.arange(self.n)
        self._zeta_pow = np.exp(1j * np.pi * k / self.n)
        self.sk: Optional[np.ndarray] = None
        self.pk: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- keys -------------------------------------------------------------
    def _ternary(self) -> np.ndarray:
        s = np.zeros(self.n, np.int64)
        idx = self._rng.choice(self.n, size=_SECRET_HAMMING, replace=False)
        s[idx] = self._rng.choice(np.array([-1, 1]), size=_SECRET_HAMMING)
        return s

    def _noise(self) -> np.ndarray:
        return np.rint(
            self._rng.normal(0.0, _NOISE_SIGMA, self.n)).astype(np.int64)

    def keygen(self) -> "CKKSContext":
        self.sk = self._ternary()
        a = self._rng.integers(0, self.q, self.n, dtype=np.int64)
        e = self._noise()
        b = np.mod(-(polymul(a, self.sk, self.q)) + e, self.q)
        self.pk = (b, a)
        return self

    # -- encode / decode (canonical embedding) ----------------------------
    def encode(self, values: np.ndarray) -> np.ndarray:
        """Real slot values (≤ N/2 of them) → integer plaintext poly."""
        values = np.asarray(values, np.float64)
        limit = self.q / (2.0 * self.delta)
        if values.size and np.abs(values).max() >= limit:
            raise ValueError(
                f"slot value {np.abs(values).max():.1f} exceeds the "
                f"CKKS range |x| < {limit:.0f} at delta={self.delta} "
                f"(a field wrap is silent — refuse instead)")
        z = np.zeros(self.slots, np.complex128)
        z[: len(values)] = values
        # conjugate-symmetric extension fixes a real polynomial
        zfull = np.concatenate([z, np.conj(z[::-1])])
        # a_k = (Δ/N) Σ_j zfull_j ζ^{-(2j+1)k}: inverse of the decode FFT
        coeffs = np.fft.fft(zfull) * np.conj(self._zeta_pow) / self.n
        return np.rint(np.real(coeffs) * self.delta).astype(np.int64)

    def decode(self, poly: np.ndarray, length: Optional[int] = None) -> np.ndarray:
        """Centered plaintext poly → real slot values."""
        vals = np.fft.ifft(np.asarray(poly, np.float64) * self._zeta_pow) * self.n
        z = np.real(vals[: self.slots]) / self.delta
        return z[:length] if length is not None else z

    # -- encrypt / decrypt ------------------------------------------------
    def encrypt_poly(self, m: np.ndarray) -> CKKSCiphertext:
        if self.pk is None:
            raise RuntimeError("keygen() first")
        b, a = self.pk
        u = self._ternary()
        return CKKSCiphertext(
            np.mod(polymul(b, u, self.q) + self._noise() + m, self.q),
            np.mod(polymul(a, u, self.q) + self._noise(), self.q),
        )

    def decrypt_poly(self, ct: CKKSCiphertext) -> np.ndarray:
        if self.sk is None:
            raise RuntimeError("no secret key in this context")
        return _center(ct.c0 + polymul(ct.c1, self.sk, self.q), self.q)

    # -- homomorphic ops --------------------------------------------------
    def add(self, x: CKKSCiphertext, y: CKKSCiphertext) -> CKKSCiphertext:
        return CKKSCiphertext(np.mod(x.c0 + y.c0, self.q),
                              np.mod(x.c1 + y.c1, self.q))

    def add_plain(self, x: CKKSCiphertext, m: np.ndarray) -> CKKSCiphertext:
        return CKKSCiphertext(np.mod(x.c0 + m, self.q), x.c1)

    # -- vector API (arbitrary-length payloads) ---------------------------
    def encrypt_vector(self, vec: np.ndarray) -> List[CKKSCiphertext]:
        vec = np.asarray(vec, np.float64).ravel()
        return [
            self.encrypt_poly(self.encode(vec[i: i + self.slots]))
            for i in range(0, max(len(vec), 1), self.slots)
        ]

    def decrypt_vector(self, cts: List[CKKSCiphertext], length: int) -> np.ndarray:
        out = np.concatenate([self.decode(self.decrypt_poly(ct)) for ct in cts])
        return out[:length]

    def add_vectors(self, a: List[CKKSCiphertext],
                    b: List[CKKSCiphertext]) -> List[CKKSCiphertext]:
        if len(a) != len(b):
            raise ValueError("ciphertext vectors have different chunk counts")
        return [self.add(x, y) for x, y in zip(a, b)]
