"""CKKS homomorphic encryption (additive subset) — numpy implementation.

Parity target: the reference's TenSEAL CKKS backend
(``core/fhe/fhe_agg.py:10``). TenSEAL is unavailable here, so this module
implements the actual RLWE/CKKS algebra from scratch:

- ring R_q = Z_q[X]/(X^N + 1), negacyclic polynomial arithmetic done as
  an exact integer matmul with 16-bit limb splitting (no NTT needed at
  these sizes, and the matmul form vectorizes in numpy);
- canonical-embedding encode/decode via FFT (slots = N/2 real values,
  fixed-point scale Δ);
- RLWE keygen (ternary secret, discrete-gaussian noise), public-key
  encryption, decryption, and ciphertext + ciphertext / ciphertext +
  plaintext addition — everything encrypted FedAvg needs. (Ciphertext
  multiplication/rescaling is deliberately out of scope: aggregation is
  additive.)

Two parameter regimes:

- **demo** (``CKKSContext``, N=1024, one 31-bit prime, Δ=2^19): correct
  CKKS algebra with a real noise term, sized so exact arithmetic fits
  int64 via the O(N²) limb-split matmul — fast to construct, NOT a
  production security level.
- **secure** (``RNSCKKSContext``, N=8192, two ~30-bit NTT primes —
  logQ ≈ 60 ≪ the ≤218 the HE standard allows at N=8192/128-bit —
  Δ=2^40): RNS residue arithmetic with negacyclic NTT polynomial
  multiplication, uniform ternary secret. This is the RNS-CKKS-at-N≥8192
  profile; select it with ``fhe_profile: "secure"`` (or
  ``fhe_poly_degree >= 4096``).

Correctness bound: coefficient noise |e| ≈ a few hundred spreads over
slots by ≈ √N at decode, so slot error ≈ √N·e/Δ ≈ 6e-3 at the defaults,
and slot values must satisfy Δ·max|x| < q/2 — |x| < 2048 at Δ=2^19
(``encode`` raises beyond it). Summing K ciphertexts scales both the
value range and the noise by K.
"""
from __future__ import annotations

import logging
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_N = 1024
DEFAULT_Q = (1 << 31) - 1  # Mersenne prime, same field as core/mpc
DEFAULT_DELTA = 1 << 19
_NOISE_SIGMA = 3.2
_SECRET_HAMMING = 64  # sparse ternary secret/ephemeral → small noise

# -- native NTT kernel (same build/bind pattern as core/mpc/lcc.py) ---------
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))), "native")
_NTT_LIB_PATH = os.path.join(_NATIVE_DIR, "libntt.so")
_ntt_lib = None
_ntt_tried = False


def _load_ntt_native():
    """ctypes handle to ``native/libntt.so`` (built on demand), or None —
    callers fall back to the numpy butterfly, which computes identical
    residues."""
    global _ntt_lib, _ntt_tried
    if _ntt_tried:
        return _ntt_lib
    _ntt_tried = True
    if not os.path.exists(_NTT_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "libntt.so"],
                           check=True, capture_output=True, timeout=120)
        except Exception as e:  # pragma: no cover
            logger.info("native ntt build unavailable (%s); using numpy", e)
            return None
    try:
        import ctypes

        lib = ctypes.CDLL(_NTT_LIB_PATH)
        for fn in (lib.ntt_polymul_bcast, lib.ntt_polymul_batch):
            fn.restype = None
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
            ]
        _ntt_lib = lib
    except OSError as e:  # pragma: no cover
        logger.info("native ntt load failed (%s); using numpy", e)
        _ntt_lib = None
    return _ntt_lib


def _negacyclic_matrix(a: np.ndarray, q: int) -> np.ndarray:
    """M such that M @ b == a * b mod (X^N + 1), entries in [0, q)."""
    n = a.shape[0]
    idx = np.arange(n)
    # row k, col j: +a[k-j] for j<=k, -a[n+k-j] for j>k
    diff = idx[:, None] - idx[None, :]
    m = a[diff % n].astype(np.int64)
    m = np.where(diff < 0, (-m) % q, m % q)
    return m


def polymul(a: np.ndarray, b: np.ndarray, q: int) -> np.ndarray:
    """Exact (a*b mod X^N+1 mod q) via limb-split integer matmul.

    Entries < q < 2^31; split the matrix into 16-bit limbs so every
    partial matmul accumulates within int64 (2^15·2^31·N ≤ 2^57 for
    N ≤ 2^11).
    """
    m = _negacyclic_matrix(np.mod(a, q), q)
    b = np.mod(b, q).astype(np.int64)
    hi, lo = m >> 16, m & 0xFFFF
    part_hi = (hi @ b) % q
    part_lo = (lo @ b) % q
    return ((part_hi << 16) + part_lo) % q


def _center(x: np.ndarray, q: int) -> np.ndarray:
    x = np.mod(x, q)
    return np.where(x > q // 2, x - q, x).astype(np.float64)


class CKKSCiphertext:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: np.ndarray, c1: np.ndarray):
        self.c0 = c0
        self.c1 = c1


class CKKSContext:
    def __init__(self, n: int = DEFAULT_N, q: int = DEFAULT_Q,
                 delta: int = DEFAULT_DELTA, seed: Optional[int] = None):
        if n & (n - 1):
            raise ValueError("ring degree n must be a power of two")
        self.n = int(n)
        self.q = int(q)
        self.delta = int(delta)
        self.slots = self.n // 2
        self._rng = np.random.default_rng(seed)
        # canonical-embedding twist: evaluation at odd powers of the
        # 2N-th root ζ reduces to an FFT of (a_k · ζ^k)
        k = np.arange(self.n)
        self._zeta_pow = np.exp(1j * np.pi * k / self.n)
        self.sk: Optional[np.ndarray] = None
        self.pk: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- keys -------------------------------------------------------------
    def _ternary(self) -> np.ndarray:
        s = np.zeros(self.n, np.int64)
        idx = self._rng.choice(self.n, size=_SECRET_HAMMING, replace=False)
        s[idx] = self._rng.choice(np.array([-1, 1]), size=_SECRET_HAMMING)
        return s

    def _noise(self) -> np.ndarray:
        return np.rint(
            self._rng.normal(0.0, _NOISE_SIGMA, self.n)).astype(np.int64)

    def keygen(self) -> "CKKSContext":
        self.sk = self._ternary()
        a = self._rng.integers(0, self.q, self.n, dtype=np.int64)
        e = self._noise()
        b = np.mod(-(polymul(a, self.sk, self.q)) + e, self.q)
        self.pk = (b, a)
        return self

    # -- encode / decode (canonical embedding) ----------------------------
    def encode(self, values: np.ndarray) -> np.ndarray:
        """Real slot values (≤ N/2 of them) → integer plaintext poly."""
        values = np.asarray(values, np.float64)
        limit = self.q / (2.0 * self.delta)
        if values.size and np.abs(values).max() >= limit:
            raise ValueError(
                f"slot value {np.abs(values).max():.1f} exceeds the "
                f"CKKS range |x| < {limit:.0f} at delta={self.delta} "
                f"(a field wrap is silent — refuse instead)")
        z = np.zeros(self.slots, np.complex128)
        z[: len(values)] = values
        # conjugate-symmetric extension fixes a real polynomial
        zfull = np.concatenate([z, np.conj(z[::-1])])
        # a_k = (Δ/N) Σ_j zfull_j ζ^{-(2j+1)k}: inverse of the decode FFT
        coeffs = np.fft.fft(zfull) * np.conj(self._zeta_pow) / self.n
        return np.rint(np.real(coeffs) * self.delta).astype(np.int64)

    def decode(self, poly: np.ndarray, length: Optional[int] = None) -> np.ndarray:
        """Centered plaintext poly → real slot values."""
        vals = np.fft.ifft(np.asarray(poly, np.float64) * self._zeta_pow) * self.n
        z = np.real(vals[: self.slots]) / self.delta
        return z[:length] if length is not None else z

    # -- encrypt / decrypt ------------------------------------------------
    def encrypt_poly(self, m: np.ndarray) -> CKKSCiphertext:
        if self.pk is None:
            raise RuntimeError("keygen() first")
        b, a = self.pk
        u = self._ternary()
        return CKKSCiphertext(
            np.mod(polymul(b, u, self.q) + self._noise() + m, self.q),
            np.mod(polymul(a, u, self.q) + self._noise(), self.q),
        )

    def decrypt_poly(self, ct: CKKSCiphertext) -> np.ndarray:
        if self.sk is None:
            raise RuntimeError("no secret key in this context")
        return _center(ct.c0 + polymul(ct.c1, self.sk, self.q), self.q)

    # -- homomorphic ops --------------------------------------------------
    def add(self, x: CKKSCiphertext, y: CKKSCiphertext) -> CKKSCiphertext:
        return CKKSCiphertext(np.mod(x.c0 + y.c0, self.q),
                              np.mod(x.c1 + y.c1, self.q))

    def add_plain(self, x: CKKSCiphertext, m: np.ndarray) -> CKKSCiphertext:
        return CKKSCiphertext(np.mod(x.c0 + m, self.q), x.c1)

    # -- vector API (arbitrary-length payloads) ---------------------------
    def encrypt_vector(self, vec: np.ndarray) -> List[CKKSCiphertext]:
        vec = np.asarray(vec, np.float64).ravel()
        return [
            self.encrypt_poly(self.encode(vec[i: i + self.slots]))
            for i in range(0, max(len(vec), 1), self.slots)
        ]

    def decrypt_vector(self, cts: List[CKKSCiphertext], length: int) -> np.ndarray:
        out = np.concatenate([self.decode(self.decrypt_poly(ct)) for ct in cts])
        return out[:length]

    def add_vectors(self, a: List[CKKSCiphertext],
                    b: List[CKKSCiphertext]) -> List[CKKSCiphertext]:
        if len(a) != len(b):
            raise ValueError("ciphertext vectors have different chunk counts")
        return [self.add(x, y) for x, y in zip(a, b)]


# ---------------------------------------------------------------------------
# RNS-CKKS at production scale: NTT polynomial arithmetic over a chain of
# primes, N >= 4096.
# ---------------------------------------------------------------------------

def _is_prime(n: int) -> bool:
    """Deterministic Miller-Rabin for n < 3.3e24."""
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def find_ntt_primes(two_n: int, bits: int, count: int) -> List[int]:
    """``count`` primes q ≡ 1 (mod 2N) just below 2^bits (NTT-friendly)."""
    primes: List[int] = []
    q = ((1 << bits) - 1) // two_n * two_n + 1
    while len(primes) < count and q > (1 << (bits - 1)):
        if _is_prime(q):
            primes.append(q)
        q -= two_n
    if len(primes) < count:
        raise ValueError(f"not enough {bits}-bit NTT primes for 2N={two_n}")
    return primes


def _primitive_2n_root(q: int, two_n: int) -> int:
    """ψ of order 2N in Z_q* (exists since 2N | q-1)."""
    # factor q-1 (= 2N · m, m small for our prime sizes) by trial division
    m = q - 1
    factors = set()
    d = 2
    while d * d <= m:
        while m % d == 0:
            factors.add(d)
            m //= d
        d += 1
    if m > 1:
        factors.add(m)
    for g in range(2, 1000):
        if all(pow(g, (q - 1) // p, q) != 1 for p in factors):
            psi = pow(g, (q - 1) // two_n, q)
            return psi
    raise ValueError(f"no generator found for q={q}")


class _NTTPlan:
    """Precomputed tables for negacyclic (X^N+1) NTT mod one prime."""

    def __init__(self, q: int, n: int):
        self.q, self.n = q, n
        psi = _primitive_2n_root(q, 2 * n)
        self.psi = int(psi)
        k = np.arange(n)
        self.psi_pow = np.array(
            [pow(psi, int(i), q) for i in k], np.int64)
        psi_inv = pow(psi, q - 2, q)
        self.psi_inv_pow = np.array(
            [pow(psi_inv, int(i), q) for i in k], np.int64)
        self.n_inv = pow(n, q - 2, q)
        w = pow(psi, 2, q)  # n-th root for the cyclic core
        self.w_pows = {}
        self.w_inv_pows = {}
        length = 2
        while length <= n:
            base = pow(w, n // length, q)
            base_inv = pow(base, q - 2, q)
            self.w_pows[length] = np.array(
                [pow(base, int(i), q) for i in range(length // 2)], np.int64)
            self.w_inv_pows[length] = np.array(
                [pow(base_inv, int(i), q) for i in range(length // 2)],
                np.int64)
            length *= 2
        bits = n.bit_length() - 1
        rev = np.zeros(n, np.int64)
        for i in range(n):
            rev[i] = int(format(i, f"0{bits}b")[::-1], 2)
        self.bitrev = rev

    def _core(self, a: np.ndarray, inverse: bool) -> np.ndarray:
        q, n = self.q, self.n
        a = a[..., self.bitrev]
        length = 2
        while length <= n:
            half = length // 2
            w = self.w_inv_pows[length] if inverse else self.w_pows[length]
            shape = a.shape[:-1] + (n // length, length)
            a = a.reshape(shape)
            lo, hi = a[..., :half], a[..., half:]
            t = hi * w % q  # < 2^30 · 2^30 → fits int64
            a = np.concatenate([(lo + t) % q, (lo - t) % q], axis=-1)
            a = a.reshape(a.shape[:-2] + (n,))
            length *= 2
        return a

    def mul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """a·b mod (X^N+1, q) via ψ-twisted NTT."""
        q = self.q
        fa = self._core(a % q * self.psi_pow % q, False)
        fb = self._core(b % q * self.psi_pow % q, False)
        fc = fa * fb % q
        c = self._core(fc, True)
        return c * self.n_inv % q * self.psi_inv_pow % q

    def mul_bcast(self, fixed: np.ndarray, batch: np.ndarray) -> np.ndarray:
        """``fixed[N] · batch[B, N]`` mod (X^N+1, q) — the encrypt/decrypt
        hot path (one key poly against every ciphertext chunk of a
        payload). Dispatches to ``native/ntt.cpp`` when the C++ kernel is
        available (≈20× the numpy butterfly on N=8192); the numpy fallback
        broadcasts through the same ``mul`` math. Results are bit-identical
        either way (exact modular arithmetic)."""
        batch = np.ascontiguousarray(batch, np.int64)
        if batch.ndim == 1:
            batch = batch[None]
        lib = _load_ntt_native()
        if lib is None:
            return self.mul(np.asarray(fixed, np.int64), batch)
        import ctypes

        fixed = np.ascontiguousarray(fixed, np.int64)
        out = np.empty_like(batch)
        lib.ntt_polymul_bcast(
            fixed.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            batch.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            batch.shape[0], self.n, self.q, self.psi,
        )
        return out


class RNSCKKSContext:
    """CKKS additive subset over an RNS basis with NTT arithmetic.

    Same public surface as :class:`CKKSContext` (keygen / encode /
    decode / encrypt_poly / decrypt_poly / add / add_plain / vector
    API); ciphertext polys are residue matrices ``[k_primes, N]``.
    """

    def __init__(self, n: int = 8192, prime_bits: int = 30,
                 n_primes: int = 2, delta: int = 1 << 40,
                 seed: Optional[int] = None):
        if n & (n - 1):
            raise ValueError("ring degree n must be a power of two")
        if n_primes != 2:
            raise ValueError("int64 CRT path supports exactly 2 primes")
        self.n = int(n)
        self.delta = int(delta)
        self.primes = find_ntt_primes(2 * n, prime_bits, n_primes)
        self.q = self.primes[0] * self.primes[1]  # composite modulus Q
        self.plans = [_NTTPlan(q, n) for q in self.primes]
        self.slots = n // 2
        self._rng = np.random.default_rng(seed)
        k = np.arange(self.n)
        self._zeta_pow = np.exp(1j * np.pi * k / self.n)
        self.sk: Optional[np.ndarray] = None          # [N] small ints
        self.pk: Optional[Tuple[np.ndarray, np.ndarray]] = None  # [k,N] each

    # -- residue helpers --------------------------------------------------
    def _to_rns(self, small: np.ndarray) -> np.ndarray:
        """Small signed ints [N] → residues [k, N]."""
        return np.stack([np.mod(small, q) for q in self.primes])

    def _from_rns_centered(self, r: np.ndarray) -> np.ndarray:
        """Residues [k, N] → centered representative of Z_Q, float64.

        CRT: x = r1 + q1·((r2-r1)·q1⁻¹ mod q2). Every step INCLUDING the
        reconstruction q1·t (< 2^61) and the centering subtraction is
        done in exact int64 — converting to float64 before centering
        would cost up to 2^7 of rounding per coefficient.
        """
        q1, q2 = self.primes
        inv_q1 = pow(q1 % q2, q2 - 2, q2)
        t = (r[1] - r[0]) % q2 * inv_q1 % q2
        x = r[0] + np.int64(q1) * t                 # exact, < 2^61
        x = np.where(x > self.q // 2, x - np.int64(self.q), x)
        return x.astype(np.float64)

    def _polymul_rns(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        return np.stack([p.mul(a[i], b[i])
                         for i, p in enumerate(self.plans)])

    # -- keys -------------------------------------------------------------
    def _ternary(self) -> np.ndarray:
        # uniform ternary secret — the standard-compliant choice at this N
        return self._rng.integers(-1, 2, self.n).astype(np.int64)

    def _noise(self) -> np.ndarray:
        return np.rint(
            self._rng.normal(0.0, _NOISE_SIGMA, self.n)).astype(np.int64)

    def keygen(self) -> "RNSCKKSContext":
        self.sk = self._ternary()
        s = self._to_rns(self.sk)
        a = np.stack([self._rng.integers(0, q, self.n, dtype=np.int64)
                      for q in self.primes])
        e = self._to_rns(self._noise())
        b = np.stack([
            np.mod(-(self.plans[i].mul(a[i], s[i])) + e[i], self.primes[i])
            for i in range(len(self.primes))
        ])
        self.pk = (b, a)
        return self

    # -- encode / decode --------------------------------------------------
    def encode(self, values: np.ndarray) -> np.ndarray:
        """Real slot values (≤ N/2) → integer plaintext poly [N]."""
        values = np.asarray(values, np.float64)
        limit = self.q / (2.0 * self.delta)
        if values.size and np.abs(values).max() >= limit:
            raise ValueError(
                f"slot value {np.abs(values).max():.1f} exceeds the CKKS "
                f"range |x| < {limit:.0f} at delta={self.delta}")
        z = np.zeros(self.slots, np.complex128)
        z[: len(values)] = values
        zfull = np.concatenate([z, np.conj(z[::-1])])
        coeffs = np.fft.fft(zfull) * np.conj(self._zeta_pow) / self.n
        return np.rint(np.real(coeffs) * self.delta).astype(np.int64)

    def decode(self, poly: np.ndarray,
               length: Optional[int] = None) -> np.ndarray:
        vals = np.fft.ifft(np.asarray(poly, np.float64)
                           * self._zeta_pow) * self.n
        z = np.real(vals[: self.slots]) / self.delta
        return z[:length] if length is not None else z

    # -- encrypt / decrypt ------------------------------------------------
    def encrypt_poly(self, m: np.ndarray) -> CKKSCiphertext:
        if self.pk is None:
            raise RuntimeError("keygen() first")
        b, a = self.pk
        u = self._to_rns(self._ternary())
        # ONE noise draw reduced into every residue ring — independent
        # draws per prime would not represent a single ring element.
        # (m's coeffs, up to Δ·|x| ≈ 2^50, exceed one prime: same rule.)
        noise0 = self._noise() + m
        e0 = np.stack([np.mod(noise0, q) for q in self.primes])
        c0 = np.mod(self._polymul_rns(b, u) + e0,
                    np.asarray(self.primes)[:, None])
        c1 = np.mod(self._polymul_rns(a, u)
                    + self._to_rns(self._noise()),
                    np.asarray(self.primes)[:, None])
        return CKKSCiphertext(c0, c1)

    def decrypt_poly(self, ct: CKKSCiphertext) -> np.ndarray:
        if self.sk is None:
            raise RuntimeError("no secret key in this context")
        s = self._to_rns(self.sk)
        m = np.mod(ct.c0 + self._polymul_rns(ct.c1, s),
                   np.asarray(self.primes)[:, None])
        return self._from_rns_centered(m)

    # -- homomorphic ops --------------------------------------------------
    def add(self, x: CKKSCiphertext, y: CKKSCiphertext) -> CKKSCiphertext:
        qcol = np.asarray(self.primes)[:, None]
        return CKKSCiphertext(np.mod(x.c0 + y.c0, qcol),
                              np.mod(x.c1 + y.c1, qcol))

    def add_plain(self, x: CKKSCiphertext, m: np.ndarray) -> CKKSCiphertext:
        qcol = np.asarray(self.primes)[:, None]
        return CKKSCiphertext(np.mod(x.c0 + self._to_rns(m), qcol), x.c1)

    # -- vector API (same shape as CKKSContext) ---------------------------
    add_vectors = CKKSContext.add_vectors

    # -- batched vector API (the hot path for LoRA-sized payloads) --------
    # A 10M-param adapter payload is ~2.4k ciphertexts; per-ct python
    # dispatch dominated the numpy profile, so encode/encrypt/decrypt all
    # run batched: one FFT over [B, N], one native-NTT call per (prime,
    # key-poly) against the whole batch (native/ntt.cpp; numpy butterfly
    # fallback is bit-identical). Secure-profile round cost is measured
    # in tools/fhe_bench.py / PERF_NOTES.
    def encode_batch(self, values: np.ndarray) -> np.ndarray:
        """[B, ≤slots] real slot values → [B, N] integer plaintext polys."""
        values = np.asarray(values, np.float64)
        limit = self.q / (2.0 * self.delta)
        if values.size and np.abs(values).max() >= limit:
            raise ValueError(
                f"slot value {np.abs(values).max():.1f} exceeds the CKKS "
                f"range |x| < {limit:.0f} at delta={self.delta}")
        z = np.zeros((values.shape[0], self.slots), np.complex128)
        z[:, : values.shape[1]] = values
        zfull = np.concatenate([z, np.conj(z[:, ::-1])], axis=1)
        coeffs = np.fft.fft(zfull, axis=-1) * np.conj(self._zeta_pow) / self.n
        return np.rint(np.real(coeffs) * self.delta).astype(np.int64)

    def encrypt_vector(self, vec: np.ndarray) -> List[CKKSCiphertext]:
        vec = np.asarray(vec, np.float64).ravel()
        n_ct = max(1, -(-max(len(vec), 1) // self.slots))
        padded = np.zeros(n_ct * self.slots, np.float64)
        padded[: len(vec)] = vec
        m = self.encode_batch(padded.reshape(n_ct, self.slots))
        b, a = self.pk
        u = self._rng.integers(-1, 2, (n_ct, self.n)).astype(np.int64)
        e0 = np.rint(self._rng.normal(
            0.0, _NOISE_SIGMA, (n_ct, self.n))).astype(np.int64) + m
        e1 = np.rint(self._rng.normal(
            0.0, _NOISE_SIGMA, (n_ct, self.n))).astype(np.int64)
        k = len(self.primes)
        c0 = np.empty((k, n_ct, self.n), np.int64)
        c1 = np.empty_like(c0)
        for i, (plan, q) in enumerate(zip(self.plans, self.primes)):
            c0[i] = np.mod(plan.mul_bcast(b[i], u) + e0, q)
            c1[i] = np.mod(plan.mul_bcast(a[i], u) + e1, q)
        return [CKKSCiphertext(np.ascontiguousarray(c0[:, j]),
                               np.ascontiguousarray(c1[:, j]))
                for j in range(n_ct)]

    def decrypt_vector(self, cts: List[CKKSCiphertext],
                       length: int) -> np.ndarray:
        if self.sk is None:
            raise RuntimeError("no secret key in this context")
        s = self._to_rns(self.sk)
        c0 = np.stack([np.asarray(ct.c0, np.int64) for ct in cts])  # [B,k,N]
        c1 = np.stack([np.asarray(ct.c1, np.int64) for ct in cts])
        k, n_ct = len(self.primes), len(cts)
        m = np.empty((k, n_ct, self.n), np.int64)
        for i, (plan, q) in enumerate(zip(self.plans, self.primes)):
            m[i] = np.mod(c0[:, i] + plan.mul_bcast(s[i], c1[:, i]), q)
        centered = self._from_rns_centered(m)  # CRT works batched: [B, N]
        vals = np.fft.ifft(centered * self._zeta_pow, axis=-1) * self.n
        out = (np.real(vals[:, : self.slots]) / self.delta).ravel()
        return out[:length]
