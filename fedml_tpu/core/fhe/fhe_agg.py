"""FedMLFHE — homomorphic-encryption aggregation facade.

Parity: ``core/fhe/fhe_agg.py:10`` (TenSEAL CKKS in the reference). TenSEAL
is not available in this environment, so the default backend is a
deterministic additive-masking scheme with the same algebra (ciphertexts can
be summed; decryption removes the aggregate mask) — adequate for protocol
and pipeline testing. A real CKKS backend can be slotted in behind the same
``fhe_enc/fhe_dec/fhe_fedavg`` surface when the library is present.
"""
from __future__ import annotations

import logging
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_stack, weighted_tree_sum

Pytree = Any


class _AdditiveMaskCipher:
    """Toy additive-HE stand-in: enc(x) = x + PRG(key); sum of ciphertexts
    decrypts with the sum of masks. NOT cryptographically meaningful on its
    own (see core/mpc for the real SecAgg protocols); exists to exercise the
    FHE code path without TenSEAL."""

    def __init__(self, seed: int):
        self.seed = seed
        self._counter = 0

    def _mask_for(self, counter: int, leaf: jax.Array) -> jax.Array:
        key = jax.random.fold_in(jax.random.key(self.seed), counter)
        return jax.random.normal(key, leaf.shape, dtype=leaf.dtype)

    def enc(self, params: Pytree) -> Pytree:
        self._counter += 1
        c = self._counter
        leaves, treedef = jax.tree.flatten(params)
        out = [leaf + self._mask_for(c * 1000 + i, leaf) for i, leaf in enumerate(leaves)]
        tagged = jax.tree.unflatten(treedef, out)
        return {"__fhe__": True, "counter": c, "payload": tagged}

    def dec(self, cipher: Any) -> Pytree:
        if not (isinstance(cipher, dict) and cipher.get("__fhe__")):
            return cipher
        c = cipher["counter"]
        leaves, treedef = jax.tree.flatten(cipher["payload"])
        out = [leaf - self._mask_for(c * 1000 + i, leaf) for i, leaf in enumerate(leaves)]
        return jax.tree.unflatten(treedef, out)


class FedMLFHE:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self._cipher = None

    @classmethod
    def get_instance(cls) -> "FedMLFHE":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_fhe", False))
        if self.is_enabled:
            self._cipher = _AdditiveMaskCipher(int(getattr(args, "random_seed", 0)))
            logging.info("FHE enabled (additive-mask backend)")

    def is_fhe_enabled(self) -> bool:
        return self.is_enabled

    def fhe_enc(self, params: Pytree) -> Pytree:
        return self._cipher.enc(params)

    def fhe_dec(self, params: Pytree) -> Pytree:
        return self._cipher.dec(params)

    def fhe_fedavg(self, raw_client_model_list: List[Tuple[int, Pytree]]) -> Pytree:
        # Weighted mean over ciphertexts: decrypt each (masks are server-side
        # in this stand-in), then average — mirrors the encrypted FedAvg shape.
        counts = jnp.asarray([float(num) for num, _ in raw_client_model_list])
        weights = counts / jnp.sum(counts)
        plains = [self._cipher.dec(p) for _, p in raw_client_model_list]
        return weighted_tree_sum(tree_stack(plains), weights)

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
