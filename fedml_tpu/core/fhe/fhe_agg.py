"""FedMLFHE — homomorphic-encryption aggregation facade.

Parity: ``core/fhe/fhe_agg.py:10`` (TenSEAL CKKS in the reference).
Backend: the in-tree CKKS implementation (:mod:`fedml_tpu.core.fhe.ckks`
— real RLWE/CKKS algebra in numpy; see its docstring for parameters and
noise bounds). The deployment model mirrors the reference's shared
pickled TenSEAL context: every participant derives the SAME context
(keys included) from ``fhe_key_seed``/``random_seed``, clients encrypt
their updates, the server aggregates **without decrypting** (ciphertext
scalar-times-weight + ciphertext adds), and clients decrypt the
aggregate on receipt (``ClientTrainer.on_before_local_training``).

Wire format of an encrypted pytree (plain dict of numpy arrays, so the
pickle-free serializer ships it unchanged):

    {"__fhe_ckks__": True, "cts": [{"c0": int64[N], "c1": int64[N]}...],
     "length": D, "scale": float, "template": zeros-like pytree}
"""
from __future__ import annotations

import logging
from typing import Any, List, Tuple

import numpy as np

Pytree = Any

_WEIGHT_SCALE = 256  # plaintext weights quantized to 1/256


def _is_cipher(obj: Any) -> bool:
    return isinstance(obj, dict) and obj.get("__fhe_ckks__") is True


class FedMLFHE:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.ctx = None

    @classmethod
    def get_instance(cls) -> "FedMLFHE":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_fhe", False))
        if not self.is_enabled:
            return
        from fedml_tpu.core.fhe.ckks import CKKSContext, RNSCKKSContext

        explicit_seed = getattr(args, "fhe_key_seed", None)
        seed = (int(explicit_seed) if explicit_seed is not None
                else int(getattr(args, "random_seed", 0))) + 40487
        profile = str(getattr(args, "fhe_profile", "demo")).lower()
        degree = int(getattr(args, "fhe_poly_degree", 0) or 0)
        if profile == "secure" or degree >= 4096:
            # RNS-CKKS at N≥8192: NTT arithmetic, two ~30-bit primes —
            # inside the HE-standard security envelope for this N.
            # Keys come from OS entropy UNLESS fhe_key_seed is explicitly
            # set: deriving sk from the shared run config would let the
            # aggregator regenerate it and decrypt client updates, voiding
            # the lattice security (ADVICE r4). Multi-process deployments
            # that need every party to hold the same context must
            # distribute a key seed out of band (docs/trust_stack.md).
            self.ctx = RNSCKKSContext(
                n=degree or 8192,
                delta=int(getattr(args, "fhe_scale", 1 << 40)),
                seed=seed if explicit_seed is not None else None,
            ).keygen()
            logging.info("FHE enabled: RNS-CKKS n=%d primes=%s logQ=%d",
                         self.ctx.n, self.ctx.primes,
                         self.ctx.q.bit_length())
        else:
            # demo-scale params (N=1024, one 31-bit prime): real CKKS
            # algebra, NOT a production security level — fast for tests
            self.ctx = CKKSContext(
                n=degree or 1024,
                delta=int(getattr(args, "fhe_scale", 1 << 19)),
                seed=seed,
            ).keygen()
            logging.info(
                "FHE enabled: CKKS n=%d slots=%d (DEMO-SCALE parameters; "
                "set fhe_profile: secure for RNS-CKKS at N=8192)",
                self.ctx.n, self.ctx.slots)

    def is_fhe_enabled(self) -> bool:
        return self.is_enabled

    # -- pytree <-> cipher -------------------------------------------------
    def fhe_enc(self, params: Pytree) -> Pytree:
        import jax

        from fedml_tpu.utils.tree import tree_flatten_vector

        if _is_cipher(params):
            return params
        vec = np.asarray(tree_flatten_vector(params), np.float64)
        # aggregation multiplies ciphertexts by quantized weights (Σ≈256),
        # shrinking the safe range by that factor — enforce the POST-
        # aggregation bound here, where the plaintext is still visible
        # (after fhe_fedavg a wrap would be silent garbage)
        agg_limit = self.ctx.q / (2.0 * self.ctx.delta * _WEIGHT_SCALE * 2.0)
        peak = float(np.abs(vec).max()) if vec.size else 0.0
        if peak >= agg_limit:
            raise ValueError(
                f"model weight magnitude {peak:.2f} exceeds the encrypted-"
                f"aggregation range |x| < {agg_limit:.2f}; lower fhe_scale "
                f"(delta) or clip the update before encryption")
        cts = self.ctx.encrypt_vector(vec)
        template = jax.tree.map(
            lambda x: np.zeros(np.shape(x), np.float32), params)
        return {
            "__fhe_ckks__": True,
            "cts": [{"c0": ct.c0, "c1": ct.c1} for ct in cts],
            "length": int(vec.size),
            "scale": float(self.ctx.delta),
            "template": template,
        }

    def fhe_dec(self, params: Pytree) -> Pytree:
        from fedml_tpu.core.fhe.ckks import CKKSCiphertext
        from fedml_tpu.utils.tree import tree_unflatten_vector

        if not _is_cipher(params):
            return params
        cts = [CKKSCiphertext(np.asarray(c["c0"], np.int64),
                              np.asarray(c["c1"], np.int64))
               for c in params["cts"]]
        save_delta = self.ctx.delta
        try:
            # effective scale after plaintext-weight multiplication
            self.ctx.delta = params.get("scale", save_delta)
            vec = self.ctx.decrypt_vector(cts, int(params["length"]))
        finally:
            self.ctx.delta = save_delta
        import jax.numpy as jnp

        return tree_unflatten_vector(jnp.asarray(vec, jnp.float32),
                                     params["template"])

    # -- encrypted FedAvg --------------------------------------------------
    def fhe_fedavg(self, raw_client_model_list: List[Tuple[int, Pytree]]) -> Pytree:
        """Count-weighted FedAvg over ciphertexts, never decrypting:
        acc = Σ round(w_k·256)·ct_k, recorded at scale Δ·Σ round(w_k·256)
        so decryption yields the (quantized-)weighted mean directly."""
        ciphers = [p for _, p in raw_client_model_list]
        if not all(_is_cipher(p) for p in ciphers):
            raise ValueError("fhe_fedavg expects encrypted client payloads")
        counts = np.asarray([float(n) for n, _ in raw_client_model_list])
        weights = counts / counts.sum()
        wq = np.maximum(1, np.rint(weights * _WEIGHT_SCALE)).astype(np.int64)

        q = self.ctx.q
        acc = None
        for w, cipher in zip(wq, ciphers):
            scaled = [{"c0": np.mod(c["c0"] * int(w), q),
                       "c1": np.mod(c["c1"] * int(w), q)}
                      for c in cipher["cts"]]
            if acc is None:
                acc = scaled
            else:
                acc = [{"c0": np.mod(a["c0"] + s["c0"], q),
                        "c1": np.mod(a["c1"] + s["c1"], q)}
                       for a, s in zip(acc, scaled)]
        first = ciphers[0]
        return {
            "__fhe_ckks__": True,
            "cts": acc,
            "length": first["length"],
            "scale": float(first["scale"]) * float(np.sum(wq)),
            "template": first["template"],
        }

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
