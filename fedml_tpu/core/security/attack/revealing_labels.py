"""Revealing labels from gradients (label-leakage attack).

Parity: ``core/security/attack/revealing_labels_from_gradients_attack.py``
(Wainakh et al. / iDLG-style label restoration). For softmax
cross-entropy the classifier-layer gradient decomposes as
g_c = Σ_i (p_c^i − 1[y_i = c]): every occurrence of class c subtracts
exactly 1 from row/bias c while the softmax terms add only p_c ∈ (0,1).
The attack inverts that: estimate Σ_i p_c^i (uniform 1/C prior at an
untrained model, the paper's setting) and round

    count_c = round(B·(1/C) − B·g_c)            (bias gradient)

where g_c is the MEAN gradient over the batch of size B. Without a bias
term the per-class score falls back to the weight-gradient row sums,
whose sign/magnitude carry the same signal.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack

Pytree = Any


@register("revealing_labels")
@register("revealing_labels_from_gradients")
class RevealingLabelsAttack(BaseAttack):
    is_reconstruct = True

    def __init__(self, args: Any):
        super().__init__(args)

    def reconstruct_data(self, a_gradient: Pytree,
                         extra_auxiliary_info: Any = None) -> Dict[int, int]:
        """Recover the victim batch's label histogram.

        ``extra_auxiliary_info``: {"batch_size": B, "num_classes": C,
        "bias_grad": mean bias gradient [C]  (or "weight_grad": [F, C] /
        [C, F] classifier weight gradient)}.
        Returns {class → estimated count}, Σ counts == B.
        """
        info = extra_auxiliary_info or {}
        batch = int(info["batch_size"])
        num_classes = int(info["num_classes"])
        g = info.get("bias_grad")
        if g is None:
            wg = np.asarray(info["weight_grad"], np.float64)
            # orient to [.., C] and collapse the feature axis: row sums of
            # the classifier gradient behave like a scaled bias gradient
            if wg.shape[0] == num_classes and wg.shape[-1] != num_classes:
                wg = wg.T
            g = wg.sum(axis=0)
        g = np.asarray(g, np.float64)
        # count_c ≈ B/C − B·g_c, projected to a valid histogram of size B
        raw = batch / num_classes - batch * g
        counts = np.maximum(0, np.rint(raw)).astype(int)
        # repair rounding drift so Σ counts == B exactly: add/remove where
        # the unrounded residual points (largest fractional surplus /
        # smallest count first). Terminates: adding is always possible,
        # and drift < 0 implies some count > 0 each pass.
        drift = batch - int(counts.sum())
        resid = raw - counts
        order = np.argsort(-resid) if drift > 0 else np.argsort(resid)
        while drift != 0:
            progressed = False
            for c in order:
                if drift == 0:
                    break
                step = 1 if drift > 0 else -1
                if counts[c] + step >= 0:
                    counts[c] += step
                    drift -= step
                    progressed = True
            if not progressed:  # all counts 0 and drift < 0: impossible,
                break           # but never loop forever on bad input
        return {c: int(counts[c]) for c in range(num_classes)}
