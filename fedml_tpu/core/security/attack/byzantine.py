"""Byzantine attack: replace a subset of client updates with zeros, random
noise, or sign-flipped values.

Parity: ``core/security/attack/byzantine_attack.py``.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack
from fedml_tpu.utils.tree import tree_scale

Pytree = Any


@register("byzantine")
class ByzantineAttack(BaseAttack):
    is_model_attack = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.byzantine_client_num = int(getattr(args, "byzantine_client_num", 1))
        self.attack_mode = str(getattr(args, "attack_mode", "random")).lower()
        self._seed = int(getattr(args, "random_seed", 0)) + 31337
        self._counter = 0

    def attack_model(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        k = min(self.byzantine_client_num, len(raw_client_grad_list))
        out = list(raw_client_grad_list)
        for i in range(k):
            n, params = out[i]
            if self.attack_mode == "zero":
                evil = tree_scale(params, 0.0)
            elif self.attack_mode == "flip":
                evil = tree_scale(params, -1.0)
            else:  # random
                self._counter += 1
                key = jax.random.fold_in(jax.random.key(self._seed), self._counter)
                leaves, treedef = jax.tree.flatten(params)
                keys = jax.random.split(key, len(leaves))
                evil = jax.tree.unflatten(
                    treedef,
                    [jax.random.normal(kk, l.shape, dtype=l.dtype) for l, kk in zip(leaves, keys)],
                )
            out[i] = (n, evil)
        return out
