"""Backdoor (trigger-pattern) data poisoning.

Parity: ``core/security/attack/backdoor_attack.py`` (+ edge-case variant):
stamp a pixel trigger onto a fraction of samples and relabel them to the
backdoor target.
"""
from __future__ import annotations

from typing import Any

import numpy as np

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack


@register("backdoor")
class BackdoorAttack(BaseAttack):
    is_data_attack = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.target_class = int(getattr(args, "backdoor_target_class", 0))
        self.ratio = float(getattr(args, "poisoned_ratio", 0.2))
        self.trigger_value = float(getattr(args, "trigger_value", 1.0))
        self.trigger_size = int(getattr(args, "trigger_size", 3))
        self._rng = np.random.default_rng(int(getattr(args, "random_seed", 0)) + 23)

    def poison_data(self, dataset: Any) -> Any:
        x, y = np.array(dataset[0], copy=True), np.array(dataset[1], copy=True)
        n = len(y)
        idx = self._rng.choice(n, size=int(self.ratio * n), replace=False)
        t = self.trigger_size
        if x.ndim >= 3:  # image batch [N, H, W, ...] — stamp corner patch
            x[idx, :t, :t, ...] = self.trigger_value
        else:  # flat features — stamp leading coords
            x[idx, :t] = self.trigger_value
        y[idx] = self.target_class
        return (x, y)


@register("edge_case_backdoor")
class EdgeCaseBackdoorAttack(BackdoorAttack):
    """Edge-case backdoor (Wang et al., NeurIPS'20): poison with inputs from
    the tail of the data distribution — samples far from the local data
    mean — relabeled to the target class. Unlike the trigger patch, the
    poisons are valid-looking rare inputs, which evades norm-based
    defenses. Parity: ``core/security/attack/edge_case_backdoor_attack.py``.
    """

    def poison_data(self, dataset: Any) -> Any:
        x, y = np.array(dataset[0], copy=True), np.array(dataset[1], copy=True)
        n = len(y)
        n_poison = max(1, int(self.ratio * n))
        flat = x.reshape(n, -1).astype(np.float64)
        center = flat.mean(axis=0)
        dist = np.linalg.norm(flat - center[None], axis=1)
        tail = np.argsort(dist)[-n_poison:]  # the distribution's edge cases
        # amplify the edge samples outward and pin them to the target label
        x[tail] = x[tail] + (x[tail] - center.reshape(x.shape[1:]).astype(x.dtype))
        y[tail] = self.target_class
        return (x, y)
