"""DLG / InvertGradient — gradient-leakage data reconstruction.

Parity: ``core/security/attack/dlg_attack.py`` / ``invert_gradient_attack.py``
(Zhu et al. NeurIPS'19; Geiping et al. NeurIPS'20). TPU-native twist: the
inner optimization (match dummy-data gradients to the observed gradient) is
a jitted ``optax.adam`` loop — gradient-of-gradient via ``jax.grad`` over the
model's loss, no torch autograd graph surgery needed.
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack

Pytree = Any


@register("dlg")
@register("invert_gradient")
class DLGAttack(BaseAttack):
    is_reconstruct = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.iters = int(getattr(args, "dlg_iters", 300))
        self.lr = float(getattr(args, "dlg_lr", 0.1))
        self.use_cosine = bool(getattr(args, "dlg_cosine", True))
        self._seed = int(getattr(args, "random_seed", 0)) + 99991

    def reconstruct_data(
        self,
        a_gradient: Pytree,
        extra_auxiliary_info: Any = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Recover (x, y-logits) from an observed per-example gradient.

        ``extra_auxiliary_info`` must provide:
          loss_grad_fn(params, x, y_soft) -> gradient pytree
          params, x_shape, num_classes
        """
        loss_grad_fn: Callable = extra_auxiliary_info["loss_grad_fn"]
        params = extra_auxiliary_info["params"]
        x_shape = tuple(extra_auxiliary_info["x_shape"])
        num_classes = int(extra_auxiliary_info["num_classes"])

        key = jax.random.key(self._seed)
        kx, ky = jax.random.split(key)
        dummy_x = jax.random.normal(kx, x_shape, dtype=jnp.float32)
        dummy_y = jax.random.normal(ky, (x_shape[0], num_classes), dtype=jnp.float32)

        target_leaves = [g.astype(jnp.float32) for g in jax.tree.leaves(a_gradient)]

        def match_loss(xy):
            dx, dy = xy
            g = loss_grad_fn(params, dx, jax.nn.softmax(dy))
            leaves = [l.astype(jnp.float32) for l in jax.tree.leaves(g)]
            if self.use_cosine:
                num = sum(jnp.vdot(a, b) for a, b in zip(leaves, target_leaves))
                na = jnp.sqrt(sum(jnp.vdot(a, a) for a in leaves))
                nb = jnp.sqrt(sum(jnp.vdot(b, b) for b in target_leaves))
                return 1.0 - num / (na * nb + 1e-12)
            return sum(jnp.sum((a - b) ** 2) for a, b in zip(leaves, target_leaves))

        opt = optax.adam(self.lr)
        state = opt.init((dummy_x, dummy_y))

        @jax.jit
        def step(carry, _):
            xy, st = carry
            loss, grads = jax.value_and_grad(match_loss)(xy)
            updates, st = opt.update(grads, st)
            xy = optax.apply_updates(xy, updates)
            return (xy, st), loss

        (xy, _), _ = jax.lax.scan(step, ((dummy_x, dummy_y), state), None, length=self.iters)
        dx, dy = xy
        return dx, jax.nn.softmax(dy)
