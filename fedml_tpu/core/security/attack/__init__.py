"""Attack registry (for defense CI and research).

Parity target: ``core/security/attack/*.py`` (11 files): byzantine,
label-flipping, backdoor (+ model replacement), and DLG gradient-leak
reconstruction.
"""
from __future__ import annotations

from typing import Any

_REGISTRY = {}


def register(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def create_attacker(name: str, args: Any):
    from fedml_tpu.core.security.attack import (  # noqa: F401
        backdoor,
        byzantine,
        dlg,
        label_flipping,
        lazy_worker,
        model_replacement,
        revealing_labels,
    )

    key = name.strip().lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown attack {name!r}; available: {sorted(_REGISTRY)}")
    return _REGISTRY[key](args)


def available_attacks() -> list[str]:
    return sorted(_REGISTRY)
