"""Lazy-worker attack — free-riding clients that skip training.

Parity: ``core/security/attack/lazy_worker.py`` in the reference (the only
*fault-injection*-style attack it ships): a lazy client uploads the global
model it received — optionally with small gaussian camouflage noise so a
naive exact-duplicate check misses it — instead of a trained update.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax
import numpy as np

from fedml_tpu.core.alg_frame.params import Context
from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack

Pytree = Any


@register("lazy_worker")
class LazyWorkerAttack(BaseAttack):
    is_model_attack = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.n_lazy = int(getattr(args, "lazy_worker_num", 1))
        self.camouflage_std = float(getattr(args, "lazy_camouflage_std", 1e-3))
        self._rng = np.random.default_rng(
            int(getattr(args, "random_seed", 0)) + 41
        )

    def attack_model(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        base = extra_auxiliary_info
        if base is None:
            base = Context().get("global_model_for_defense")
        if base is None:  # nothing to free-ride on: no-op
            return raw_client_grad_list
        out = list(raw_client_grad_list)
        std = self.camouflage_std
        for i in range(min(self.n_lazy, len(out))):
            n, _ = out[i]
            lazy = jax.tree.map(
                lambda x: np.asarray(x)
                + self._rng.normal(0.0, std, np.shape(x)).astype(np.asarray(x).dtype)
                if np.asarray(x).dtype.kind == "f" else np.asarray(x),
                base,
            )
            out[i] = (n, lazy)
        return out
