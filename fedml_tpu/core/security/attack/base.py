"""Attack base class."""
from __future__ import annotations

from typing import Any, List, Tuple

Pytree = Any


class BaseAttack:
    is_data_attack = False
    is_model_attack = False
    is_reconstruct = False

    def __init__(self, args: Any):
        self.args = args

    def poison_data(self, dataset: Any) -> Any:
        return dataset

    def attack_model(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        return raw_client_grad_list

    def reconstruct_data(self, a_gradient: Pytree, extra_auxiliary_info: Any = None):
        raise NotImplementedError
