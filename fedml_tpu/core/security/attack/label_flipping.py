"""Label-flipping data poisoning.

Parity: ``core/security/attack/label_flipping_attack.py``: flip labels from
``original_class`` to ``target_class`` (or random permutation when
unspecified) on the attacker's local dataset.

Datasets here are ``(x, y)`` numpy pairs (see fedml_tpu.data.dataset).
"""
from __future__ import annotations

from typing import Any

import numpy as np

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack


@register("label_flipping")
class LabelFlippingAttack(BaseAttack):
    is_data_attack = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.original_class = getattr(args, "original_class_list", None)
        self.target_class = getattr(args, "target_class_list", None)
        self.ratio = float(getattr(args, "poisoned_ratio", 1.0))
        self._rng = np.random.default_rng(int(getattr(args, "random_seed", 0)) + 17)

    def poison_data(self, dataset: Any) -> Any:
        x, y = dataset[0], np.array(dataset[1])
        n = len(y)
        n_poison = int(self.ratio * n)
        idx = self._rng.choice(n, size=n_poison, replace=False)
        if self.original_class is not None and self.target_class is not None:
            orig = np.atleast_1d(self.original_class)
            targ = np.atleast_1d(self.target_class)
            for o, t in zip(orig, targ):
                mask = np.isin(idx, np.where(y == o)[0])
                y[idx[mask]] = t
        else:
            num_classes = int(y.max()) + 1 if n else 0
            y[idx] = (y[idx] + 1) % max(1, num_classes)
        return (x, y)
