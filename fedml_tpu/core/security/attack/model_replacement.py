"""Model-replacement (scaled backdoor) attack — Bagdasaryan et al.

Parity: ``core/security/attack/model_replacement_attack.py``: scale the
attacker's update by ~N/eta so it survives averaging.
"""
from __future__ import annotations

from typing import Any, List, Tuple

from fedml_tpu.core.security.attack import register
from fedml_tpu.core.security.attack.base import BaseAttack
from fedml_tpu.utils.tree import tree_axpy, tree_sub

Pytree = Any


@register("model_replacement")
class ModelReplacementAttack(BaseAttack):
    is_model_attack = True

    def __init__(self, args: Any):
        super().__init__(args)
        self.scale = float(getattr(args, "replacement_scale", 0.0))  # 0 → auto N

    def attack_model(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        if not raw_client_grad_list:
            return raw_client_grad_list
        gamma = self.scale or float(len(raw_client_grad_list))
        n, params = raw_client_grad_list[0]
        if extra_auxiliary_info is not None:
            # global + gamma * (params - global)
            delta = tree_sub(params, extra_auxiliary_info)
            boosted = tree_axpy(gamma, delta, extra_auxiliary_info)
        else:
            boosted = tree_axpy(gamma - 1.0, params, params)
        out = list(raw_client_grad_list)
        out[0] = (n, boosted)
        return out
