"""FedMLAttacker — adversarial-injection singleton (CI / research use).

Parity: ``core/security/fedml_attacker.py:14``. Attacks are used to *test*
defenses; they are enabled only via explicit config (``enable_attack``).
"""
from __future__ import annotations

import logging
from typing import Any, List, Optional, Tuple

Pytree = Any


class FedMLAttacker:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.attack_type: Optional[str] = None
        self.attacker = None

    @classmethod
    def get_instance(cls) -> "FedMLAttacker":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_attack", False))
        if not self.is_enabled:
            return
        self.attack_type = str(getattr(args, "attack_type", "")).strip().lower()
        from fedml_tpu.core.security.attack import create_attacker

        self.attacker = create_attacker(self.attack_type, args)
        logging.info("attack enabled: %s", self.attack_type)

    # -- predicates (reference surface) ----------------------------------
    def is_attack_enabled(self) -> bool:
        return self.is_enabled

    def is_data_poisoning_attack(self) -> bool:
        return self.is_enabled and getattr(self.attacker, "is_data_attack", False)

    def is_model_attack(self) -> bool:
        return self.is_enabled and getattr(self.attacker, "is_model_attack", False)

    def is_reconstruct_data_attack(self) -> bool:
        return self.is_enabled and getattr(self.attacker, "is_reconstruct", False)

    def is_to_poison_data(self) -> bool:
        return self.is_data_poisoning_attack()

    # -- ops --------------------------------------------------------------
    def poison_data(self, dataset: Any) -> Any:
        return self.attacker.poison_data(dataset)

    def attack_model(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        return self.attacker.attack_model(raw_client_grad_list, extra_auxiliary_info)

    def reconstruct_data(self, a_gradient, extra_auxiliary_info: Any = None):
        return self.attacker.reconstruct_data(a_gradient, extra_auxiliary_info)

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
