"""FedMLDefender — robust-aggregation singleton.

Parity: ``core/security/fedml_defender.py:40``. The defense registry lives in
``core/security/defense``; each defense implements one or more of
``defend_before_aggregation`` / ``defend_on_aggregation`` /
``defend_after_aggregation``.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

Pytree = Any


class FedMLDefender:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.defense_type: Optional[str] = None
        self.defender = None

    @classmethod
    def get_instance(cls) -> "FedMLDefender":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_defense", False))
        if not self.is_enabled:
            return
        self.defense_type = str(getattr(args, "defense_type", "")).strip().lower()
        from fedml_tpu.core.security.defense import create_defender

        self.defender = create_defender(self.defense_type, args)
        logging.info("defense enabled: %s", self.defense_type)

    def is_defense_enabled(self) -> bool:
        return self.is_enabled

    def is_norm_only_defense(self) -> bool:
        """True when the active defense needs only per-client update
        NORMS (norm-difference clipping). Norms are computable straight
        off compressed blocks × scales (``telemetry.health.update_norm``)
        and the clip factor folds into the aggregation weight, so these
        defenses ride the dequant-fused path — no f32 fallback."""
        return self.is_enabled and self.defense_type == "norm_diff_clipping"

    def norm_clip_bound(self) -> float:
        """The active norm bound (norm-only defenses)."""
        return float(getattr(self.defender, "norm_bound", 0.0))

    def is_fused_defense(self) -> bool:
        """True when the active defense is a coordinate-wise robust
        statistic the integrity layer computes in the compressed domain
        (``fedml_tpu.integrity.fused_robust_sum``): shift-equivariant,
        so running it on the stacked compressed DELTAS and adding the
        broadcast base equals running it on full client models — no
        decode fallback needed."""
        return self.is_enabled and self.defense_type in (
            "trimmed_mean", "coordinate_wise_median")

    def fused_agg_spec(self) -> Optional[str]:
        """The active fused defense as an ``agg_robust`` negotiation
        spec (``trimmed_mean@beta`` / ``median``), or None."""
        if not self.is_fused_defense():
            return None
        if self.defense_type == "coordinate_wise_median":
            return "median"
        return f"trimmed_mean@{float(getattr(self.defender, 'beta', 0.1)):g}"

    def fused_clip_factors(self, cts) -> Optional[List[float]]:
        """Per-client clip factors for the dequant-fused aggregation
        path: ``min(1, bound/‖d_i‖)`` with the delta norm read straight
        off the compressed blocks × scales (``health.update_norm`` — the
        PR 4 path, reused, not re-decoded). None when no norm-only
        defense is active. The SINGLE definition for every fused caller
        (cross-silo aggregator, sp simulation)."""
        if not self.is_norm_only_defense():
            return None
        from fedml_tpu.telemetry.health import update_norm
        from fedml_tpu.telemetry.registry import get_registry

        bound = self.norm_clip_bound()
        factors = []
        for ct in cts:
            norm = update_norm(ct)
            if norm is None:  # pragma: no cover - delta cts always norm
                logging.warning("norm-only defense could not norm a "
                                "compressed update; leaving it unclipped")
                factors.append(1.0)
            else:
                factors.append(min(1.0, bound / (norm + 1e-12)))
        get_registry().counter("health/norm_clips_fused").inc(
            sum(1 for f in factors if f < 1.0))
        return factors

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        return self.defender.defend_before_aggregation(
            raw_client_grad_list, extra_auxiliary_info
        )

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        return self.defender.defend_on_aggregation(
            raw_client_grad_list, base_aggregation_func, extra_auxiliary_info
        )

    def defend_after_aggregation(self, global_model: Pytree) -> Pytree:
        return self.defender.defend_after_aggregation(global_model)

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
