"""FedMLDefender — robust-aggregation singleton.

Parity: ``core/security/fedml_defender.py:40``. The defense registry lives in
``core/security/defense``; each defense implements one or more of
``defend_before_aggregation`` / ``defend_on_aggregation`` /
``defend_after_aggregation``.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

Pytree = Any


class FedMLDefender:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.defense_type: Optional[str] = None
        self.defender = None

    @classmethod
    def get_instance(cls) -> "FedMLDefender":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_defense", False))
        if not self.is_enabled:
            return
        self.defense_type = str(getattr(args, "defense_type", "")).strip().lower()
        from fedml_tpu.core.security.defense import create_defender

        self.defender = create_defender(self.defense_type, args)
        logging.info("defense enabled: %s", self.defense_type)

    def is_defense_enabled(self) -> bool:
        return self.is_enabled

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        return self.defender.defend_before_aggregation(
            raw_client_grad_list, extra_auxiliary_info
        )

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        return self.defender.defend_on_aggregation(
            raw_client_grad_list, base_aggregation_func, extra_auxiliary_info
        )

    def defend_after_aggregation(self, global_model: Pytree) -> Pytree:
        return self.defender.defend_after_aggregation(global_model)

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
