"""Bulyan (El Mhamdi et al., ICML'18): Multi-Krum selection + per-coordinate
trimmed aggregation around the median.

Parity: ``core/security/defense/bulyan_defense.py``.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import (
    BaseDefense,
    pairwise_sq_dists,
    stack_updates,
)
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@register("bulyan")
class BulyanDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.byzantine_client_num = int(getattr(args, "byzantine_client_num", 1))

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        n = len(raw_client_grad_list)
        f = min(self.byzantine_client_num, max(0, (n - 3) // 4))
        theta = max(1, n - 2 * f)  # selection set size
        beta = max(1, theta - 2 * f)  # per-coordinate kept count
        vecs, _, template = stack_updates(raw_client_grad_list)
        d = pairwise_sq_dists(vecs)
        d = d.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
        m = max(1, n - f - 2)
        scores = jnp.sum(jnp.sort(d, axis=1)[:, :m], axis=1)
        selected = vecs[jnp.argsort(scores)[:theta]]
        # per-coordinate: keep the beta values closest to the median, average
        med = jnp.median(selected, axis=0)
        dist = jnp.abs(selected - med[None, :])
        order = jnp.argsort(dist, axis=0)[:beta]
        kept = jnp.take_along_axis(selected, order, axis=0)
        return tree_unflatten_vector(jnp.mean(kept, axis=0), template)
