"""FoolsGold (Fung et al.): down-weight sybils by cosine-similarity history.

Parity: ``core/security/defense/foolsgold_defense.py``. History of aggregated
update directions per client; pairwise cosine similarity → adaptive learning
rates; all as batched matmuls.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@register("foolsgold")
class FoolsGoldDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.use_memory = bool(getattr(args, "foolsgold_use_memory", True))
        self._history: Dict[int, jnp.ndarray] = {}

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        vecs, _, template = stack_updates(raw_client_grad_list)
        n = vecs.shape[0]
        if self.use_memory:
            for i in range(n):
                self._history[i] = self._history.get(i, 0.0) + vecs[i]
            hist = jnp.stack([self._history[i] for i in range(n)])
        else:
            hist = vecs
        normed = hist / (jnp.linalg.norm(hist, axis=1, keepdims=True) + 1e-12)
        cs = normed @ normed.T
        cs = cs - jnp.eye(n)
        maxcs = jnp.max(cs, axis=1)
        # pardoning: rescale similarity by relative maximums
        ratio = maxcs[None, :] / (maxcs[:, None] + 1e-12)
        cs = jnp.where(maxcs[:, None] < maxcs[None, :], cs * ratio, cs)
        wv = 1.0 - jnp.max(cs, axis=1)
        wv = jnp.clip(wv, 0.0, 1.0)
        wv = wv / (jnp.max(wv) + 1e-12)
        # logit re-scaling as in the paper
        safe = jnp.clip(wv, 1e-6, 1.0 - 1e-6)
        wv = jnp.where(wv == 1.0, 1.0, jnp.clip(jnp.log(safe / (1.0 - safe)) / 4.0 + 0.5, 0.0, 1.0))
        agg = jnp.einsum("n,nd->d", wv / (jnp.sum(wv) + 1e-12), vecs)
        return tree_unflatten_vector(agg, template)
