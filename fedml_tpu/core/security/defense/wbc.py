"""WBC — weight-based clustering defense: cluster client updates (2-means on
distance to coordinate median) and keep the larger cluster.

Parity: ``core/security/defense/wbc_defense.py``.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates

Pytree = Any


@register("wbc")
class WbcDefense(BaseDefense):
    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, _, _ = stack_updates(raw_client_grad_list)
        med = jnp.median(vecs, axis=0)
        dists = jnp.linalg.norm(vecs - med[None, :], axis=1)
        # simple 1-D 2-means on distances: threshold at midpoint of extremes
        lo, hi = jnp.min(dists), jnp.max(dists)
        thresh = (lo + hi) / 2.0
        for _ in range(10):
            lo_mean = jnp.mean(jnp.where(dists <= thresh, dists, 0.0))
            lo_cnt = jnp.sum(dists <= thresh)
            hi_cnt = jnp.maximum(1, dists.shape[0] - lo_cnt)
            hi_mean = jnp.sum(jnp.where(dists > thresh, dists, 0.0)) / hi_cnt
            lo_mean = jnp.sum(jnp.where(dists <= thresh, dists, 0.0)) / jnp.maximum(1, lo_cnt)
            new_thresh = (lo_mean + hi_mean) / 2.0
            thresh = jnp.where(jnp.isfinite(new_thresh), new_thresh, thresh)
        keep = dists <= thresh
        kept = [raw_client_grad_list[i] for i in range(len(raw_client_grad_list)) if bool(keep[i])]
        return kept if kept else raw_client_grad_list
