"""Soteria (Sun et al., CVPR'21): defend gradient-leakage by perturbing the
representation layer of the update (largest fc layer), preserving utility.

Parity: ``core/security/defense/soteria_defense.py``. Applied client-side in
the reference; here exposed as a before-aggregation transform that prunes
the smallest-magnitude fraction of the chosen layer.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense

Pytree = Any


@register("soteria")
class SoteriaDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.percentile = float(getattr(args, "soteria_percentile", 10.0))

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        def _perturb_largest_leaf(tree: Pytree) -> Pytree:
            leaves, treedef = jax.tree.flatten(tree)
            sizes = [leaf.size for leaf in leaves]
            target = int(jnp.argmax(jnp.asarray(sizes)))
            out = []
            for i, leaf in enumerate(leaves):
                if i == target:
                    thresh = jnp.percentile(jnp.abs(leaf), self.percentile)
                    leaf = jnp.where(jnp.abs(leaf) < thresh, 0.0, leaf).astype(leaf.dtype)
                out.append(leaf)
            return jax.tree.unflatten(treedef, out)

        return [(n, _perturb_largest_leaf(p)) for n, p in raw_client_grad_list]
