"""Cross-round + cross-client outlier detection on update norms and cosine
similarity to the running aggregate.

Parity: ``core/security/defense/outlier_detection.py`` / ``crossround_defense``.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates

Pytree = Any


@register("outlier_detection")
@register("cross_round")
class OutlierDetectionDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.threshold = float(getattr(args, "outlier_cos_threshold", -0.5))
        self._prev_mean = None

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, _, _ = stack_updates(raw_client_grad_list)
        mean = jnp.mean(vecs, axis=0)
        has_prev = (self._prev_mean is not None
                    and self._prev_mean.shape == mean.shape)
        ref = self._prev_mean if has_prev else mean
        self._prev_mean = mean
        cos = (vecs @ ref) / (
            jnp.linalg.norm(vecs, axis=1) * (jnp.linalg.norm(ref) + 1e-12) + 1e-12
        )
        norms = jnp.linalg.norm(vecs, axis=1)
        med = jnp.median(norms)
        keep = (cos >= self.threshold) & (norms <= 5.0 * (med + 1e-12))
        kept = [raw_client_grad_list[i] for i in range(len(raw_client_grad_list)) if bool(keep[i])]
        return kept if kept else raw_client_grad_list
