"""Cross-round consistency defense.

Parity: ``core/security/defense/cross_round_defense.py``: clients whose
update *direction* is wildly inconsistent with their own previous rounds
(cosine similarity below a threshold) are down-weighted — a client that
suddenly flips its gradient direction is either compromised or unstable.
State (per-client history) lives across rounds in the defense instance.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import (
    BaseDefense,
    stack_updates,
)

Pytree = Any


@register("cross_round")
class CrossRoundDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.sim_threshold = float(getattr(args, "cross_round_sim_threshold", -0.2))
        self._history: Dict[int, np.ndarray] = {}

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, counts, template = stack_updates(raw_client_grad_list)
        vecs_np = np.asarray(vecs)
        keep = []
        for i in range(vecs_np.shape[0]):
            prev = self._history.get(i)
            ok = True
            if prev is not None:
                denom = (np.linalg.norm(prev) * np.linalg.norm(vecs_np[i]) + 1e-12)
                cos = float(prev @ vecs_np[i]) / denom
                ok = cos >= self.sim_threshold
            self._history[i] = vecs_np[i]
            if ok:
                keep.append(i)
        if not keep:  # never reject the whole round
            keep = list(range(vecs_np.shape[0]))
        return [raw_client_grad_list[i] for i in keep]
