"""Norm-difference clipping (Sun et al., "Can you really backdoor FL?").

Parity: ``core/security/defense/norm_diff_clipping_defense.py``: clip each
client update's *difference from the global model* to a norm bound.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates, unstack_to_list
from fedml_tpu.utils.tree import tree_flatten_vector

Pytree = Any


@jax.jit
def _clip_rows_to(vecs: jnp.ndarray, center: jnp.ndarray, bound: jnp.ndarray) -> jnp.ndarray:
    diffs = vecs - center[None, :]
    norms = jnp.linalg.norm(diffs, axis=1, keepdims=True)
    factor = jnp.minimum(1.0, bound / (norms + 1e-12))
    return center[None, :] + diffs * factor


@register("norm_diff_clipping")
class NormDiffClippingDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.norm_bound = float(getattr(args, "norm_bound", 5.0))

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, counts, template = stack_updates(raw_client_grad_list)
        if extra_auxiliary_info is not None and not isinstance(extra_auxiliary_info, dict):
            center = tree_flatten_vector(extra_auxiliary_info)
        else:
            center = jnp.zeros((vecs.shape[1],), dtype=vecs.dtype)
        clipped = _clip_rows_to(vecs, center, jnp.float32(self.norm_bound))
        return unstack_to_list(clipped, counts, template)

    def defend_stacked(self, vecs, counts, valid, global_vec):
        """Traced clip + count-weighted FedAvg for the in-mesh round.

        Center matches the host path's default (zeros — the aux passed by
        the hook chain is a metrics dict, not a model).
        """
        center = jnp.zeros((vecs.shape[1],), dtype=vecs.dtype)
        clipped = _clip_rows_to(vecs, center, jnp.float32(self.norm_bound))
        w = counts / jnp.sum(counts)
        return jnp.einsum("n,nd->d", w, clipped)
