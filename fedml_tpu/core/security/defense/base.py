"""Defense base class + shared tensorization helpers."""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.utils.tree import (
    tree_flatten_vector,
    tree_stack,
    tree_unflatten_vector,
    weighted_tree_sum,
)

Pytree = Any


class BaseDefense:
    """A defense may hook any of the three aggregation phases.

    Mirrors ``core/security/defense/defense_base.py`` in the reference.
    """

    def __init__(self, args: Any):
        self.args = args

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        return raw_client_grad_list

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        return base_aggregation_func(self.args, raw_client_grad_list)

    def defend_after_aggregation(self, global_model: Pytree) -> Pytree:
        return global_model


def stack_updates(
    raw_client_grad_list: List[Tuple[int, Pytree]],
) -> Tuple[jnp.ndarray, jnp.ndarray, Pytree]:
    """[(n_k, tree)] → (N×D update matrix fp32, (N,) sample counts, template)."""
    counts = jnp.asarray([float(n) for n, _ in raw_client_grad_list])
    vecs = jnp.stack([tree_flatten_vector(p) for _, p in raw_client_grad_list])
    template = raw_client_grad_list[0][1]
    return vecs, counts, template


def unstack_to_list(
    vecs: jnp.ndarray, counts: jnp.ndarray, template: Pytree
) -> List[Tuple[int, Pytree]]:
    return [
        (float(counts[i]), tree_unflatten_vector(vecs[i], template))
        for i in range(vecs.shape[0])
    ]


@jax.jit
def pairwise_sq_dists(vecs: jnp.ndarray) -> jnp.ndarray:
    """N×N squared L2 distances via one gram matmul (MXU-friendly)."""
    sq = jnp.sum(vecs * vecs, axis=1)
    gram = vecs @ vecs.T
    d = sq[:, None] + sq[None, :] - 2.0 * gram
    return jnp.maximum(d, 0.0)


def aggregate_trees(
    trees: List[Pytree], weights: jnp.ndarray
) -> Pytree:
    w = weights / jnp.sum(weights)
    return weighted_tree_sum(tree_stack(trees), w)
