"""Three-sigma outlier rejection on update-norm scores.

Parity: ``core/security/defense/three_sigma_defense.py`` (+ geomedian/krum
scored variants): compute a per-client score, drop clients whose score is
more than 3 sigma from the mean.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.core.security.defense.geometric_median import geometric_median

Pytree = Any


@register("3sigma")
@register("three_sigma")
class ThreeSigmaDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.score = str(getattr(args, "three_sigma_score", "geomedian")).lower()
        self.k_sigma = float(getattr(args, "k_sigma", 3.0))

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, counts, _ = stack_updates(raw_client_grad_list)
        if self.score == "geomedian":
            center = geometric_median(vecs, counts)
        else:
            center = jnp.mean(vecs, axis=0)
        scores = jnp.linalg.norm(vecs - center[None, :], axis=1)
        mu, sigma = jnp.mean(scores), jnp.std(scores) + 1e-12
        keep = scores <= mu + self.k_sigma * sigma
        kept = [raw_client_grad_list[i] for i in range(len(raw_client_grad_list)) if bool(keep[i])]
        return kept if kept else raw_client_grad_list
