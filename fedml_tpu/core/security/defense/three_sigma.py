"""Three-sigma outlier rejection over pluggable client scores.

Parity: ``core/security/defense/three_sigma_defense.py`` +
``three_sigma_geomedian_defense.py`` + ``three_sigma_foolsgold_defense.py``:
compute a per-client score, drop clients whose score is more than k·sigma
beyond the mean. Scores:

  geomedian — distance to the geometric median (magnitude outliers)
  mean      — distance to the coordinate mean
  foolsgold — max pairwise cosine similarity (sybil colluders, who are
              suspiciously ALIGNED rather than far away)
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.core.security.defense.geometric_median import geometric_median

Pytree = Any


@register("3sigma")
@register("three_sigma")
class ThreeSigmaDefense(BaseDefense):
    score_override = None

    def __init__(self, args: Any):
        super().__init__(args)
        self.score = (self.score_override or
                      str(getattr(args, "three_sigma_score", "geomedian"))).lower()
        self.k_sigma = float(getattr(args, "k_sigma", 3.0))

    def _scores(self, vecs: jnp.ndarray, counts) -> jnp.ndarray:
        if self.score == "foolsgold":
            # sybil indicator: near-duplicate update directions ⇒ max
            # cosine similarity to any other client spikes toward 1
            normed = vecs / (jnp.linalg.norm(vecs, axis=1, keepdims=True) + 1e-12)
            cs = normed @ normed.T - jnp.eye(vecs.shape[0])
            return jnp.max(cs, axis=1)
        if self.score == "geomedian":
            center = geometric_median(vecs, counts)
        else:
            center = jnp.mean(vecs, axis=0)
        return jnp.linalg.norm(vecs - center[None, :], axis=1)

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        vecs, counts, _ = stack_updates(raw_client_grad_list)
        scores = self._scores(vecs, counts)
        mu, sigma = jnp.mean(scores), jnp.std(scores) + 1e-12
        keep = scores <= mu + self.k_sigma * sigma
        kept = [raw_client_grad_list[i] for i in range(len(raw_client_grad_list)) if bool(keep[i])]
        return kept if kept else raw_client_grad_list


@register("three_sigma_geomedian")
class ThreeSigmaGeoMedianDefense(ThreeSigmaDefense):
    """Parity: ``three_sigma_geomedian_defense.py``."""

    score_override = "geomedian"


@register("three_sigma_foolsgold")
class ThreeSigmaFoolsGoldDefense(ThreeSigmaDefense):
    """Parity: ``three_sigma_foolsgold_defense.py``."""

    score_override = "foolsgold"
