"""Coordinate-wise median aggregation (Yin et al., ICML'18).

Parity: ``core/security/defense/coordinate_wise_median_defense.py``.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense
from fedml_tpu.utils.tree import tree_stack

Pytree = Any


@jax.jit
def _median_tree(stacked: Pytree) -> Pytree:
    return jax.tree.map(lambda x: jnp.median(x, axis=0).astype(x.dtype), stacked)


@register("coordinate_wise_median")
class CoordinateWiseMedianDefense(BaseDefense):
    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        from fedml_tpu.core.security.defense.blockwise import (
            coordinate_median_blockwise,
            should_go_blockwise,
        )

        trees = [p for _, p in raw_client_grad_list]
        if should_go_blockwise(raw_client_grad_list, self.args):
            return coordinate_median_blockwise(trees)
        return _median_tree(tree_stack(trees))

    def defend_stacked(self, vecs, counts, valid, global_vec):
        """Traced masked median for the in-mesh compiled round.

        Matches ``jnp.median`` semantics (mean of the two middles for even
        counts) over the *valid* rows only.
        """
        import jax.numpy as jnp

        big = jnp.float32(1e30)
        col = jnp.where(valid[:, None], vecs, big)  # pads sort to the end
        s = jnp.sort(col, axis=0)
        nv = jnp.sum(valid.astype(jnp.int32))
        lo = (nv - 1) // 2
        hi = nv // 2
        return 0.5 * (s[lo] + s[hi])
