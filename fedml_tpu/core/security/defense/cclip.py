"""Centered clipping (Karimireddy et al., "Learning from history").

Parity: ``core/security/defense/cclip_defense.py``: clip updates around a
momentum center maintained across rounds, then average.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.core.security.defense.norm_diff_clipping import _clip_rows_to
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@register("cclip")
class CClipDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.tau = float(getattr(args, "cclip_tau", 10.0))
        self.iters = int(getattr(args, "cclip_iters", 1))
        self._center = None

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        vecs, counts, template = stack_updates(raw_client_grad_list)
        center = (
            self._center
            if self._center is not None and self._center.shape == (vecs.shape[1],)
            else jnp.zeros((vecs.shape[1],), dtype=vecs.dtype)
        )
        w = counts / jnp.sum(counts)
        for _ in range(self.iters):
            clipped = _clip_rows_to(vecs, center, jnp.float32(self.tau))
            center = jnp.einsum("n,nd->d", w, clipped)
        self._center = center
        return tree_unflatten_vector(center, template)
