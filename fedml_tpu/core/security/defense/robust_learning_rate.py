"""Robust learning rate (Ozdayi et al., AAAI'21): flip the server learning
rate on coordinates where update signs disagree below a threshold.

Parity: ``core/security/defense/RobustLearningRate``-style defense.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@register("robust_learning_rate")
class RobustLearningRateDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.robust_threshold = float(getattr(args, "robust_threshold", 4.0))

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        vecs, counts, template = stack_updates(raw_client_grad_list)
        w = counts / jnp.sum(counts)
        agg = jnp.einsum("n,nd->d", w, vecs)
        sign_agreement = jnp.abs(jnp.sum(jnp.sign(vecs), axis=0))
        lr_sign = jnp.where(sign_agreement >= self.robust_threshold, 1.0, -1.0)
        return tree_unflatten_vector(lr_sign * agg, template)
