"""Krum / Multi-Krum (Blanchard et al., NeurIPS'17).

Parity: ``core/security/defense/krum_defense.py``. The reference computes
pairwise distances with nested numpy loops; here it is a gram matmul on
the MXU — one N×D program when the stacked updates fit the device budget,
and the blockwise-streamed accumulation from ``blockwise.py`` when they
don't (full-parameter LLM payloads: N×D fp32 at 7B is >200 GB, far over
HBM — see SURVEY hard part (e)).
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import (
    BaseDefense,
    pairwise_sq_dists,
    stack_updates,
)

Pytree = Any


def select_krum(d: jnp.ndarray, f: int, k: int) -> List[int]:
    """Krum selection from an N×N squared-distance matrix: keep the ``k``
    clients whose summed n-f-2 nearest distances are smallest. Shared by
    the dense path, the blockwise >HBM path, and the benches."""
    n = d.shape[0]
    m = max(1, n - f - 2)
    d = jnp.asarray(d).at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    sorted_d = jnp.sort(d, axis=1)
    scores = jnp.sum(sorted_d[:, :m], axis=1)
    keep = jnp.argsort(scores)[:k]
    return sorted(int(i) for i in keep)


@register("krum")
class KrumDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.byzantine_client_num = int(getattr(args, "byzantine_client_num", 1))
        # multi-krum keeps k survivors; plain krum keeps 1
        self.krum_param_k = int(getattr(args, "krum_param_k", 1))
        if bool(getattr(args, "multi", False)):
            self.krum_param_k = max(self.krum_param_k, 2)

    def defend_before_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        extra_auxiliary_info: Any = None,
    ) -> List[Tuple[int, Pytree]]:
        n = len(raw_client_grad_list)
        f = min(self.byzantine_client_num, max(0, (n - 3) // 2))
        from fedml_tpu.core.security.defense.blockwise import (
            flatten_clients,
            iter_blocks,
            pairwise_sq_dists_blockwise,
            should_go_blockwise,
        )

        if should_go_blockwise(raw_client_grad_list, self.args):
            d = jnp.asarray(pairwise_sq_dists_blockwise(
                iter_blocks(flatten_clients(
                    [p for _, p in raw_client_grad_list])), n))
        else:
            vecs, _, _ = stack_updates(raw_client_grad_list)
            d = pairwise_sq_dists(vecs)
        keep_idx = select_krum(d, f, self.krum_param_k)
        return [raw_client_grad_list[i] for i in keep_idx]

    def defend_stacked(self, vecs, counts, valid, global_vec):
        """Traced krum for the in-mesh compiled round.

        Same math as ``defend_before_aggregation`` + count-weighted FedAvg
        over the survivors, but fully traceable (no data-dependent Python),
        so it runs *inside* the one-XLA-program mesh round. ``valid`` masks
        padded scheduler slots (their rows never enter distances/selection).
        """
        n = vecs.shape[0]
        big = jnp.float32(1e30)
        inv = ~valid
        d = pairwise_sq_dists(vecs)
        d = d + big * (inv[:, None] | inv[None, :]).astype(jnp.float32)
        d = d.at[jnp.arange(n), jnp.arange(n)].set(big)
        n_valid = jnp.sum(valid.astype(jnp.int32))
        f = jnp.minimum(self.byzantine_client_num,
                        jnp.maximum(0, (n_valid - 3) // 2))
        m = jnp.maximum(1, n_valid - f - 2)
        sorted_d = jnp.sort(d, axis=1)
        take = jnp.arange(n)[None, :] < m
        scores = jnp.sum(jnp.where(take, sorted_d, 0.0), axis=1)
        scores = scores + big * inv.astype(jnp.float32)
        keep = jnp.argsort(scores)[: self.krum_param_k]
        w = jnp.zeros((n,), jnp.float32).at[keep].set(counts[keep])
        return jnp.einsum("n,nd->d", w / jnp.sum(w), vecs)
