"""Coordinate-wise trimmed mean (Yin et al., ICML'18).

Parity: ``core/security/defense/coordinate_wise_trimmed_mean_defense.py``.
Trims the beta largest and smallest values per coordinate, then averages.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense
from fedml_tpu.utils.tree import tree_stack

Pytree = Any


@functools.partial(jax.jit, static_argnames=("k",))
def _trimmed_mean_tree(stacked: Pytree, k: int) -> Pytree:
    def _tm(x):
        xs = jnp.sort(x, axis=0)
        n = x.shape[0]
        kept = jax.lax.slice_in_dim(xs, k, n - k, axis=0)
        return jnp.mean(kept, axis=0).astype(x.dtype)

    return jax.tree.map(_tm, stacked)


@register("trimmed_mean")
class TrimmedMeanDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.beta = float(getattr(args, "beta", 0.1))  # trim fraction per side

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        n = len(raw_client_grad_list)
        k = min(int(self.beta * n), (n - 1) // 2)
        stacked = tree_stack([p for _, p in raw_client_grad_list])
        return _trimmed_mean_tree(stacked, k)
