"""Coordinate-wise trimmed mean (Yin et al., ICML'18).

Parity: ``core/security/defense/coordinate_wise_trimmed_mean_defense.py``.
Trims the beta largest and smallest values per coordinate, then averages.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense
from fedml_tpu.utils.tree import tree_stack

Pytree = Any


@functools.partial(jax.jit, static_argnames=("k",))
def _trimmed_mean_tree(stacked: Pytree, k: int) -> Pytree:
    def _tm(x):
        xs = jnp.sort(x, axis=0)
        n = x.shape[0]
        kept = jax.lax.slice_in_dim(xs, k, n - k, axis=0)
        return jnp.mean(kept, axis=0).astype(x.dtype)

    return jax.tree.map(_tm, stacked)


@register("trimmed_mean")
class TrimmedMeanDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.beta = float(getattr(args, "beta", 0.1))  # trim fraction per side

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        n = len(raw_client_grad_list)
        k = min(int(self.beta * n), (n - 1) // 2)
        from fedml_tpu.core.security.defense.blockwise import (
            should_go_blockwise,
            trimmed_mean_blockwise,
        )

        trees = [p for _, p in raw_client_grad_list]
        if should_go_blockwise(raw_client_grad_list, self.args):
            return trimmed_mean_blockwise(trees, k)
        return _trimmed_mean_tree(tree_stack(trees), k)

    def defend_stacked(self, vecs, counts, valid, global_vec):
        """Traced masked trimmed mean for the in-mesh compiled round."""
        import jax.numpy as jnp

        n = vecs.shape[0]
        big = jnp.float32(1e30)
        nv = jnp.sum(valid.astype(jnp.int32))
        # +1e-4 before truncation: float32 beta*nv can land just below an
        # exact integer (0.35*20 → 6.99999988) where the host path's float64
        # int(beta*n) truncates to the integer — keep the two paths agreeing
        k = jnp.minimum(
            (self.beta * nv + 1e-4).astype(jnp.int32), (nv - 1) // 2
        )
        col = jnp.where(valid[:, None], vecs, big)  # pads sort to the end
        s = jnp.sort(col, axis=0)
        rank = jnp.arange(n)[:, None]
        keep = (rank >= k) & (rank < nv - k)
        denom = jnp.maximum(nv - 2 * k, 1).astype(jnp.float32)
        return jnp.sum(jnp.where(keep, s, 0.0), axis=0) / denom
