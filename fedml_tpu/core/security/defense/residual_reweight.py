"""Residual-based reweighting (Fu et al.): IRLS-style per-client weights from
repeated-median-regression residuals, approximated per coordinate.

Parity: ``core/security/defense/residual_based_reweighting_defense.py``.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@register("residual_based_reweighting")
@register("residual_reweight")
class ResidualReweightDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.lmbda = float(getattr(args, "residual_lambda", 2.0))

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        vecs, _, template = stack_updates(raw_client_grad_list)
        med = jnp.median(vecs, axis=0)
        mad = jnp.median(jnp.abs(vecs - med[None, :]), axis=0) * 1.4826 + 1e-12
        std_res = jnp.abs(vecs - med[None, :]) / mad[None, :]
        # per-coordinate confidence, averaged per client → IRLS weight
        conf = jnp.clip(1.0 - std_res / self.lmbda, 0.0, 1.0)
        wv = jnp.mean(conf, axis=1)
        wv = wv / (jnp.sum(wv) + 1e-12)
        return tree_unflatten_vector(jnp.einsum("n,nd->d", wv, vecs), template)
