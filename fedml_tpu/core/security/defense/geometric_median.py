"""RFA / geometric median via smoothed Weiszfeld (Pillutla et al., 2022).

Parity: ``core/security/defense/RFA_defense.py`` / ``geometric_median_defense``.
Fixed-iteration Weiszfeld runs under ``lax.fori_loop`` so the whole defense
is one compiled program.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense, stack_updates
from fedml_tpu.utils.tree import tree_unflatten_vector

Pytree = Any


@functools.partial(jax.jit, static_argnames=("iters",))
def geometric_median(vecs: jnp.ndarray, weights: jnp.ndarray, iters: int = 10,
                     eps: float = 1e-8) -> jnp.ndarray:
    w = weights / jnp.sum(weights)
    z0 = jnp.einsum("n,nd->d", w, vecs)

    def body(_, z):
        dists = jnp.sqrt(jnp.sum((vecs - z[None, :]) ** 2, axis=1) + eps)
        alpha = w / dists
        alpha = alpha / jnp.sum(alpha)
        return jnp.einsum("n,nd->d", alpha, vecs)

    return jax.lax.fori_loop(0, iters, body, z0)


@register("rfa")
@register("geometric_median")
class GeometricMedianDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.iters = int(getattr(args, "geo_median_iters", 10))

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        from fedml_tpu.core.security.defense.blockwise import (
            geometric_median_blockwise,
            should_go_blockwise,
        )

        if should_go_blockwise(raw_client_grad_list, self.args):
            return geometric_median_blockwise(
                [p for _, p in raw_client_grad_list],
                [n for n, _ in raw_client_grad_list],
                iters=self.iters,
            )
        vecs, counts, template = stack_updates(raw_client_grad_list)
        gm = geometric_median(vecs, counts, self.iters)
        return tree_unflatten_vector(gm, template)
