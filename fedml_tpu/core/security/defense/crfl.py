"""CRFL (Xie et al., ICML'21): certifiably robust FL — clip the aggregated
model then add smoothing noise each round.

Parity: ``core/security/defense/crfl_defense.py``.
"""
from __future__ import annotations

from typing import Any

import jax

from fedml_tpu.core.dp.frames.dp_clip import clip_update
from fedml_tpu.core.dp.mechanisms import add_gaussian_noise
from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense

Pytree = Any


@register("crfl")
class CRFLDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.clip_threshold = float(getattr(args, "crfl_clip_threshold", 15.0))
        self.sigma = float(getattr(args, "crfl_sigma", 0.01))
        self._counter = 0
        self._seed = int(getattr(args, "random_seed", 0)) + 15485863

    def defend_after_aggregation(self, global_model: Pytree) -> Pytree:
        self._counter += 1
        clipped = clip_update(global_model, self.clip_threshold)
        key = jax.random.fold_in(jax.random.key(self._seed), self._counter)
        return add_gaussian_noise(clipped, key, self.sigma)
