"""Blockwise robust-aggregation math for payloads larger than HBM.

SURVEY §7 hard part (e): one fp32 vector of a 6.76B-param model is
27 GB, so the N×D stacked update matrix the plain defenses build
(``stack_updates``) can never be device-resident on a 16 GB chip for
full-parameter LLM federation. Reference counterparts
(``core/security/defense/krum_defense.py``,
``coordinate_wise_median_defense.py``, ``RFA_defense.py``) sidestep the
question by running per-pair numpy loops on the host — correct but
orders of magnitude slower and still RAM-bound.

Here every robust aggregator decomposes into per-block device programs
over ``[N, C]`` slices of the virtual N×D matrix, streamed in flattened
leaf order with a fixed block width (one compiled program per op):

- krum / pairwise distances — gram accumulation ``G += X_b @ X_bᵀ``;
  distances follow from ``G`` alone, so device memory is N×C + N×N;
- coordinate-wise median / trimmed mean — per-coordinate, embarrassingly
  blockwise;
- geometric median — smoothed Weiszfeld; each iteration is one
  distance-accumulation pass plus one weighted-reduction pass.

Client payloads stay in host RAM (they arrive from the federation
transport as host arrays anyway); the device holds at most one block.
Blocks enter via an iterator so benchmarks can synthesize them on-device
(GB-scale host→device pushes through the axon tunnel are minutes-slow
and would measure the tunnel, not the defense).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

# 1<<25 elems × 8 clients × 4 B = 1 GB device-resident per block at N=8
DEFAULT_BLOCK_ELEMS = 1 << 25


def flatten_clients(trees: Sequence[Pytree]) -> List[List[np.ndarray]]:
    """Per-client flattened leaf lists (host views where possible)."""
    return [
        [np.asarray(leaf).reshape(-1) for leaf in jax.tree.leaves(t)]
        for t in trees
    ]


def iter_blocks(
    flat_clients: List[List[np.ndarray]],
    block_elems: int = DEFAULT_BLOCK_ELEMS,
) -> Iterable[Tuple[np.ndarray, int]]:
    """Yield ``(block [N, block_elems] fp32, valid_width)`` slices of the
    virtual concatenated N×D matrix. The final block is zero-padded to the
    fixed width so every block hits the same compiled program."""
    n = len(flat_clients)
    n_leaves = len(flat_clients[0])
    block = np.zeros((n, block_elems), np.float32)
    fill = 0
    for li in range(n_leaves):
        size = flat_clients[0][li].size
        off = 0
        while off < size:
            take = min(block_elems - fill, size - off)
            for ci in range(n):
                block[ci, fill : fill + take] = flat_clients[ci][li][
                    off : off + take
                ]
            fill += take
            off += take
            if fill == block_elems:
                yield block, fill
                block = np.zeros((n, block_elems), np.float32)
                fill = 0
    if fill:
        block[:, fill:] = 0.0
        yield block, fill


@jax.jit
def _gram_update(g: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    return g + x @ x.T


def pairwise_sq_dists_blockwise(
    blocks: Iterable[Tuple[Any, int]], n: int
) -> np.ndarray:
    """N×N squared L2 distances without ever materializing N×D.

    Zero padding contributes nothing to the gram, so padded tails are
    harmless. d_ij = g_ii + g_jj - 2 g_ij, clamped at 0.
    """
    g = jnp.zeros((n, n), jnp.float32)
    for x, _ in blocks:
        g = _gram_update(g, jnp.asarray(x, jnp.float32))
    g = np.asarray(g)
    sq = np.diag(g)
    d = sq[:, None] + sq[None, :] - 2.0 * g
    return np.maximum(d, 0.0)


@functools.partial(jax.jit, static_argnames=("k",))
def _trimmed_mean_block(x: jnp.ndarray, k: int) -> jnp.ndarray:
    xs = jnp.sort(x, axis=0)
    kept = jax.lax.slice_in_dim(xs, k, x.shape[0] - k, axis=0)
    return jnp.mean(kept, axis=0)


@jax.jit
def _median_block(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.median(x, axis=0)


@jax.jit
def _weighted_sum_block(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    return jnp.einsum("n,nc->c", w, x)


@jax.jit
def _sqdist_to_z_block(x: jnp.ndarray, z: jnp.ndarray) -> jnp.ndarray:
    d = x - z[None, :]
    return jnp.sum(d * d, axis=1)


def coordinate_reduce_blockwise(
    trees: Sequence[Pytree],
    reduce_block: Callable[[jnp.ndarray], jnp.ndarray],
    block_elems: int = DEFAULT_BLOCK_ELEMS,
) -> Pytree:
    """Apply a per-coordinate reduction (median, trimmed mean, …) over the
    client axis, block by block; returns a tree like one client's."""
    flat = flatten_clients(trees)
    total = sum(a.size for a in flat[0])
    out = np.empty((total,), np.float32)
    pos = 0
    for x, width in iter_blocks(flat, block_elems):
        r = np.asarray(reduce_block(jnp.asarray(x)))
        out[pos : pos + width] = r[:width]
        pos += width
    return _unflatten_like(out, trees[0])


def trimmed_mean_blockwise(trees, k: int,
                           block_elems: int = DEFAULT_BLOCK_ELEMS) -> Pytree:
    return coordinate_reduce_blockwise(
        trees, lambda x: _trimmed_mean_block(x, k), block_elems)


def coordinate_median_blockwise(
        trees, block_elems: int = DEFAULT_BLOCK_ELEMS) -> Pytree:
    return coordinate_reduce_blockwise(trees, _median_block, block_elems)


def geometric_median_blockwise(
    trees: Sequence[Pytree],
    weights: Sequence[float],
    iters: int = 10,
    eps: float = 1e-8,
    block_elems: int = DEFAULT_BLOCK_ELEMS,
) -> Pytree:
    """Smoothed Weiszfeld over blocks: per iteration, one full pass
    accumulates every client's squared distance to the current estimate,
    then one pass rebuilds the estimate from the reweighted average."""
    flat = flatten_clients(trees)
    n = len(flat)
    total = sum(a.size for a in flat[0])
    w = np.asarray(weights, np.float64)
    w = w / w.sum()

    # z₀ = weighted mean, built blockwise
    z = np.empty((total,), np.float32)
    wj = jnp.asarray(w, jnp.float32)
    pos = 0
    for x, width in iter_blocks(flat, block_elems):
        z[pos : pos + width] = np.asarray(
            _weighted_sum_block(jnp.asarray(x), wj))[:width]
        pos += width

    for _ in range(iters):
        sqd = np.zeros((n,), np.float64)
        pos = 0
        for x, width in iter_blocks(flat, block_elems):
            zb = jnp.asarray(z[pos : pos + block_elems]
                             if width == block_elems
                             else np.concatenate([
                                 z[pos : pos + width],
                                 np.zeros(block_elems - width, np.float32)]))
            sqd += np.asarray(_sqdist_to_z_block(jnp.asarray(x), zb),
                              np.float64)
            pos += width
        alpha = w / np.sqrt(sqd + eps)
        alpha = alpha / alpha.sum()
        aj = jnp.asarray(alpha, jnp.float32)
        pos = 0
        for x, width in iter_blocks(flat, block_elems):
            z[pos : pos + width] = np.asarray(
                _weighted_sum_block(jnp.asarray(x), aj))[:width]
            pos += width
    return _unflatten_like(z, trees[0])


def _unflatten_like(vec: np.ndarray, template: Pytree) -> Pytree:
    leaves, treedef = jax.tree.flatten(template)
    out, pos = [], 0
    for leaf in leaves:
        size = int(np.prod(np.shape(leaf)) or 1)
        out.append(
            np.asarray(vec[pos : pos + size], np.float32)
            .reshape(np.shape(leaf))
            .astype(np.asarray(leaf).dtype)
        )
        pos += size
    return jax.tree.unflatten(treedef, out)


def stacked_bytes(raw_client_grad_list: List[Tuple[int, Pytree]]) -> int:
    """fp32 bytes the dense N×D stack would occupy."""
    n = len(raw_client_grad_list)
    d = sum(int(np.prod(np.shape(x)) or 1)
            for x in jax.tree.leaves(raw_client_grad_list[0][1]))
    return 4 * n * d


def should_go_blockwise(raw_client_grad_list, args: Any,
                        default_budget: int = 4 << 30) -> bool:
    """True when the dense stack would exceed the device budget
    (``defense_stack_budget_bytes``, default 4 GB — the stack shares HBM
    with the model, gram workspace, and XLA scratch)."""
    budget = int(getattr(args, "defense_stack_budget_bytes", 0)
                 or default_budget)
    return stacked_bytes(raw_client_grad_list) > budget
