"""SLSGD (Xie et al.): trimmed-mean aggregation + server-side moving average.

Parity: ``core/security/defense/slsgd_defense.py``.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense
from fedml_tpu.core.security.defense.trimmed_mean import _trimmed_mean_tree
from fedml_tpu.utils.tree import tree_axpy, tree_scale, tree_stack

Pytree = Any


@register("slsgd")
class SLSGDDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.b = int(getattr(args, "trim_param_b", 1))
        self.alpha = float(getattr(args, "alpha", 0.6))
        self._last_global = None

    def defend_on_aggregation(
        self,
        raw_client_grad_list: List[Tuple[int, Pytree]],
        base_aggregation_func: Callable = None,
        extra_auxiliary_info: Any = None,
    ) -> Pytree:
        n = len(raw_client_grad_list)
        k = min(self.b, (n - 1) // 2)
        stacked = tree_stack([p for _, p in raw_client_grad_list])
        agg = _trimmed_mean_tree(stacked, k)
        if extra_auxiliary_info is not None:
            # (1 - alpha) * old_global + alpha * aggregated
            agg = tree_axpy(1.0 - self.alpha, extra_auxiliary_info, tree_scale(agg, self.alpha))
        return agg
