"""Defense registry.

Parity target: the 22-defense registry in ``core/security/fedml_defender.py``
/ ``core/security/defense/*.py``. All numeric work runs on stacked client
update matrices (N × D) as jitted XLA programs — the reference's per-pair
numpy loops (e.g. krum pairwise distances) become one batched matmul, which
is what makes these usable at 7B scale on TPU (SURVEY §7 hard part (e)).
"""
from __future__ import annotations

from typing import Any

from fedml_tpu.core.security.defense.base import BaseDefense

_REGISTRY = {}


def register(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def create_defender(name: str, args: Any) -> BaseDefense:
    # populate registry
    from fedml_tpu.core.security.defense import (  # noqa: F401
        bulyan,
        cclip,
        cross_round,
        coord_median,
        crfl,
        foolsgold,
        geometric_median,
        krum,
        norm_diff_clipping,
        outlier_detection,
        residual_reweight,
        robust_learning_rate,
        slsgd,
        soteria,
        three_sigma,
        trimmed_mean,
        weak_dp,
        wbc,
    )

    key = name.strip().lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown defense {name!r}; available: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key](args)


def available_defenses() -> list[str]:
    return sorted(_REGISTRY)
