"""Weak DP defense: add small gaussian noise to the aggregate.

Parity: ``core/security/defense/weak_dp_defense.py``.
"""
from __future__ import annotations

from typing import Any

import jax

from fedml_tpu.core.dp.mechanisms import add_gaussian_noise
from fedml_tpu.core.security.defense import register
from fedml_tpu.core.security.defense.base import BaseDefense

Pytree = Any


@register("weak_dp")
class WeakDPDefense(BaseDefense):
    def __init__(self, args: Any):
        super().__init__(args)
        self.stddev = float(getattr(args, "stddev", 0.002))
        self._counter = 0
        self._seed = int(getattr(args, "random_seed", 0)) + 104729

    def defend_after_aggregation(self, global_model: Pytree) -> Pytree:
        self._counter += 1
        key = jax.random.fold_in(jax.random.key(self._seed), self._counter)
        return add_gaussian_noise(global_model, key, self.stddev)
