"""Metrics/logging with a local JSONL sink.

Parity: ``core/mlops/mlops_metrics.py`` + the public ``fedml.mlops.log*``
API (``mlops/__init__.py:23-182``). The hosted MQTT/REST control plane is
absent by design; the sink writes JSONL under ``.fedml_logs/run_<id>/`` and
mirrors to wandb when enabled and available.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("fedml_tpu.mlops")

_GLOBAL: "MLOpsMetrics | None" = None


class MLOpsMetrics:
    def __init__(self, args: Any = None, sink_dir: Optional[str] = None):
        run_id = str(getattr(args, "run_id", "0")) if args else "0"
        self.run_id = run_id
        self._dir = sink_dir or os.path.join(
            str(getattr(args, "log_file_dir", "") or ".fedml_logs"), f"run_{run_id}"
        )
        self._lock = threading.Lock()
        self._fh = None
        self._fh_path: "str | None" = None
        self._wandb = None
        if args is not None and bool(getattr(args, "enable_wandb", False)):
            try:
                import wandb

                self._wandb = wandb
            except ImportError:
                logger.warning("wandb requested but not installed; using local sink")

    def _handle(self):
        """Cached append handle (caller holds the lock). Reopens when the
        sink dir changed or the file was rotated/deleted underneath us —
        one stat per write instead of makedirs+open+close per write."""
        path = os.path.join(self._dir, "metrics.jsonl")
        if (self._fh is None or self._fh_path != path
                or not os.path.exists(path)):
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            os.makedirs(self._dir, exist_ok=True)
            self._fh = open(path, "a")
            self._fh_path = path
        return self._fh

    def _write(self, kind: str, payload: Dict) -> None:
        rec = {"ts": time.time(), "kind": kind, **payload}
        with self._lock:
            f = self._handle()
            f.write(json.dumps(rec, default=str) + "\n")
            f.flush()
        if self._wandb is not None and kind == "metric":
            self._wandb.log(payload)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
                self._fh_path = None

    def report_server_training_metric(self, metric: Dict) -> None:
        self._write("server_metric", metric)

    def report_client_training_metric(self, metric: Dict) -> None:
        self._write("client_metric", metric)

    def report_training_status(self, status: str, run_id: Any = None) -> None:
        self._write("status", {"status": status, "run_id": run_id or self.run_id})

    def log(self, metrics: Dict) -> None:
        self._write("metric", metrics)


def _global_sink() -> MLOpsMetrics:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = MLOpsMetrics()
    return _GLOBAL


def init(args: Any) -> MLOpsMetrics:
    global _GLOBAL
    _GLOBAL = MLOpsMetrics(args)
    return _GLOBAL


def log(metrics: Dict) -> None:
    """``fedml.mlops.log`` parity."""
    _global_sink().log(metrics)


def log_metric(metrics: Dict) -> None:
    _global_sink().log(metrics)


def log_artifact(path: str, artifact_name: str = None,
                 artifact_type: str = "general") -> str:
    """``fedml.mlops.log_artifact`` parity: copy the file into the sink's
    artifacts dir and record it. Returns the stored path."""
    import shutil

    sink = _global_sink()
    name = artifact_name or os.path.basename(path)
    dst_dir = os.path.join(sink._dir, "artifacts")
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, name)
    shutil.copy2(path, dst)
    sink._write("artifact", {"name": name, "type": artifact_type,
                             "path": dst})
    return dst


def log_model(model_name: str, params: Any) -> str:
    """``fedml.mlops.log_model`` parity: persist a params pytree into the
    artifacts dir (pickle-free serializer). Returns the stored path."""
    from fedml_tpu.utils.serialization import safe_dumps

    sink = _global_sink()
    dst_dir = os.path.join(sink._dir, "artifacts")
    os.makedirs(dst_dir, exist_ok=True)
    dst = os.path.join(dst_dir, f"{model_name}.fedml")
    with open(dst, "wb") as f:
        f.write(safe_dumps(params))
    sink._write("model", {"name": model_name, "path": dst})
    return dst


def log_llm_record(record: Dict, record_type: str = "inference") -> None:
    """``fedml.mlops.log_llm_record`` parity: prompt/response telemetry."""
    _global_sink()._write("llm_record", {"record_type": record_type,
                                         **record})


def log_round_info(total_rounds: int, round_idx: int) -> None:
    _global_sink()._write("round_info", {"total_rounds": int(total_rounds),
                                         "round_idx": int(round_idx)})
