"""Metrics/logging with a local JSONL sink.

Parity: ``core/mlops/mlops_metrics.py`` + the public ``fedml.mlops.log*``
API (``mlops/__init__.py:23-182``). The hosted MQTT/REST control plane is
absent by design; the sink writes JSONL under ``.fedml_logs/run_<id>/`` and
mirrors to wandb when enabled and available.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("fedml_tpu.mlops")

_GLOBAL: "MLOpsMetrics | None" = None


class MLOpsMetrics:
    def __init__(self, args: Any = None, sink_dir: Optional[str] = None):
        run_id = str(getattr(args, "run_id", "0")) if args else "0"
        self.run_id = run_id
        self._dir = sink_dir or os.path.join(
            str(getattr(args, "log_file_dir", "") or ".fedml_logs"), f"run_{run_id}"
        )
        self._lock = threading.Lock()
        self._wandb = None
        if args is not None and bool(getattr(args, "enable_wandb", False)):
            try:
                import wandb

                self._wandb = wandb
            except ImportError:
                logger.warning("wandb requested but not installed; using local sink")

    def _write(self, kind: str, payload: Dict) -> None:
        os.makedirs(self._dir, exist_ok=True)
        rec = {"ts": time.time(), "kind": kind, **payload}
        with self._lock:
            with open(os.path.join(self._dir, "metrics.jsonl"), "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        if self._wandb is not None and kind == "metric":
            self._wandb.log(payload)

    def report_server_training_metric(self, metric: Dict) -> None:
        self._write("server_metric", metric)

    def report_client_training_metric(self, metric: Dict) -> None:
        self._write("client_metric", metric)

    def report_training_status(self, status: str, run_id: Any = None) -> None:
        self._write("status", {"status": status, "run_id": run_id or self.run_id})

    def log(self, metrics: Dict) -> None:
        self._write("metric", metrics)


def _global_sink() -> MLOpsMetrics:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = MLOpsMetrics()
    return _GLOBAL


def init(args: Any) -> MLOpsMetrics:
    global _GLOBAL
    _GLOBAL = MLOpsMetrics(args)
    return _GLOBAL


def log(metrics: Dict) -> None:
    """``fedml.mlops.log`` parity."""
    _global_sink().log(metrics)


def log_metric(metrics: Dict) -> None:
    _global_sink().log(metrics)
