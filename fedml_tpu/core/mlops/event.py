"""Span-event profiler.

Parity: ``core/mlops/mlops_profiler_event.py:9`` — ``log_event_started/
log_event_ended`` timestamped spans. Transport here is a local JSONL sink
(plus optional ``jax.profiler`` traces) instead of MQTT; the hosted control
plane can attach later via the same interface.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple


class MLOpsProfilerEvent:
    def __init__(self, args: Any = None, sink_path: Optional[str] = None):
        self.enabled = bool(getattr(args, "sys_perf_profiling", True)) if args else True
        run_id = str(getattr(args, "run_id", "0")) if args else "0"
        base = sink_path or os.path.join(
            str(getattr(args, "log_file_dir", "") or ".fedml_logs"), f"run_{run_id}"
        )
        self._dir = base
        self._lock = threading.Lock()
        self._open_spans: Dict[Tuple[str, Any], float] = {}
        self._events = []
        self._jax_trace_dir = getattr(args, "jax_trace_dir", None) if args else None

    def log_event_started(self, event_name: str, event_edge_id: Any = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._open_spans[(event_name, event_edge_id)] = time.time()

    def log_event_ended(self, event_name: str, event_edge_id: Any = 0) -> None:
        if not self.enabled:
            return
        now = time.time()
        with self._lock:
            t0 = self._open_spans.pop((event_name, event_edge_id), now)
            self._events.append(
                {
                    "event": event_name,
                    "edge_id": event_edge_id,
                    "started": t0,
                    "ended": now,
                    "duration_ms": (now - t0) * 1000.0,
                }
            )

    def spans(self):
        return list(self._events)

    def flush(self) -> Optional[str]:
        if not self._events:
            return None
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, "events.jsonl")
        with open(path, "a") as f:
            for e in self._events:
                f.write(json.dumps(e) + "\n")
        self._events.clear()
        return path

    # jax profiler passthrough for deep TPU traces
    def start_trace(self):
        if self._jax_trace_dir:
            import jax

            jax.profiler.start_trace(self._jax_trace_dir)

    def stop_trace(self):
        if self._jax_trace_dir:
            import jax

            jax.profiler.stop_trace()
