"""Span-event profiler — thin facade over the telemetry tracer.

Parity: ``core/mlops/mlops_profiler_event.py:9`` — ``log_event_started/
log_event_ended`` timestamped spans. The recording engine is
:class:`fedml_tpu.telemetry.Tracer` (same span records, same
``events.jsonl`` sink file as before); this class keeps the reference's
started/ended-by-name API for existing call sites.

Durability: spans auto-flush when the buffer passes ``flush_threshold``
and again at interpreter exit, so a caller that never reaches ``flush()``
(crash, SIGTERM path, forgotten call) loses at most the current buffer
tail instead of the whole run.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from fedml_tpu.telemetry import Tracer


class MLOpsProfilerEvent:
    def __init__(self, args: Any = None, sink_path: Optional[str] = None,
                 flush_threshold: int = 512):
        self.enabled = bool(getattr(args, "sys_perf_profiling", True)) if args else True
        run_id = str(getattr(args, "run_id", "0")) if args else "0"
        base = sink_path or os.path.join(
            str(getattr(args, "log_file_dir", "") or ".fedml_logs"), f"run_{run_id}"
        )
        self._dir = base
        self._lock = threading.Lock()
        self._open_spans: Dict[Tuple[str, Any], Any] = {}
        # threshold auto-flush + the tracer module's shared atexit hook
        # (weak-ref'd, so profilers stay collectable) cover the
        # never-calls-flush() case
        self._tracer = Tracer(sink_dir=base, filename="events.jsonl",
                              buffer_limit=max(int(flush_threshold), 1))
        self._jax_trace_dir = getattr(args, "jax_trace_dir", None) if args else None

    def log_event_started(self, event_name: str, event_edge_id: Any = 0) -> None:
        if not self.enabled:
            return
        span = self._tracer.begin(f"event/{event_name}", edge_id=event_edge_id)
        with self._lock:
            self._open_spans[(event_name, event_edge_id)] = span

    def log_event_ended(self, event_name: str, event_edge_id: Any = 0) -> None:
        if not self.enabled:
            return
        now = time.time()
        with self._lock:
            span = self._open_spans.pop((event_name, event_edge_id), None)
        if span is None:
            # unmatched end: record an explicit zero-duration marker, not a
            # fabricated span pretending it started just now
            span = self._tracer.begin(f"event/{event_name}",
                                      edge_id=event_edge_id, unmatched=True)
            span.started = now
            self._tracer.end(span, ended=now)
            return
        self._tracer.end(span, ended=now)

    def spans(self):
        """Buffered (not-yet-flushed) spans in the legacy record shape."""
        out = []
        for rec in self._tracer.records():
            attrs = rec.get("attrs", {})
            out.append({
                "event": rec["name"].split("/", 1)[-1],
                "edge_id": attrs.get("edge_id", 0),
                "started": rec["started"],
                "ended": rec["ended"],
                "duration_ms": 0.0 if attrs.get("unmatched")
                else rec["duration_ms"],
            })
        return out

    def flush(self) -> Optional[str]:
        return self._tracer.flush()

    # deep-trace facade: the old direct jax.profiler passthrough (wired
    # to nothing, fighting any other trace owner for the profiler
    # singleton) is retired — manual traces now go through the ONE
    # budgeted TraceController the profile CLI and the online doctor's
    # auto-captures also use
    def start_trace(self) -> bool:
        if not self._jax_trace_dir:
            return False
        from fedml_tpu.telemetry.profiling import get_trace_controller

        return get_trace_controller().start_manual(self._jax_trace_dir)

    def stop_trace(self):
        if not self._jax_trace_dir:
            return None
        from fedml_tpu.telemetry.profiling import get_trace_controller

        return get_trace_controller().stop_manual()
