from fedml_tpu.core.mlops.event import MLOpsProfilerEvent
from fedml_tpu.core.mlops.metrics import MLOpsMetrics, log, log_metric

__all__ = ["MLOpsProfilerEvent", "MLOpsMetrics", "log", "log_metric"]
