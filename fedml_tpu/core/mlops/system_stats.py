"""System/device performance sampling.

Parity: ``core/mlops/mlops_device_perfs.py`` + ``system_stats.py`` (psutil
CPU/mem/disk/net + GPU utilization shipped to the backend). TPU edition:
psutil host stats plus per-device HBM occupancy from
``jax.Device.memory_stats()`` (the TPU equivalent of nvidia-smi memory),
sampled on a daemon thread into the local JSONL sink.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from fedml_tpu.core.mlops.metrics import MLOpsMetrics


def sample_system_stats() -> Dict:
    out: Dict = {"ts": time.time()}
    try:
        import psutil

        out["cpu_percent"] = psutil.cpu_percent(interval=None)
        vm = psutil.virtual_memory()
        out["mem_percent"] = vm.percent
        out["mem_used_gb"] = round(vm.used / 2**30, 3)
        try:
            io = psutil.net_io_counters()
            out["net_sent_mb"] = round(io.bytes_sent / 2**20, 2)
            out["net_recv_mb"] = round(io.bytes_recv / 2**20, 2)
        except Exception:
            pass
    except Exception:
        out["psutil"] = "unavailable"
    return out


def sample_device_stats() -> List[Dict]:
    devices = []
    try:
        import jax

        for d in jax.local_devices():
            entry: Dict = {"id": d.id, "kind": d.device_kind,
                           "platform": d.platform}
            try:
                ms = d.memory_stats() or {}
                if "bytes_in_use" in ms:
                    entry["hbm_used_gb"] = round(ms["bytes_in_use"] / 2**30, 3)
                if "bytes_limit" in ms:
                    entry["hbm_limit_gb"] = round(ms["bytes_limit"] / 2**30, 3)
            except Exception:
                pass
            devices.append(entry)
    except Exception:
        pass
    return devices


class SysStatsSampler:
    """Periodic sampler → metrics sink (`{"sys_stats": ..., "devices": ...}`)."""

    def __init__(self, args: Any = None, sink_dir: Optional[str] = None,
                 interval_s: float = 10.0, run_id: str = "0"):
        self.run_id = str(run_id)
        self._metrics = MLOpsMetrics(args, sink_dir=sink_dir)
        self._interval = float(interval_s)
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self.samples = 0
        # sample_once() is public API and the poll-thread body: the
        # sample counter is shared state, so the increment takes a lock
        self._lock = threading.Lock()

    def sample_once(self) -> Dict:
        entry = {
            "run_id": self.run_id,
            "sys_stats": sample_system_stats(),
            "devices": sample_device_stats(),
        }
        self._metrics.log(entry)
        with self._lock:
            self.samples += 1
        return entry

    def start(self) -> "SysStatsSampler":
        if self._thread is None:
            self._stopping.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                self.sample_once()
            except Exception:
                pass
            self._stopping.wait(self._interval)
