"""Runtime log daemon — tails run logs and ships them to the sink.

Parity: ``core/mlops/mlops_runtime_log_daemon.py`` (504 LoC: tail run log
files, batch lines, POST to the MLOps backend). Local-sink edition: a
daemon thread follows the file from its current end, batches appended
lines, and writes them into the JSONL metrics sink tagged with the run id
— the same stream the scheduler agent and endpoint monitor use, so one
`tail -f` of the sink shows a run's logs, status, and metrics together.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from fedml_tpu.core.mlops.metrics import MLOpsMetrics


class MLOpsRuntimeLogDaemon:
    def __init__(self, run_id: str, log_path: str, args: Any = None,
                 sink_dir: Optional[str] = None,
                 poll_interval: float = 0.2, batch_lines: int = 64):
        self.run_id = str(run_id)
        self.log_path = os.path.abspath(log_path)
        self._metrics = MLOpsMetrics(args, sink_dir=sink_dir)
        self._poll = float(poll_interval)
        self._batch = int(batch_lines)
        self._offset = 0
        self._line_no = 0
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        # flush() is public API and also the poll-thread body: without a
        # lock a caller's flush racing the daemon's reads the same byte
        # range twice and double-ships those log lines
        self._lock = threading.Lock()

    def start(self, from_beginning: bool = True) -> "MLOpsRuntimeLogDaemon":
        if not from_beginning and os.path.exists(self.log_path):
            with self._lock:
                self._offset = os.path.getsize(self.log_path)
        if self._thread is None:
            self._stopping.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.flush()

    def flush(self) -> int:
        """Ship anything appended since the last poll; returns lines shipped."""
        with self._lock:
            if not os.path.exists(self.log_path):
                return 0
            size = os.path.getsize(self.log_path)
            if size < self._offset:  # truncated/rotated: restart from the top
                self._offset = 0
            if size == self._offset:
                return 0
            with open(self.log_path, "rb") as f:
                f.seek(self._offset)
                data = f.read(size - self._offset)
            # only complete lines ship; a partial trailing line waits
            last_nl = data.rfind(b"\n")
            if last_nl < 0:
                return 0
            self._offset += last_nl + 1
            lines = data[: last_nl + 1].decode(errors="replace").splitlines()
            shipped = 0
            for i in range(0, len(lines), self._batch):
                chunk = lines[i : i + self._batch]
                self._metrics.log({
                    "run_id": self.run_id,
                    "log_lines": chunk,
                    "line_start": self._line_no,
                })
                self._line_no += len(chunk)
                shipped += len(chunk)
            return shipped

    def _loop(self) -> None:
        while not self._stopping.is_set():
            try:
                self.flush()
            except OSError:
                pass
            time.sleep(self._poll)
