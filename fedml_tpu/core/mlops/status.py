"""Run-status state machine.

Parity target: ``core/mlops/mlops_status.py`` + the status constants used
by the agents (``slave/client_constants.py`` / ``master/server_constants``:
IDLE/UPGRADING/QUEUED/INITIALIZING/TRAINING/STOPPING/KILLED/FAILED/
FINISHED/EXCEPTION transitions). The reference scatters transition checks
across runners; here one machine validates transitions and mirrors every
change into the local metrics sink, so agents and engines share a single
source of truth.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional


class RunStatus:
    IDLE = "IDLE"
    QUEUED = "QUEUED"
    PROVISIONING = "PROVISIONING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    # supervision: the run's process died abnormally and the agent is
    # waiting out the restart backoff before relaunching it
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    KILLED = "KILLED"
    # preemption: the run was gracefully quiesced (SIGTERM + grace) so a
    # master can reschedule it elsewhere; terminal FOR THIS AGENT — the
    # job plane supersedes the run with a resumed one on another node
    PREEMPTED = "PREEMPTED"
    EXCEPTION = "EXCEPTION"

    TERMINAL = {FINISHED, FAILED, KILLED, PREEMPTED, EXCEPTION}

    _ALLOWED = {
        IDLE: {QUEUED, PROVISIONING, RUNNING, KILLED},
        QUEUED: {PROVISIONING, RUNNING, KILLED, FAILED},
        PROVISIONING: {RUNNING, FAILED, KILLED, EXCEPTION},
        RUNNING: {STOPPING, RESTARTING, FINISHED, FAILED, KILLED, EXCEPTION},
        RESTARTING: {RUNNING, STOPPING, FAILED, KILLED},
        STOPPING: {KILLED, PREEMPTED, FINISHED, FAILED, EXCEPTION},
        FINISHED: set(),
        FAILED: set(),
        KILLED: set(),
        PREEMPTED: set(),
        EXCEPTION: set(),
    }

    @classmethod
    def can_transition(cls, src: str, dst: str) -> bool:
        return dst in cls._ALLOWED.get(src, set())


class RunStatusMachine:
    """Validated status holder for one run; mirrors changes to observers."""

    def __init__(self, run_id: Any, sink: Optional[Callable[[Dict], None]] = None):
        self.run_id = run_id
        self.status = RunStatus.IDLE
        self.history: List[Dict] = []
        self._sink = sink

    def transition(self, dst: str, reason: str = "") -> bool:
        """Returns True if applied; False (no-op) for an illegal move."""
        if dst == self.status:
            return True
        if not RunStatus.can_transition(self.status, dst):
            return False
        entry = {
            "run_id": self.run_id,
            "from": self.status,
            "to": dst,
            "reason": reason,
            "ts": time.time(),
        }
        self.status = dst
        self.history.append(entry)
        if self._sink is not None:
            try:
                self._sink(entry)
            except Exception:
                pass
        return True

    @property
    def is_terminal(self) -> bool:
        return self.status in RunStatus.TERMINAL
