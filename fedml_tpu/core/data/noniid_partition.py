"""Non-IID Dirichlet partitioning.

Semantics parity with ``core/data/noniid_partition.py:6-130`` in the
reference: per-class Dirichlet(alpha) proportions across clients, with the
balancing rule that a client already holding >= N/num_clients samples gets
zero share of further classes (same rebalancing trick as the reference's
``partition_class_samples_with_dirichlet_distribution``).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np


def partition_class_samples_with_dirichlet_distribution(
    N: int,
    alpha: float,
    client_num: int,
    idx_batch: List[List[int]],
    idx_k: np.ndarray,
    rng: np.random.Generator,
):
    rng.shuffle(idx_k)
    proportions = rng.dirichlet(np.repeat(alpha, client_num))
    # zero out clients already at capacity, renormalize
    proportions = np.array(
        [p * (len(idx_j) < N / client_num) for p, idx_j in zip(proportions, idx_batch)]
    )
    s = proportions.sum()
    if s <= 0:
        proportions = np.full(client_num, 1.0 / client_num)
    else:
        proportions = proportions / s
    cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
    idx_batch = [
        idx_j + idx.tolist() for idx_j, idx in zip(idx_batch, np.split(idx_k, cuts))
    ]
    min_size = min(len(idx_j) for idx_j in idx_batch)
    return idx_batch, min_size


def non_iid_partition_with_dirichlet_distribution(
    label_list: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    seed: int = 0,
    task: str = "classification",
) -> Dict[int, np.ndarray]:
    """Return {client_idx: sample_index_array} with Dirichlet(alpha) skew."""
    label_list = np.asarray(label_list)
    N = label_list.shape[0]
    rng = np.random.default_rng(seed)
    min_size = 0
    idx_batch: List[List[int]] = [[] for _ in range(client_num)]
    while min_size < 10 and N >= 10 * client_num:
        idx_batch = [[] for _ in range(client_num)]
        for k in range(classes):
            idx_k = np.where(label_list == k)[0]
            idx_batch, min_size = partition_class_samples_with_dirichlet_distribution(
                N, alpha, client_num, idx_batch, idx_k, rng
            )
    if N < 10 * client_num:  # tiny datasets: round-robin fallback
        order = rng.permutation(N)
        idx_batch = [order[i::client_num].tolist() for i in range(client_num)]
    net_dataidx_map = {}
    for i in range(client_num):
        rng.shuffle(idx_batch[i])
        net_dataidx_map[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return net_dataidx_map


def homo_partition(N: int, client_num: int, seed: int = 0) -> Dict[int, np.ndarray]:
    """IID split: shuffle then deal evenly."""
    rng = np.random.default_rng(seed)
    order = rng.permutation(N)
    return {i: np.sort(order[i::client_num]) for i in range(client_num)}


def record_data_stats(label_list: np.ndarray, net_dataidx_map: Dict[int, np.ndarray]):
    return {
        i: {int(c): int(n) for c, n in zip(*np.unique(label_list[idx], return_counts=True))}
        for i, idx in net_dataidx_map.items()
    }
