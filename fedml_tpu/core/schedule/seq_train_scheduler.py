"""Client→device workload scheduling for parallel simulation.

Parity: ``core/schedule/seq_train_scheduler.py:9`` + ``runtime_estimate.py``
in the reference (DP-based assignment of simulated clients to GPUs using
fitted runtime estimates). TPU-native framing: the output is a *static*
[n_devices, clients_per_device] id matrix (padded with -1) consumed by one
``shard_map``'d round program — scheduling happens on host between rounds,
never inside the compiled program.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class FrozenEstimate:
    """An immutable ``(a, b)`` affine estimate — a point-in-time snapshot
    of a :class:`RuntimeEstimator`.

    The pipelined round engine schedules round ``r+1`` while round ``r``
    is still in flight, so the estimator coefficients it plans with must
    be pinned at a well-defined point in the round sequence: a snapshot
    taken when round ``r`` is handed to the device gives prefetching and
    inline staging the exact same schedule inputs, keeping the two modes
    bit-identical.
    """

    __slots__ = ("a", "b")

    def __init__(self, a: float = 1.0, b: float = 0.0):
        self.a = float(a)
        self.b = float(b)

    def estimate(self, n_samples: float) -> float:
        return self.a * float(n_samples) + self.b


class RuntimeEstimator:
    """Fit t ≈ a * n_samples + b per client from observed round times.

    Parity: ``core/schedule/runtime_estimate.py`` (``t_sample_fit``).
    """

    def __init__(self):
        self._obs: List[Tuple[float, float]] = []  # (n_samples, seconds)
        self.a = 1.0
        self.b = 0.0

    def snapshot(self) -> FrozenEstimate:
        """Freeze the current fit for deferred (prefetch-time) scheduling."""
        return FrozenEstimate(self.a, self.b)

    def observe(self, n_samples: float, seconds: float) -> None:
        self._obs.append((float(n_samples), float(seconds)))
        if len(self._obs) >= 2:
            x = np.asarray([o[0] for o in self._obs])
            y = np.asarray([o[1] for o in self._obs])
            A = np.stack([x, np.ones_like(x)], axis=1)
            sol, *_ = np.linalg.lstsq(A, y, rcond=None)
            self.a, self.b = float(sol[0]), float(sol[1])

    def estimate(self, n_samples: float) -> float:
        return self.a * float(n_samples) + self.b


class SeqTrainScheduler:
    """Greedy LPT (longest-processing-time-first) assignment of clients to
    devices, balancing estimated runtime — the practical equivalent of the
    reference's DP workload solver, with O(S log S) cost.
    """

    def __init__(
        self,
        workloads: Sequence[float],
        constraints_num: int,
        estimator: RuntimeEstimator | None = None,
    ):
        self.workloads = [float(w) for w in workloads]
        self.n_devices = int(constraints_num)
        self.estimator = estimator or RuntimeEstimator()

    def schedule(self) -> List[List[int]]:
        """Return per-device client-index lists, balanced by workload."""
        est = [self.estimator.estimate(w) for w in self.workloads]
        order = np.argsort(est)[::-1]
        loads = np.zeros(self.n_devices)
        assignment: List[List[int]] = [[] for _ in range(self.n_devices)]
        for idx in order:
            d = int(np.argmin(loads))
            assignment[d].append(int(idx))
            loads[d] += est[idx]
        return assignment


def schedule_clients_to_devices(
    client_ids: Sequence[int],
    client_sample_counts: Dict[int, int],
    n_devices: int,
    estimator: RuntimeEstimator | None = None,
) -> np.ndarray:
    """Static [n_devices, slots] id matrix, padded with -1.

    ``slots`` = max clients on any device; every device sees the same
    number of slots so the compiled round program has one shape.
    """
    workloads = [client_sample_counts[c] for c in client_ids]
    sched = SeqTrainScheduler(workloads, n_devices, estimator)
    assignment = sched.schedule()
    slots = max(1, max(len(a) for a in assignment))
    out = np.full((n_devices, slots), -1, dtype=np.int32)
    for d, idxs in enumerate(assignment):
        for s, local_idx in enumerate(idxs):
            out[d, s] = client_ids[local_idx]
    return out
