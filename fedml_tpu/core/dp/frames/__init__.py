"""DP frames: where noise is applied in the FL pipeline.

Parity: ``core/dp/frames/{ldp,cdp,NbAFL,dp_clip}.py``.
- LDP: each client noises its own update before upload.
- CDP: server noises the aggregate.
- NbAFL (Wei et al.): clip + client-side noise + server-side noise scaled by
  the number of participants.
"""
from __future__ import annotations

from typing import Any

import jax

from fedml_tpu.core.dp.frames.dp_clip import clip_update
from fedml_tpu.core.dp.mechanisms import build_mechanism

Pytree = Any


class BaseDPFrame:
    def __init__(self, args: Any):
        self.mechanism = build_mechanism(
            getattr(args, "mechanism_type", "gaussian"),
            float(getattr(args, "epsilon", 1.0)),
            float(getattr(args, "delta", 1e-5)),
            float(getattr(args, "sensitivity", 1.0)),
        )
        self.clipping_norm = getattr(args, "clipping_norm", None)

    def add_local_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return params

    def add_global_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return params


class LocalDP(BaseDPFrame):
    def add_local_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        if self.clipping_norm is not None:
            params = clip_update(params, float(self.clipping_norm))
        return self.mechanism.add_noise(params, key)


class CentralDP(BaseDPFrame):
    def add_global_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return self.mechanism.add_noise(params, key)


class NbAFL(BaseDPFrame):
    """Clip + noise on both sides (NbAFL, IEEE TIFS'20)."""

    def add_local_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        if self.clipping_norm is not None:
            params = clip_update(params, float(self.clipping_norm))
        return self.mechanism.add_noise(params, key)

    def add_global_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return self.mechanism.add_noise(params, key)


def build_dp_frame(solution: str, args: Any) -> BaseDPFrame:
    solution = (solution or "LDP").upper()
    if solution == "LDP":
        return LocalDP(args)
    if solution == "CDP":
        return CentralDP(args)
    if solution == "NBAFL":
        return NbAFL(args)
    raise ValueError(f"unknown dp solution {solution!r}")
