"""L2 clipping of a model update (pytree), jit-friendly."""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_norm

Pytree = Any


@jax.jit
def _clip(params: Pytree, max_norm: jax.Array) -> Pytree:
    norm = tree_norm(params)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return jax.tree.map(lambda x: (x * factor).astype(x.dtype), params)


def clip_update(params: Pytree, max_norm: float) -> Pytree:
    return _clip(params, jnp.float32(max_norm))
