"""DP budget accountant — RDP composition for the Gaussian mechanism.

Parity: the reference's ``core/dp`` budget accountant (tracked per-round
privacy spend). Implementation follows the standard Rényi-DP recipe
(Mironov '17): one Gaussian release with noise multiplier σ (= sigma /
sensitivity) costs RDP(α) = α / (2σ²); T compositions sum; conversion to
(ε, δ)-DP takes the minimum over α of

    ε(α) = T·α/(2σ²) + log(1/δ)/(α − 1).

The accountant also supports a hard ε budget: :meth:`check_budget` raises
once the spend would exceed it, so a run stops *before* over-spending.
"""
from __future__ import annotations

import math
from typing import Any, Optional, Sequence

DEFAULT_ORDERS = tuple([1 + x / 10.0 for x in range(1, 100)]
                       + list(range(11, 64)) + [128, 256, 512])


class RDPAccountant:
    def __init__(self, noise_multiplier: float,
                 orders: Sequence[float] = DEFAULT_ORDERS):
        if noise_multiplier <= 0:
            raise ValueError("noise_multiplier must be positive")
        self.noise_multiplier = float(noise_multiplier)
        self.orders = tuple(orders)
        self.steps = 0

    def step(self, n: int = 1) -> None:
        self.steps += int(n)

    def get_epsilon(self, delta: float) -> float:
        """(ε, δ)-DP spend after the recorded steps."""
        if self.steps == 0:
            return 0.0
        sigma2 = self.noise_multiplier ** 2
        best = math.inf
        for a in self.orders:
            if a <= 1:
                continue
            rdp = self.steps * a / (2.0 * sigma2)
            eps = rdp + math.log(1.0 / delta) / (a - 1.0)
            best = min(best, eps)
        return best


class BudgetAccountant:
    """Run-level accountant bound to the DP config (epsilon/delta are the
    *per-release* calibration; ``max_epsilon`` is the total budget)."""

    def __init__(self, args: Any):
        from fedml_tpu.core.dp.mechanisms import gaussian_sigma

        self.delta = float(getattr(args, "delta", 1e-5))
        eps = float(getattr(args, "epsilon", 1.0))
        sens = float(getattr(args, "sensitivity", 1.0))
        # noise multiplier = sigma / sensitivity for the configured mechanism
        self.noise_multiplier = gaussian_sigma(eps, self.delta, sens) / sens
        self.rdp = RDPAccountant(self.noise_multiplier)
        self.max_epsilon: Optional[float] = None
        if getattr(args, "max_epsilon", None) is not None:
            self.max_epsilon = float(args.max_epsilon)

    def record_release(self, n: int = 1) -> None:
        self.rdp.step(n)

    def epsilon_spent(self) -> float:
        return self.rdp.get_epsilon(self.delta)

    def check_budget(self, pending: int = 1) -> None:
        """Raise BudgetExceeded if the next ``pending`` releases would break
        the budget (a batched release — e.g. mesh LDP keys for n clients —
        must be probed as n compositions, not 1)."""
        if self.max_epsilon is None:
            return
        probe = RDPAccountant(self.noise_multiplier)
        probe.steps = self.rdp.steps + max(1, int(pending))
        if probe.get_epsilon(self.delta) > self.max_epsilon:
            raise BudgetExceededError(
                f"next DP release would exceed max_epsilon={self.max_epsilon} "
                f"(spent ≈ {self.epsilon_spent():.3f} after {self.rdp.steps} releases)"
            )


class BudgetExceededError(RuntimeError):
    pass
