"""Differential-privacy frame — singleton facade.

Parity target: ``core/dp/fedml_differential_privacy.py:13`` with the
reference's frames (LDP, CDP, NbAFL, dp-clip) and mechanisms (gaussian,
laplace). Noise is drawn with ``jax.random`` from a counter-advanced key so
the whole pipeline stays deterministic given ``args.random_seed``.
"""
from __future__ import annotations

import logging
from typing import Any, List, Tuple

import jax

Pytree = Any

DP_LDP = "LDP"
DP_CDP = "CDP"
DP_NBAFL = "NbAFL"


class FedMLDifferentialPrivacy:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.dp_solution = None
        self.frame = None
        self.clipping_norm = None
        self.accountant = None  # RDP budget accountant (gaussian mechanism)
        self._rng_counter = 0
        self._seed = 0

    @classmethod
    def get_instance(cls) -> "FedMLDifferentialPrivacy":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args: Any) -> None:
        self.is_enabled = bool(getattr(args, "enable_dp", False))
        if not self.is_enabled:
            return
        self.dp_solution = getattr(args, "dp_solution_type", DP_LDP)
        self._seed = int(getattr(args, "random_seed", 0)) + 7919
        self.clipping_norm = getattr(args, "clipping_norm", None)
        from fedml_tpu.core.dp.frames import build_dp_frame

        self.frame = build_dp_frame(self.dp_solution, args)
        if str(getattr(args, "mechanism_type", "gaussian")).lower() == "gaussian":
            from fedml_tpu.core.dp.budget_accountant import BudgetAccountant

            self.accountant = BudgetAccountant(args)
        logging.info("DP enabled: %s", self.dp_solution)

    # -- predicates -------------------------------------------------------
    def is_dp_enabled(self) -> bool:
        return self.is_enabled

    def is_local_dp_enabled(self) -> bool:
        return self.is_enabled and self.dp_solution in (DP_LDP, DP_NBAFL)

    def is_global_dp_enabled(self) -> bool:
        return self.is_enabled and self.dp_solution in (DP_CDP, DP_NBAFL)

    is_central_dp_enabled = is_global_dp_enabled

    def is_clipping(self) -> bool:
        return self.is_enabled and self.clipping_norm is not None

    # -- ops --------------------------------------------------------------
    def _next_key(self) -> jax.Array:
        self._rng_counter += 1
        return jax.random.fold_in(jax.random.key(self._seed), self._rng_counter)

    def take_key_data(self, n: int):
        """Raw key data for the next ``n`` counter keys (advances the counter).

        The mesh simulator stages these onto devices so LDP noise drawn
        *inside* the compiled round is bit-identical to the sequential sp
        path calling :meth:`add_local_noise` once per client in order.
        Each key is one noise release — accounted like add_local_noise.
        """
        self._account(n)
        import numpy as np

        return np.stack(
            [np.asarray(jax.random.key_data(self._next_key())) for _ in range(n)]
        )

    def _account(self, n: int = 1) -> None:
        if self.accountant is not None:
            self.accountant.check_budget(pending=n)
            self.accountant.record_release(n)

    def epsilon_spent(self) -> float:
        """Total (ε, δ)-DP spend so far (RDP-composed); 0 when untracked."""
        return self.accountant.epsilon_spent() if self.accountant else 0.0

    def add_local_noise(self, params: Pytree) -> Pytree:
        self._account()
        return self.frame.add_local_noise(params, self._next_key())

    def add_global_noise(self, params: Pytree) -> Pytree:
        self._account()
        return self.frame.add_global_noise(params, self._next_key())

    def global_clip(
        self, client_list: List[Tuple[int, Pytree]]
    ) -> List[Tuple[int, Pytree]]:
        from fedml_tpu.core.dp.frames.dp_clip import clip_update

        return [
            (n, clip_update(p, float(self.clipping_norm))) for n, p in client_list
        ]

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
