"""DP noise mechanisms (gaussian, laplace) as jitted pytree ops.

Parity: ``core/dp/mechanisms/{gaussian,laplace}.py``. Sigma calibration for
the Gaussian mechanism follows the classic analytic bound
sigma = sqrt(2 ln(1.25/delta)) * sensitivity / epsilon.
"""
from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def gaussian_sigma(epsilon: float, delta: float, sensitivity: float) -> float:
    return math.sqrt(2.0 * math.log(1.25 / delta)) * sensitivity / epsilon


def add_gaussian_noise(params: Pytree, key: jax.Array, sigma: float) -> Pytree:
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    noised = [
        leaf + sigma * jax.random.normal(k, leaf.shape, dtype=leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


def add_laplace_noise(params: Pytree, key: jax.Array, scale: float) -> Pytree:
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    noised = [
        leaf + scale * jax.random.laplace(k, leaf.shape, dtype=leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noised)


class Gaussian:
    def __init__(self, epsilon: float, delta: float, sensitivity: float):
        self.sigma = gaussian_sigma(epsilon, delta, sensitivity)

    def add_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return add_gaussian_noise(params, key, self.sigma)


class Laplace:
    def __init__(self, epsilon: float, delta: float, sensitivity: float):
        del delta
        self.scale = sensitivity / epsilon

    def add_noise(self, params: Pytree, key: jax.Array) -> Pytree:
        return add_laplace_noise(params, key, self.scale)


def build_mechanism(name: str, epsilon: float, delta: float, sensitivity: float):
    name = (name or "gaussian").lower()
    if name == "gaussian":
        return Gaussian(epsilon, delta, sensitivity)
    if name == "laplace":
        return Laplace(epsilon, delta, sensitivity)
    raise ValueError(f"unknown DP mechanism {name!r}")
