"""gRPC federation transport.

Parity: ``core/distributed/communication/grpc/grpc_comm_manager.py:30`` —
one gRPC server per rank at base_port+rank, ip table from config. Unlike the
reference (pickled payloads — arbitrary code execution on load), messages
ride the pickle-free safe wire format (``utils/serialization.py``),
so a hostile peer can at worst inject wrong numbers. The proto contract
matches the reference's ``grpc_comm_manager.proto`` (a unary ``sendMessage``
carrying opaque bytes); we register the service generically so no codegen
step is needed.
"""
from __future__ import annotations

import logging
import queue
from concurrent import futures
from typing import Dict, List, Optional

from fedml_tpu.core.distributed.communication.base_com_manager import (
    BaseCommunicationManager,
    Observer,
)
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)

GRPC_BASE_PORT = 8890  # parity: communication/grpc/constants.py
_MAX_MSG = 512 * 1024 * 1024

try:
    import grpc

    GRPC_AVAILABLE = True
except ImportError:  # pragma: no cover
    GRPC_AVAILABLE = False


_SERVICE = "fedml.CommunicationService"
_METHOD = "sendMessage"


class GRPCCommManager(BaseCommunicationManager):
    def __init__(
        self,
        host: str = "0.0.0.0",
        port: Optional[int] = None,
        ip_config: Optional[Dict[int, str]] = None,
        client_id: int = 0,
        client_num: int = 1,
        base_port: int = GRPC_BASE_PORT,
    ):
        if not GRPC_AVAILABLE:
            raise RuntimeError("grpcio is not installed; use LOCAL backend")
        self.rank = int(client_id)
        self.client_num = int(client_num)
        self.base_port = int(base_port)
        self.port = int(port if port is not None else self.base_port + self.rank)
        self.ip_config = ip_config or {i: "127.0.0.1" for i in range(client_num + 1)}
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False

        inbox = self._inbox

        from fedml_tpu.utils.serialization import safe_loads

        def handler(request: bytes, context) -> bytes:
            from fedml_tpu.telemetry import get_registry

            get_registry().counter(
                "comm/wire_bytes_in", labels={"backend": "grpc"}
            ).inc(len(request))
            inbox.put(Message.construct_from_params(safe_loads(request)))
            return b"ok"

        rpc = grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )
        service = grpc.method_handlers_generic_handler(_SERVICE, {_METHOD: rpc})
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4),
            options=[
                ("grpc.max_send_message_length", _MAX_MSG),
                ("grpc.max_receive_message_length", _MAX_MSG),
            ],
        )
        self._server.add_generic_rpc_handlers((service,))
        self._server.add_insecure_port(f"{host}:{self.port}")
        self._server.start()
        self._channels: Dict[int, grpc.Channel] = {}

    def _stub(self, receiver_id: int):
        if receiver_id not in self._channels:
            ip = self.ip_config.get(receiver_id, "127.0.0.1")
            port = self.base_port + int(receiver_id)
            self._channels[receiver_id] = grpc.insecure_channel(
                f"{ip}:{port}",
                options=[
                    ("grpc.max_send_message_length", _MAX_MSG),
                    ("grpc.max_receive_message_length", _MAX_MSG),
                ],
            )
        ch = self._channels[receiver_id]
        return ch.unary_unary(
            f"/{_SERVICE}/{_METHOD}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    def send_message(self, msg: Message) -> None:
        from fedml_tpu.telemetry import get_registry
        from fedml_tpu.utils.serialization import safe_dumps

        payload = safe_dumps(msg.get_params())
        get_registry().counter(
            "comm/wire_bytes_out", labels={"backend": "grpc"}
        ).inc(len(payload))
        # transient failures surface as grpc.RpcError (UNAVAILABLE /
        # DEADLINE_EXCEEDED), which FedMLCommManager's retry policy
        # treats as retryable; wait_for_ready already rides out a peer
        # that is listening but not yet serving
        self._stub(msg.get_receiver_id())(payload, wait_for_ready=True, timeout=120)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                msg = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if msg is None:
                break
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(None)
        self._server.stop(grace=0.5)
        for ch in self._channels.values():
            ch.close()
