"""Broker+object-store federation transport — the MQTT+S3 equivalent.

Parity target: ``mqtt_s3/mqtt_s3_multi_clients_comm_manager.py:20`` — the
reference's DEFAULT cross-silo backend: control messages ride MQTT topics
keyed by run_id/receiver, model payloads are offloaded to S3 and the
message carries only the storage key. Identical shape here:

  control plane:  PubSubBroker topic ``fedml/<run_id>/<receiver_rank>``
  payload plane:  any pytree larger than ``payload_offload_bytes`` is
                  written to the ObjectStore; the wire message replaces it
                  with {MSG_ARG_KEY_MODEL_PARAMS_KEY: key}; the receiver
                  fetches + restores transparently.

Config:
  comm_backend: BROKER
  broker_host/broker_port      — where the PubSubBroker listens
  payload_offload_bytes        — offload threshold (default 64 KiB)
  object_store_dir             — LocalDirObjectStore root (shared dir)
"""
from __future__ import annotations

import logging
import queue
from typing import Dict, List, Optional, Tuple

from fedml_tpu.core.distributed.communication.base_com_manager import (
    BaseCommunicationManager,
    Observer,
)
from fedml_tpu.core.distributed.communication.object_store import (
    ObjectStore,
    create_object_store,
)
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)

# keys whose values are model pytrees eligible for offload (the reference
# offloads exactly the model-params field to S3)
_OFFLOADABLE_KEYS = (Message.MSG_ARG_KEY_MODEL_PARAMS,)


class BrokerCommManager(BaseCommunicationManager):
    def __init__(
        self,
        run_id: str,
        rank: int,
        host: str = "127.0.0.1",
        port: int = 1883,
        object_store: Optional[ObjectStore] = None,
        offload_bytes: int = 64 * 1024,
        protocol: str = "tcp",
        client=None,
    ):
        self.run_id = str(run_id)
        self.rank = int(rank)
        self.store = object_store or create_object_store()
        self.offload_bytes = int(offload_bytes)
        # CAS reclamation: receivers can't delete (a dedup'd CID may still
        # be awaited by sibling receivers), so the sender unpins its own
        # stale generations. The window is PER RECEIVER (a round of
        # distinct per-client payloads must not evict in-flight ones) and
        # an entry is only unpinned once it both ages out of every
        # receiver's window AND exceeds the minimum age.
        self._cas_keep_last = 4
        self._cas_min_age_s = 300.0
        self._cas_sent: Dict[int, List[Tuple[str, float]]] = {}
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False
        if client is None:
            # the protocol seam: 'tcp' = in-tree broker, 'mqtt' = paho
            # against a real MQTT broker (mqtt_compat.PubSubClient contract)
            from fedml_tpu.core.distributed.communication.mqtt_compat import (
                create_pubsub_client,
            )

            client = create_pubsub_client(protocol, host, port)
        self.client = client
        self.client.subscribe(self._topic(self.rank), self._on_frame)

    def _topic(self, rank: int) -> str:
        return f"fedml/{self.run_id}/{rank}"

    def _reclaim_cas(self, cid: str, receiver: int) -> None:
        """Sender-side unpin of CIDs that aged out of every keep window."""
        import time as _time

        now = _time.time()
        window = self._cas_sent.setdefault(receiver, [])
        self._cas_sent[receiver] = window = [
            (c, t) for (c, t) in window if c != cid  # re-sent content stays
        ]
        window.append((cid, now))
        while len(window) > self._cas_keep_last:
            stale, sent_at = window[0]
            if now - sent_at < self._cas_min_age_s:
                break  # still possibly in flight; try again next send
            window.pop(0)
            # a broadcast dedups to one CID across receivers: keep it while
            # any other receiver's window still references it
            if any(stale == c for w in self._cas_sent.values()
                   for (c, _) in w):
                continue
            try:
                self.store.delete_object(stale)
            except Exception:
                logger.debug("cas unpin failed for %s", stale, exc_info=True)

    # -- outbound ---------------------------------------------------------
    def send_message(self, msg: Message) -> None:
        from fedml_tpu.telemetry import get_registry
        from fedml_tpu.utils.serialization import safe_dumps, tree_nbytes

        reg = get_registry()
        params = dict(msg.get_params())
        for key in _OFFLOADABLE_KEYS:
            payload = params.get(key)
            if payload is None:
                continue
            try:
                nbytes = tree_nbytes(payload)
            except TypeError:
                continue  # not a tree of arrays — ship inline
            if nbytes < self.offload_bytes:
                continue
            store_key = self.store.new_key(
                f"{self.run_id}/r{msg.get_sender_id()}")
            blob = safe_dumps(payload)
            # The returned key is authoritative: content-addressed backends
            # (web3/theta CAS) return a CID, not the advisory key.
            store_key = self.store.put_object(store_key, blob)
            reg.counter("comm/offload_bytes").inc(nbytes)
            # the bytes that actually landed in the store — without this
            # the report's raw-vs-wire accounting never sums for offloaded
            # payloads (offload_bytes counts the un-serialized tree)
            reg.counter("comm/offload_wire_bytes").inc(len(blob))
            if self.store.content_addressed:
                self._reclaim_cas(store_key, msg.get_receiver_id())
            del params[key]
            params[Message.MSG_ARG_KEY_MODEL_PARAMS_KEY] = store_key
            params[Message.MSG_ARG_KEY_MODEL_PARAMS_URL] = f"store://{store_key}"
        wire = safe_dumps(params)
        reg.counter("comm/wire_bytes_out").inc(len(wire))
        self.client.publish(self._topic(msg.get_receiver_id()), wire)

    # -- inbound ----------------------------------------------------------
    def _on_frame(self, body: bytes) -> None:
        from fedml_tpu.telemetry import get_registry
        from fedml_tpu.utils.serialization import safe_loads

        get_registry().counter("comm/wire_bytes_in").inc(len(body))
        try:
            params = safe_loads(body)
            store_key = params.pop(Message.MSG_ARG_KEY_MODEL_PARAMS_KEY, None)
            if store_key is not None:
                params.pop(Message.MSG_ARG_KEY_MODEL_PARAMS_URL, None)
                blob = self.store.get_object(store_key)
                params[Message.MSG_ARG_KEY_MODEL_PARAMS] = safe_loads(blob)
                # CAS stores dedup identical broadcasts to one CID — deleting
                # here would destroy the blob before sibling receivers fetch.
                if not self.store.content_addressed:
                    self.store.delete_object(store_key)
            self._inbox.put(Message.construct_from_params(params))
        except Exception:
            logger.exception("rank %d: bad broker frame dropped", self.rank)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                msg = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if msg is None:
                break
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(None)
        self.client.close()
