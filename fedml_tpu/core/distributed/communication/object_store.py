"""Object store — the S3 payload-offload seam.

Parity target: ``core/distributed/communication/s3/remote_storage.py``
(669 LoC of boto3 put/get for model payloads). The API keeps the S3 shape
(bucket-less keys, bytes in/out) behind an ABC so a real S3/GCS backend
drops in later; the in-tree backend is a shared directory — which on
multi-host TPU pods (NFS/gcsfuse mounts) is also the realistic deployment.
"""
from __future__ import annotations

import abc
import os
import tempfile
import uuid
from typing import Optional


class ObjectStore(abc.ABC):
    #: Content-addressed stores dedup identical payloads under one key, so a
    #: receiver must never delete after fetch — another receiver of the same
    #: broadcast may still need the blob (cleanup = unpin/TTL instead).
    content_addressed = False

    @abc.abstractmethod
    def put_object(self, key: str, data: bytes) -> str:
        """Store bytes; returns the key (S3 parity: upload → url)."""

    @abc.abstractmethod
    def get_object(self, key: str) -> bytes:
        ...

    @abc.abstractmethod
    def delete_object(self, key: str) -> None:
        ...

    def new_key(self, prefix: str = "payload") -> str:
        return f"{prefix}/{uuid.uuid4().hex}"


class LocalDirObjectStore(ObjectStore):
    """Directory-backed store with atomic writes (tmp + rename)."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(
            root or os.path.join(tempfile.gettempdir(), "fedml_tpu_store")
        )
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        # Keys arrive off the wire (BrokerCommManager hands the store key
        # straight to get/delete): reject anything that escapes the root.
        if os.path.isabs(key) or os.path.splitdrive(key)[0]:
            raise ValueError(f"object key must be relative: {key!r}")
        path = os.path.realpath(os.path.join(self.root, key))
        root = os.path.realpath(self.root)
        if not (path == root or path.startswith(root + os.sep)):
            raise ValueError(f"object key escapes store root: {key!r}")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return path

    def put_object(self, key: str, data: bytes) -> str:
        path = self._path(key)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic: readers never see partials
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return key

    def get_object(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def delete_object(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass


def create_object_store(args=None) -> ObjectStore:
    """Factory keyed on ``args.remote_storage``.

    local (default) — shared-directory store (NFS/gcsfuse on TPU pods);
    s3 — real S3 REST protocol w/ SigV4 (``s3_store.S3ObjectStore``);
    web3 / theta — content-addressed decentralized stores;
    cas — offline content-addressed twin (CID = sha256).
    Parity: backend choice in the reference's comm-manager selection
    (``mqtt_s3`` / ``mqtt_web3`` / ``mqtt_thetastore`` managers).
    """
    kind = (getattr(args, "remote_storage", None) or "local").lower()
    secret = getattr(args, "ipfs_secret_key", None) if args is not None else None
    if kind == "s3":
        from fedml_tpu.core.distributed.communication.s3_store import S3ObjectStore

        return S3ObjectStore.from_args(args)
    if kind in ("web3", "ipfs"):
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            Web3ObjectStore,
        )

        return Web3ObjectStore(
            upload_uri=getattr(args, "web3_upload_uri", "https://api.web3.storage/upload"),
            download_uri=getattr(args, "web3_download_uri", "https://w3s.link"),
            secret_key=secret,
        )
    if kind == "theta":
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            ThetaObjectStore,
        )

        return ThetaObjectStore(
            rpc_uri=getattr(args, "theta_rpc_uri", "http://localhost:19888/rpc"),
            secret_key=secret,
        )
    if kind == "cas":
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            LocalCASObjectStore,
        )

        return LocalCASObjectStore(
            getattr(args, "object_store_dir", None), secret_key=secret
        )
    root = getattr(args, "object_store_dir", None) if args is not None else None
    return LocalDirObjectStore(root)
