"""XLA-ICI federation transport — ranks are devices on the pod.

The reference's compute-plane collectives are torch.distributed NCCL/gloo
(``simulation/nccl/base_framework/common.py:122-228``); SURVEY §2.10 maps
them to XLA collectives over ICI. Two layers here:

1. *Aggregation* collectives never appear as messages at all — FedAvg-as-
   psum lives inside the compiled round program (mesh simulator /
   ``parallel``). That is the hot path.
2. This class covers the *federation control plane* for intra-pod ranks:
   same ``BaseCommunicationManager`` contract as gRPC/MQTT so engines are
   transport-agnostic, but model payloads stay ON DEVICE — delivery moves
   arrays chip→chip with ``jax.device_put`` (riding ICI; no host copy, no
   serialization), which is the reason to prefer it over gRPC-over-
   loopback inside a pod.

Control metadata still flows through an in-process broker (single-process
runtime) — in a true multi-host deployment the control hop rides DCN while
payload device_put rides ICI, preserving the same interface.
"""
from __future__ import annotations

import logging

import jax

from fedml_tpu.core.distributed.communication.local_comm import (
    LocalCommManager,
)
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)


class XlaIciCommManager(LocalCommManager):
    def __init__(self, run_id: str, rank: int, size: int = 0):
        super().__init__(run_id, rank)
        devices = jax.devices()
        self.device_of_rank = {
            r: devices[r % len(devices)] for r in range(max(size, len(devices)) + 1)
        }

    def send_message(self, msg: Message) -> None:
        receiver = msg.get_receiver_id()
        target = self.device_of_rank.get(receiver)
        payload = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        if payload is not None and target is not None:
            # device→device transfer over ICI; leaves land on the
            # receiver's chip before the control message is delivered
            moved = jax.tree.map(
                lambda x: jax.device_put(x, target)
                if isinstance(x, jax.Array)
                else x,
                payload,
            )
            msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, moved)
        super().send_message(msg)
