"""Shared scaffolding for broker-connected control-plane agents.

Both control planes (the deploy plane's master/workers and the scheduler
plane's master/node agents) need the same primitives: a JSON-over-topic
broker client, a heartbeat-fed peer registry with liveness timeouts, and
a stoppable background-thread lifecycle. Keeping one implementation
prevents the two planes' liveness semantics from drifting.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, List

from fedml_tpu.core.distributed.communication.broker import BrokerClient

logger = logging.getLogger(__name__)


class BrokerJsonAgent:
    """A broker participant exchanging JSON control messages."""

    def __init__(self, broker_host: str, broker_port: int):
        self._client = BrokerClient(broker_host, broker_port)
        self._stopping = threading.Event()
        self._threads: List[threading.Thread] = []

    def subscribe_json(self, topic: str, handler: Callable[[Dict], None]) -> None:
        def _on_frame(body: bytes) -> None:
            try:
                msg = json.loads(body)
            except ValueError:
                logger.warning("%s: bad frame on %s", type(self).__name__, topic)
                return
            handler(msg)

        self._client.subscribe(topic, _on_frame)

    def publish_json(self, topic: str, msg: Dict,
                     best_effort: bool = False) -> None:
        """Publish a JSON control message.

        ``best_effort=True`` is for periodic traffic (heartbeats, status
        re-sends) where the next tick retransmits anyway. One-shot
        commands (start_run, stop_run, deploy...) must NOT set it: a
        silently dropped command strands the caller waiting forever.
        """
        try:
            self._client.publish(topic, json.dumps(msg).encode())
        except OSError:
            if not best_effort:
                raise

    def spawn_loop(self, target: Callable[[], None]) -> None:
        t = threading.Thread(target=target, daemon=True)
        t.start()
        self._threads.append(t)

    def stop_agent(self) -> None:
        self._stopping.set()
        self._client.close()


class PeerRegistry:
    """Heartbeat-fed liveness registry (peer_id → attrs + last_seen)."""

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._peers: Dict[str, Dict] = {}
        self._lock = threading.Lock()

    def touch(self, peer_id: str, **attrs) -> None:
        with self._lock:
            info = self._peers.setdefault(peer_id, {})
            info["last_seen"] = time.time()
            info.update(attrs)

    def get(self, peer_id: str) -> Dict:
        with self._lock:
            return dict(self._peers.get(peer_id, {}))

    def live(self) -> List[str]:
        now = time.time()
        with self._lock:
            return sorted(p for p, info in self._peers.items()
                          if now - info.get("last_seen", 0) < self.timeout_s)

    def dark(self) -> List[str]:
        now = time.time()
        with self._lock:
            return sorted(p for p, info in self._peers.items()
                          if now - info.get("last_seen", 0) >= self.timeout_s)

    def wait_for(self, n: int, timeout: float = 30.0,
                 what: str = "peers") -> List[str]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            live = self.live()
            if len(live) >= n:
                return live
            time.sleep(0.1)
        raise TimeoutError(f"only {len(self.live())}/{n} {what} online")
