"""Deterministic in-process transport — the test/SP federation backbone.

The reference's CI rendezvouses real processes over a hosted MQTT broker
(SURVEY §4 calls this "flaky by construction"); this backend replaces that
with an in-process broker: every rank has an inbox queue, sends are
enqueue-only, and each manager drains its own inbox on its own thread (or
cooperatively via ``pump()``), so protocol FSM tests are fully
deterministic and run in milliseconds.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

from fedml_tpu.core.distributed.communication.base_com_manager import (
    BaseCommunicationManager,
    Observer,
)
from fedml_tpu.core.distributed.message import Message


class LocalBroker:
    """Per-run registry of rank inboxes. Process-global, keyed by run_id."""

    _instances: Dict[str, "LocalBroker"] = {}
    _lock = threading.Lock()

    def __init__(self):
        # NOT a defaultdict: first access races between sender and receiver
        # threads, and two concurrent __missing__ calls would orphan a Queue
        self.inboxes: Dict[int, "queue.Queue[Optional[Message]]"] = {}
        self._inbox_lock = threading.Lock()

    @classmethod
    def get(cls, run_id: str) -> "LocalBroker":
        with cls._lock:
            if run_id not in cls._instances:
                cls._instances[run_id] = cls()
            return cls._instances[run_id]

    @classmethod
    def destroy(cls, run_id: str) -> None:
        with cls._lock:
            cls._instances.pop(run_id, None)

    def inbox(self, rank: int) -> "queue.Queue[Optional[Message]]":
        with self._inbox_lock:
            q = self.inboxes.get(rank)
            if q is None:
                q = queue.Queue()
                self.inboxes[rank] = q
            return q

    def post(self, receiver_id: int, msg: Optional[Message]) -> None:
        self.inbox(receiver_id).put(msg)


class LocalCommManager(BaseCommunicationManager):
    def __init__(self, run_id: str, rank: int):
        self.run_id = str(run_id)
        self.rank = int(rank)
        self.broker = LocalBroker.get(self.run_id)
        self._observers: List[Observer] = []
        self._running = False

    def send_message(self, msg: Message) -> None:
        from fedml_tpu.telemetry import get_registry

        get_registry().counter(
            "comm/messages_delivered", labels={"backend": "local"}
        ).inc()
        self.broker.post(msg.get_receiver_id(), msg)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        inbox = self.broker.inbox(self.rank)
        while self._running:
            try:
                msg = inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if msg is None:  # poison pill
                break
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def pump(self, max_messages: int = 0) -> int:
        """Cooperative drain (no thread): deliver pending messages now."""
        inbox = self.broker.inbox(self.rank)
        n = 0
        while not inbox.empty() and (max_messages == 0 or n < max_messages):
            msg = inbox.get_nowait()
            if msg is None:
                break
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)
            n += 1
        return n

    def stop_receive_message(self) -> None:
        self._running = False
        self.broker.post(self.rank, None)
