"""Decentralized (content-addressed) payload stores — Web3/IPFS + Theta.

Parity targets:
  ``core/distributed/distributed_storage/web3_storage/web3_storage.py`` —
  uploads the pickled model to the web3.storage HTTP API (returns a CID),
  downloads through an IPFS gateway, optionally encrypting the payload
  with a shared secret.
  ``core/distributed/distributed_storage/theta_storage/theta_storage.py`` —
  same shape against a local Theta EdgeStore RPC daemon.

TPU-era redesign decisions:
  * Both speak a plain HTTP contract (``POST {upload_uri}`` → JSON with a
    CID; ``GET {download_uri}/{cid}``) via stdlib urllib — httpx is not a
    baked-in dependency and the protocol is two requests.
  * Content addressing is first-class: ``put_object`` RETURNS the CID and
    the transport must ship that returned key (BrokerCommManager does) —
    the caller-chosen key is advisory only. ``LocalCASObjectStore`` is the
    offline twin (CID = sha256 of the payload) so the content-addressed
    path is testable with zero network.
  * Optional symmetric encryption (the reference's ``ipfs_secret_key``)
    is encrypt-then-MAC with an HMAC-SHA256 counter-mode keystream —
    stdlib-only, authenticated, and keyed per-blob with a random nonce.
"""
from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets as _secrets
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional

from fedml_tpu.core.distributed.communication.object_store import ObjectStore

# --------------------------------------------------------------------------
# Symmetric payload encryption (reference: crypto_api.encrypt/decrypt around
# the uploaded blob when args carry an ipfs_secret_key).
# --------------------------------------------------------------------------

_NONCE = 16
_TAG = 32


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    # One digest per counter value, but batch the counter blocks so the
    # Python-level loop is O(n/32) hmac calls, no per-byte work.
    blocks = (n + 31) // 32
    out = b"".join(
        hmac.new(key, nonce + c.to_bytes(8, "big"), hashlib.sha256).digest()
        for c in range(blocks)
    )
    return out[:n]


def _xor(a: bytes, b: bytes) -> bytes:
    # Constant number of Python ops regardless of size: bigint XOR.
    n = len(a)
    return (int.from_bytes(a, "little") ^ int.from_bytes(b, "little")).to_bytes(n, "little")


def _derive(secret: bytes, label: bytes) -> bytes:
    return hmac.new(secret, label, hashlib.sha256).digest()


def seal(secret: bytes, plaintext: bytes) -> bytes:
    """Encrypt-then-MAC: nonce ‖ ciphertext ‖ HMAC tag."""
    nonce = _secrets.token_bytes(_NONCE)
    enc_key = _derive(secret, b"fedml-tpu-storage-enc")
    mac_key = _derive(secret, b"fedml-tpu-storage-mac")
    ct = _xor(plaintext, _keystream(enc_key, nonce, len(plaintext)))
    tag = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()
    return nonce + ct + tag


def unseal(secret: bytes, blob: bytes) -> bytes:
    if len(blob) < _NONCE + _TAG:
        raise ValueError("sealed blob too short")
    nonce, ct, tag = blob[:_NONCE], blob[_NONCE:-_TAG], blob[-_TAG:]
    enc_key = _derive(secret, b"fedml-tpu-storage-enc")
    mac_key = _derive(secret, b"fedml-tpu-storage-mac")
    want = hmac.new(mac_key, nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise ValueError("sealed blob failed authentication")
    return _xor(ct, _keystream(enc_key, nonce, len(ct)))


# --------------------------------------------------------------------------
# Content-addressed stores
# --------------------------------------------------------------------------


class _CASBase(ObjectStore):
    """Shared encrypt/upload/download skeleton; subclasses move bytes."""

    content_addressed = True

    def __init__(self, secret_key: Optional[str] = None):
        self._secret = secret_key.encode("utf-8") if secret_key else None

    # -- subclass transport hooks -------------------------------------
    def _upload(self, data: bytes) -> str:
        raise NotImplementedError

    def _download(self, cid: str) -> bytes:
        raise NotImplementedError

    def _unpin(self, cid: str) -> None:  # pinning services: delete is best-effort
        pass

    # -- ObjectStore API ----------------------------------------------
    def put_object(self, key: str, data: bytes) -> str:
        if self._secret is not None:
            data = seal(self._secret, data)
        return self._upload(data)  # the CID, not the advisory key

    def get_object(self, key: str) -> bytes:
        data = self._download(key)
        if self._secret is not None:
            data = unseal(self._secret, data)
        return data

    def delete_object(self, key: str) -> None:
        self._unpin(key)


def _http(
    method: str,
    url: str,
    data: Optional[bytes] = None,
    timeout: float = 30.0,
    headers: Optional[dict] = None,
) -> bytes:
    req = urllib.request.Request(url, data=data, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise KeyError(url) from e
        raise IOError(f"{method} {url}: HTTP {e.code} {e.reason}") from e


class Web3ObjectStore(_CASBase):
    """web3.storage-shaped client: POST upload → {"cid": ...}, GET gateway/ipfs/{cid}."""

    def __init__(
        self,
        upload_uri: str,
        download_uri: str,
        api_token: Optional[str] = None,
        secret_key: Optional[str] = None,
        timeout: float = 30.0,
    ):
        super().__init__(secret_key)
        self.upload_uri = upload_uri
        self.download_uri = download_uri.rstrip("/")
        self.api_token = api_token or os.environ.get("WEB3_STORAGE_TOKEN")
        self.timeout = timeout

    def _upload(self, data: bytes) -> str:
        headers = {"Authorization": f"Bearer {self.api_token}"} if self.api_token else None
        body = json.loads(
            _http("POST", self.upload_uri, data, self.timeout, headers).decode("utf-8")
        )
        cid = body.get("cid")
        if not cid:
            raise IOError(f"web3 upload returned no cid: {body!r}")
        return cid

    def _download(self, cid: str) -> bytes:
        return _http("GET", f"{self.download_uri}/ipfs/{urllib.parse.quote(cid)}",
                     timeout=self.timeout)


class ThetaObjectStore(_CASBase):
    """Theta-EdgeStore-shaped client against a local RPC daemon.

    The reference drives ``edgestore.PutFile``/``GetFile`` JSON-RPC on
    ``localhost:19888``; this build keeps the JSON-RPC envelope but ships
    bytes inline (hex) instead of staging temp files in a playground dir.
    """

    def __init__(self, rpc_uri: str, secret_key: Optional[str] = None, timeout: float = 30.0):
        super().__init__(secret_key)
        self.rpc_uri = rpc_uri
        self.timeout = timeout
        self._rpc_id = 0

    def _rpc(self, rpc_method: str, params: Any) -> Any:
        self._rpc_id += 1
        envelope = {"jsonrpc": "2.0", "id": self._rpc_id, "method": rpc_method,
                    "params": params}
        body = _http("POST", self.rpc_uri, json.dumps(envelope).encode("utf-8"),
                     timeout=self.timeout)
        reply = json.loads(body.decode("utf-8"))
        if reply.get("error"):
            raise IOError(f"theta rpc {rpc_method}: {reply['error']}")
        return reply.get("result")

    def _upload(self, data: bytes) -> str:
        result = self._rpc("edgestore.PutData", [{"val": data.hex()}])
        cid = (result or {}).get("key")
        if not cid:
            raise IOError(f"theta PutData returned no key: {result!r}")
        return cid

    def _download(self, cid: str) -> bytes:
        result = self._rpc("edgestore.GetData", [{"key": cid}])
        val = (result or {}).get("val")
        if val is None:
            raise KeyError(cid)
        return bytes.fromhex(val)


class LocalCASObjectStore(_CASBase):
    """Offline content-addressed twin: CID = sha256 hex, blobs in a dir."""

    def __init__(self, root: Optional[str] = None, secret_key: Optional[str] = None):
        super().__init__(secret_key)
        self.root = os.path.abspath(
            root or os.path.join(tempfile.gettempdir(), "fedml_tpu_cas")
        )
        os.makedirs(self.root, exist_ok=True)

    def _path(self, cid: str) -> str:
        if not all(c in "0123456789abcdef" for c in cid) or len(cid) != 64:
            raise ValueError(f"not a CID: {cid!r}")
        return os.path.join(self.root, cid)

    def _upload(self, data: bytes) -> str:
        cid = hashlib.sha256(data).hexdigest()
        path = self._path(cid)
        if not os.path.exists(path):  # CAS: identical content is one blob
            fd, tmp = tempfile.mkstemp(dir=self.root)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return cid

    def _download(self, cid: str) -> bytes:
        try:
            with open(self._path(cid), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(cid) from None

    def _unpin(self, cid: str) -> None:
        try:
            os.unlink(self._path(cid))
        except (FileNotFoundError, ValueError):
            pass
