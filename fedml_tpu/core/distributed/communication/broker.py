"""Lightweight TCP pub/sub broker — the MQTT stand-in.

Parity target: the reference's default cross-silo control plane is a
hosted MQTT broker (``mqtt_s3/mqtt_s3_multi_clients_comm_manager.py:20``,
topics keyed by run_id/client). This environment ships no paho-mqtt and no
external broker, so the framework carries its own: a single-process
``PubSubBroker`` speaking a length-prefixed binary frame protocol

    frame   := u32 len ‖ payload
    payload := op (1 byte: S=subscribe, P=publish) ‖ u16 topic_len ‖ topic
               ‖ body

over TCP, with MQTT semantics (topic strings, fan-out to all subscribers,
QoS0). Any rank can host it; everyone else dials host:port — the same
deployment shape as a small MQTT broker, without the dependency.
"""
from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from fedml_tpu.telemetry import (
    activate_context,
    current_context,
    deactivate_context,
    get_registry,
    unwrap_frame_body,
    wrap_frame_body,
)

logger = logging.getLogger(__name__)

_OP_SUB = b"S"
_OP_PUB = b"P"
MAX_FRAME = 1 << 30


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds limit")
    return _recv_exact(sock, n)


def _hard_close(sock: socket.socket) -> None:
    """shutdown + close: a bare close() while another thread is blocked
    in recv() on the same socket can defer the FIN on some kernels
    (gVisor), leaving the peer parked forever; shutdown always wakes
    both sides immediately."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass  # never connected / already down
    try:
        sock.close()
    except OSError:  # pragma: no cover - already closed
        pass


def _pack(op: bytes, topic: str, body: bytes = b"") -> bytes:
    t = topic.encode()
    return op + struct.pack(">H", len(t)) + t + body


def _unpack(payload: bytes) -> Tuple[bytes, str, bytes]:
    op = payload[:1]
    (tlen,) = struct.unpack(">H", payload[1:3])
    topic = payload[3 : 3 + tlen].decode()
    return op, topic, payload[3 + tlen :]


class PubSubBroker:
    """The broker process: accepts connections, routes publishes."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        # a finite accept timeout keeps the loop interruptible: on some
        # kernels (gVisor) closing a listener does NOT unblock a thread
        # parked in accept(), which would pin the port against restarts
        self._srv.settimeout(0.5)
        self._subs: Dict[str, List[socket.socket]] = {}
        self._lock = threading.Lock()
        reg = get_registry()
        self._m_bytes_in = reg.counter("broker/bytes_in")
        self._m_bytes_out = reg.counter("broker/bytes_out")
        self._m_publish = reg.counter("broker/publish_frames")
        self._m_fanout = reg.counter("broker/fanout_deliveries")
        self._m_dropped = reg.counter("broker/dropped_deliveries")
        self._m_subscribers = reg.gauge("broker/subscriptions")
        self._m_publish_ms = reg.histogram("broker/publish_ms")
        # one write lock per subscriber socket: concurrent publishers fan
        # out from their own _serve threads, and interleaved sendall calls
        # would corrupt the length-prefixed frame stream
        self._wlocks: Dict[socket.socket, threading.Lock] = {}
        self._stopping = threading.Event()
        self._threads: List[threading.Thread] = []

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.getsockname()[:2]

    def start(self) -> "PubSubBroker":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._srv.accept()
            except TimeoutError:
                continue  # periodic stop check (see settimeout above)
            except OSError:
                return
            conn.settimeout(None)  # serve threads use blocking reads
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stopping.is_set():
                payload = _recv_frame(conn)
                if payload is None:
                    break
                self._m_bytes_in.inc(len(payload) + 4)  # +4: length prefix
                op, topic, body = _unpack(payload)
                if op == _OP_SUB:
                    with self._lock:
                        self._subs.setdefault(topic, []).append(conn)
                        self._wlocks.setdefault(conn, threading.Lock())
                        self._m_subscribers.set(
                            sum(len(s) for s in self._subs.values()))
                elif op == _OP_PUB:
                    self._route(topic, body)
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            with self._lock:
                for subs in self._subs.values():
                    if conn in subs:
                        subs.remove(conn)
                self._wlocks.pop(conn, None)
                self._m_subscribers.set(
                    sum(len(s) for s in self._subs.values()))
            _hard_close(conn)

    def _route(self, topic: str, body: bytes) -> None:
        with self._lock:
            targets = [
                (sock, self._wlocks.setdefault(sock, threading.Lock()))
                for sock in self._subs.get(topic, [])
            ]
        frame = _pack(_OP_PUB, topic, body)
        self._m_publish.inc()
        t0 = time.time()
        for sock, wlock in targets:
            try:
                with wlock:  # serialize frames per subscriber socket
                    _send_frame(sock, frame)
                self._m_bytes_out.inc(len(frame) + 4)
                self._m_fanout.inc()
            except OSError:
                self._m_dropped.inc()  # subscriber died; pruned on exit
        self._m_publish_ms.observe((time.time() - t0) * 1e3)

    def stop(self) -> None:
        self._stopping.set()
        # wake a parked accept() so the close below actually releases the
        # binding (close-while-blocked leaks the port on some kernels)
        try:
            socket.create_connection(self.address, timeout=1).close()
        except OSError:  # pragma: no cover - already unreachable
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        # drop every client connection too: a dead broker has no live
        # sockets (subscribers must observe the loss to reconnect), and
        # lingering conns would hold the port against a restart
        with self._lock:
            conns = set(self._wlocks)
            for subs in self._subs.values():
                conns.update(subs)
        for conn in conns:
            _hard_close(conn)


class NativePubSubBroker:
    """The C++ epoll broker (``native/broker.cpp``) behind the same surface.

    Same wire protocol and semantics as :class:`PubSubBroker`; parity is
    enforced by running the client test suite against both. This is the
    deployment-grade control plane (single-threaded epoll, buffered
    non-blocking writes) — the runtime-native component the reference
    delegates to a hosted MQTT broker.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import subprocess

        binary = self._ensure_built()
        self._proc = subprocess.Popen(
            [binary, str(port), host], stdout=subprocess.PIPE, text=True
        )
        line = (self._proc.stdout.readline() or "").strip()
        if not line.startswith("LISTENING "):
            self._proc.kill()
            raise RuntimeError(f"native broker failed to start: {line!r}")
        self._addr = (host, int(line.split()[1]))

    @staticmethod
    def _ensure_built() -> str:
        import subprocess

        native_dir = os.path.join(
            os.path.dirname(__file__), "..", "..", "..", "..", "native"
        )
        native_dir = os.path.abspath(native_dir)
        binary = os.path.join(native_dir, "broker")
        if not os.path.exists(binary):
            subprocess.run(["make", "-C", native_dir, "broker"],
                           check=True, capture_output=True)
        return binary

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    def start(self) -> "NativePubSubBroker":
        return self  # the process is already serving

    def stop(self) -> None:
        from subprocess import TimeoutExpired

        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except TimeoutExpired:
                self._proc.kill()
                # reap the killed process — without this wait the broker
                # lingers as a zombie for the rest of the test run
                self._proc.wait()


class BrokerClient:
    """Client connection: subscribe(topic, cb) + publish(topic, bytes).

    Trace propagation: when the publishing thread has an open telemetry
    span, the span's context rides a header envelope prepended to the
    body (opaque to both broker implementations); the subscriber strips
    it and activates the context around the handler, so handler-side
    spans stitch into the publisher's trace.

    Resilience: a lost connection is always logged and reported through
    ``on_disconnect``; with ``reconnect=True`` the reader additionally
    re-dials the SAME host:port with jittered backoff, resubscribes
    every topic, and ``publish`` blocks (bounded) for the new socket
    instead of failing — a broker kill/restart mid-run heals without
    the federation noticing beyond the retry metrics. Receiver-side
    dedup of resent frames is the comm manager's job (message ids), not
    the socket layer's.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 propagate_trace: bool = True, reconnect: bool = False,
                 reconnect_attempts: int = 30,
                 reconnect_max_delay_s: float = 2.0,
                 on_disconnect: Optional[Callable[[], None]] = None):
        self._addr = (host, port)
        self._timeout = float(timeout)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(None)
        self._handlers: Dict[str, Callable[[bytes], None]] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._connected = threading.Event()
        self._connected.set()
        self._propagate = bool(propagate_trace)
        self._reconnect = bool(reconnect)
        self._reconnect_attempts = int(reconnect_attempts)
        self._reconnect_max_delay_s = float(reconnect_max_delay_s)
        self.on_disconnect = on_disconnect
        reg = get_registry()
        self._m_pub_bytes = reg.counter("broker/client_bytes_out")
        self._m_recv_bytes = reg.counter("broker/client_bytes_in")
        self._m_disconnects = reg.counter("resilience/broker_disconnects")
        self._m_reconnects = reg.counter("resilience/broker_reconnects")
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def subscribe(self, topic: str, handler: Callable[[bytes], None]) -> None:
        with self._lock:
            self._handlers[topic] = handler
            _send_frame(self._sock, _pack(_OP_SUB, topic))

    def publish(self, topic: str, body: bytes) -> None:
        if self._propagate and current_context() is not None:
            body = wrap_frame_body(body)
        self._m_pub_bytes.inc(len(body))
        # one bounded resend after the reader's reconnect restores the
        # socket; without reconnect the caller sees the raw OSError
        for attempt in (0, 1):
            if self._reconnect and not self._connected.wait(
                    timeout=self._timeout):
                raise ConnectionError(
                    f"broker {self._addr} not reconnected within "
                    f"{self._timeout}s")
            try:
                with self._lock:
                    sock = self._sock
                    _send_frame(sock, _pack(_OP_PUB, topic, body))
                return
            except OSError:
                if not self._reconnect or attempt or self._stopping.is_set():
                    raise
                # only gate on the reader's reconnect if the socket we
                # failed on is STILL current — clearing after the reader
                # already swapped in a healthy socket (and set the
                # event) would wedge every future publish
                with self._lock:
                    if sock is self._sock:
                        self._connected.clear()  # reader will re-dial

    def _on_connection_lost(self) -> None:
        self._connected.clear()
        _hard_close(self._sock)  # release the dead fd before re-dialing
        self._m_disconnects.inc()
        logger.warning("broker connection %s lost%s", self._addr,
                       " - reconnecting" if self._reconnect else "")
        if self.on_disconnect is not None:
            try:
                self.on_disconnect()
            except Exception:  # pragma: no cover - observer must not kill IO
                logger.exception("on_disconnect callback failed")

    def _try_reconnect(self) -> bool:
        """Re-dial with deterministic jittered backoff + resubscribe."""
        from fedml_tpu.resilience.policy import RetryPolicy

        delays = RetryPolicy(
            max_attempts=self._reconnect_attempts + 1, base_delay_s=0.05,
            max_delay_s=self._reconnect_max_delay_s,
            key=f"broker:{self._addr}").delays()
        for delay in delays:
            if self._stopping.is_set():
                return False
            time.sleep(delay)
            try:
                sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                sock.settimeout(None)
            except OSError:
                continue
            with self._lock:
                try:
                    # a restarted broker has empty subscription state:
                    # replay every topic BEFORE publishing the socket —
                    # a half-subscribed socket must not become current
                    for topic in self._handlers:
                        _send_frame(sock, _pack(_OP_SUB, topic))
                except OSError:
                    _hard_close(sock)  # don't leak the failed dial
                    continue
                self._sock = sock
            self._m_reconnects.inc()
            self._connected.set()
            logger.info("broker connection %s restored", self._addr)
            return True
        return False

    def _read_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                payload = _recv_frame(self._sock)
            except OSError:
                payload = None
            if payload is None:
                if self._stopping.is_set():
                    return
                self._on_connection_lost()
                if self._reconnect and self._try_reconnect():
                    continue
                return
            _, topic, body = _unpack(payload)
            self._m_recv_bytes.inc(len(body))
            ctx, body = unwrap_frame_body(body)
            handler = self._handlers.get(topic)
            if handler is not None:
                token = activate_context(ctx)
                try:
                    handler(body)
                except Exception:
                    logger.exception("broker handler failed on %s", topic)
                finally:
                    deactivate_context(token)

    def close(self) -> None:
        self._stopping.set()
        self._connected.set()  # unblock publishers waiting on a reconnect
        _hard_close(self._sock)
