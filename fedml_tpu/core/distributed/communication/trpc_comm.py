"""torch-RPC federation transport (TRPC).

Parity: ``core/distributed/communication/trpc/trpc_comm_manager.py:21``
— the reference's TRPC backend runs FL messages over
``torch.distributed.rpc`` (TensorPipe), optionally with CUDA-RPC tensor
transfer. TPU re-design: the compute plane never touches torch; this
transport exists for deployments whose *network* fabric is already
torch-RPC (the reference's stated use case), so only the message bytes
ride it. Payloads use the pickle-free safe wire format — NOT torch
pickling — so a hostile peer can at worst inject wrong numbers; and the
CUDA-RPC device-tensor path maps to nothing here (TPU arrays hop
host-side like every cross-network transport).

Ranks rendezvous through the standard MASTER_ADDR/MASTER_PORT
TensorPipe init; each rank registers as worker ``fedml_rank_<i>``.
"""
from __future__ import annotations

import logging
import os
import queue
from typing import Dict, List

from fedml_tpu.core.distributed.communication.base_com_manager import (
    BaseCommunicationManager,
    Observer,
)
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)

try:
    import torch.distributed.rpc as _rpc

    TRPC_AVAILABLE = True
except Exception:  # pragma: no cover
    TRPC_AVAILABLE = False

# rpc target functions are resolved by qualified name on the callee —
# the receiving process finds its manager through this registry
_MANAGERS: Dict[str, "TRPCCommManager"] = {}


def _worker_name(rank: int) -> str:
    return f"fedml_rank_{int(rank)}"


def _deliver(receiver_rank: int, payload: bytes) -> bool:
    """Runs ON THE RECEIVER via rpc_sync: enqueue the wire bytes."""
    mgr = _MANAGERS.get(_worker_name(receiver_rank))
    if mgr is None:
        return False
    mgr._enqueue(payload)
    return True


class TRPCCommManager(BaseCommunicationManager):
    def __init__(
        self,
        client_id: int = 0,
        client_num: int = 1,
        master_addr: str = "127.0.0.1",
        master_port: int = 29500,
        rpc_timeout: float = 120.0,
    ):
        if not TRPC_AVAILABLE:
            raise RuntimeError(
                "torch.distributed.rpc unavailable; use BROKER/GRPC/LOCAL")
        self.rank = int(client_id)
        self.world_size = int(client_num) + 1  # server rank 0 + clients
        self.name = _worker_name(self.rank)
        self._observers: List[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False
        os.environ.setdefault("MASTER_ADDR", str(master_addr))
        os.environ.setdefault("MASTER_PORT", str(master_port))
        _MANAGERS[self.name] = self
        _rpc.init_rpc(
            self.name,
            rank=self.rank,
            world_size=self.world_size,
            rpc_backend_options=_rpc.TensorPipeRpcBackendOptions(
                rpc_timeout=rpc_timeout),
        )
        logger.info("TRPC up: %s / world %d", self.name, self.world_size)

    # -- receiver side -----------------------------------------------------
    def _enqueue(self, payload: bytes) -> None:
        from fedml_tpu.telemetry import get_registry
        from fedml_tpu.utils.serialization import safe_loads

        get_registry().counter(
            "comm/wire_bytes_in", labels={"backend": "trpc"}
        ).inc(len(payload))
        self._inbox.put(Message.construct_from_params(safe_loads(payload)))

    # -- BaseCommunicationManager ------------------------------------------
    def send_message(self, msg: Message) -> None:
        from fedml_tpu.telemetry import get_registry
        from fedml_tpu.utils.serialization import safe_dumps

        receiver = int(msg.get_receiver_id())
        payload = safe_dumps(msg.get_params())
        get_registry().counter(
            "comm/wire_bytes_out", labels={"backend": "trpc"}
        ).inc(len(payload))
        ok = _rpc.rpc_sync(
            _worker_name(receiver), _deliver,
            args=(receiver, payload))
        if not ok:
            # ConnectionError (not RuntimeError): the peer exists but its
            # manager isn't up yet / is restarting — exactly the class of
            # failure FedMLCommManager's backoff retry is meant to absorb
            raise ConnectionError(
                f"TRPC peer {receiver} has no live comm manager")

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                msg = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            if msg is None:
                break
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(None)
        _MANAGERS.pop(self.name, None)
        try:
            # graceful=True blocks until every rank drains outstanding work
            _rpc.shutdown(graceful=True)
        except Exception:  # peers may already be gone on abnormal exit
            try:
                _rpc.shutdown(graceful=False)
            except Exception:
                pass
