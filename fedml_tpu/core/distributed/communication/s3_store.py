"""Real S3-protocol object store — AWS Signature V4 over stdlib HTTP.

Parity target: ``core/distributed/communication/s3/remote_storage.py``
(the reference's 669-LoC boto3 wrapper that uploads model payloads to S3
and hands the URL around over MQTT). This build speaks the actual S3 REST
protocol (path-style ``PUT/GET/DELETE /{bucket}/{key}`` with SigV4
``Authorization`` headers) so it works against AWS S3 or any
S3-compatible endpoint (MinIO, GCS interop mode) with zero third-party
dependencies — boto3 is not in the image, and the wire protocol is small.

Credentials come from the environment (``AWS_ACCESS_KEY_ID`` /
``AWS_SECRET_ACCESS_KEY``), never from job yaml, mirroring the
reference's credential handling.
"""
from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from fedml_tpu.core.distributed.communication.object_store import ObjectStore

_ALGO = "AWS4-HMAC-SHA256"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sigv4_headers(
    method: str,
    url: str,
    payload: bytes,
    access_key: str,
    secret_key: str,
    region: str,
    service: str = "s3",
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """Build the SigV4 ``Authorization`` + ``x-amz-*`` headers for a request.

    Pure function of (request, credentials, clock) so tests can verify the
    canonicalization against an independent implementation.
    """
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    # S3 canonical URI: the on-the-wire (already URI-encoded) path, verbatim —
    # S3 disables path normalization/double-encoding in SigV4.
    canonical_uri = parsed.path or "/"
    canonical_query = ""
    if parsed.query:
        pairs = sorted(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
            for k, v in pairs
        )

    t = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    datestamp = t.strftime("%Y%m%d")
    payload_hash = _sha256_hex(payload)

    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical_headers = (
        f"host:{host}\n"
        f"x-amz-content-sha256:{payload_hash}\n"
        f"x-amz-date:{amz_date}\n"
    )
    canonical_request = "\n".join(
        [method, canonical_uri, canonical_query, canonical_headers, signed_headers, payload_hash]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [_ALGO, amz_date, scope, _sha256_hex(canonical_request.encode("utf-8"))]
    )
    key = ("AWS4" + secret_key).encode("utf-8")
    for part in (datestamp, region, service, "aws4_request"):
        key = _hmac(key, part)
    signature = hmac.new(key, string_to_sign.encode("utf-8"), hashlib.sha256).hexdigest()
    authorization = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": authorization,
    }


class S3ObjectStore(ObjectStore):
    """Path-style S3 client: ``{endpoint}/{bucket}/{key}``."""

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        region: str = "us-east-1",
        access_key: Optional[str] = None,
        secret_key: Optional[str] = None,
        timeout: float = 30.0,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.region = region
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self.timeout = timeout

    @classmethod
    def from_args(cls, args: Any) -> "S3ObjectStore":
        return cls(
            endpoint=getattr(args, "s3_endpoint", "https://s3.amazonaws.com"),
            bucket=getattr(args, "s3_bucket", "fedml-tpu"),
            region=getattr(args, "s3_region", "us-east-1"),
        )

    def _url(self, key: str) -> str:
        if key.startswith("/") or ".." in key.split("/"):
            raise ValueError(f"invalid object key: {key!r}")
        return f"{self.endpoint}/{self.bucket}/{urllib.parse.quote(key, safe='/-_.~')}"

    def _request(self, method: str, key: str, payload: bytes = b"") -> bytes:
        url = self._url(key)
        headers = sigv4_headers(
            method, url, payload, self.access_key, self.secret_key, self.region
        )
        req = urllib.request.Request(url, data=payload or None, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(key) from e
            raise IOError(f"S3 {method} {key}: HTTP {e.code} {e.reason}") from e

    def put_object(self, key: str, data: bytes) -> str:
        self._request("PUT", key, data)
        return key

    def get_object(self, key: str) -> bytes:
        return self._request("GET", key)

    def delete_object(self, key: str) -> None:
        try:
            self._request("DELETE", key)
        except KeyError:
            pass
