"""BaseCommunicationManager + Observer — the transport seam.

Parity: ``core/distributed/communication/base_com_manager.py:7`` and
``observer.py``. Every federation transport (local in-proc, gRPC, XLA-ICI,
MQTT+S3) implements this; engines never see transport details.
"""
from __future__ import annotations

import abc

from fedml_tpu.core.distributed.message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg_params: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abc.abstractmethod
    def add_observer(self, observer: Observer) -> None:
        ...

    @abc.abstractmethod
    def remove_observer(self, observer: Observer) -> None:
        ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Enter the receive loop (blocks until stopped)."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...
