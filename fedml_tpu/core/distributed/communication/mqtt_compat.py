"""MQTT protocol-compat seam for the broker transport.

VERDICT weak #10: the in-tree broker was the only "deployment-shape"
transport, with no seam to swap a real MQTT broker in. This module
defines the minimal pub/sub client contract the federation transport
needs and provides two implementations:

- :class:`TcpBrokerClient` — the in-tree ``PubSubBroker`` client
  (default; zero dependencies);
- :class:`PahoMqttClient` — the same contract over ``paho-mqtt``
  against any real MQTT broker (mosquitto, EMQX, the reference's hosted
  broker). Import-gated: constructing it without paho installed raises
  with instructions instead of failing at import time.

Select via ``comm_args``:

  comm_backend: BROKER
  broker_protocol: tcp        # tcp (in-tree) | mqtt (paho)
  broker_host/broker_port

Both speak the SAME topic scheme (``fedml/<run_id>/<rank>``) and binary
payloads, so the wire format of a federation does not change with the
transport — which is exactly the property the reference's
MqttS3MultiClientsCommManager relies on.
"""
from __future__ import annotations

import threading
import uuid
from typing import Callable

from fedml_tpu.core.distributed.communication.broker import BrokerClient


class PubSubClient:
    """The transport contract: subscribe(topic, cb), publish(topic, bytes),
    close(). Implementations must deliver callbacks on a background
    thread and tolerate concurrent publishes."""

    def subscribe(self, topic: str, handler: Callable[[bytes], None]) -> None:
        raise NotImplementedError

    def publish(self, topic: str, body: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class TcpBrokerClient(PubSubClient):
    """In-tree PubSubBroker client behind the contract.

    Frame-level trace propagation is off: comm messages already carry the
    context as a ``telemetry_ctx`` param header (FedMLCommManager), and
    stacking the frame envelope on top would propagate the same context
    twice per hop.

    Auto-reconnect is ON for the federation transport (paho does the
    same under its own loop): a broker kill/restart mid-run re-dials,
    resubscribes, and resumes delivery; receiver-side message-id dedup
    (FedMLCommManager) absorbs any resulting resends.
    """

    def __init__(self, host: str, port: int, reconnect: bool = True, **_):
        self._client = BrokerClient(host, port, propagate_trace=False,
                                    reconnect=reconnect)

    def subscribe(self, topic, handler):
        self._client.subscribe(topic, handler)

    def publish(self, topic, body):
        self._client.publish(topic, body)

    def close(self):
        self._client.close()


class PahoMqttClient(PubSubClient):
    """paho-mqtt behind the contract (QoS per reference: 2 for control)."""

    def __init__(self, host: str, port: int = 1883, qos: int = 2,
                 client_id: str = "", username: str = "",
                 password: str = "", keepalive: int = 180):
        try:
            import paho.mqtt.client as mqtt
        except ImportError as e:  # pragma: no cover - environment-dependent
            raise RuntimeError(
                "broker_protocol: mqtt requires paho-mqtt "
                "(pip install paho-mqtt); the in-tree 'tcp' protocol needs "
                "no dependencies") from e
        self.qos = int(qos)
        self._handlers = {}
        self._lock = threading.Lock()
        self._connected = threading.Event()
        cid = client_id or f"fedml-tpu-{uuid.uuid4().hex[:8]}"
        if hasattr(mqtt, "CallbackAPIVersion"):  # paho-mqtt >= 2.0
            self._client = mqtt.Client(
                mqtt.CallbackAPIVersion.VERSION1, client_id=cid)
        else:  # paho-mqtt 1.x
            self._client = mqtt.Client(client_id=cid, clean_session=True)
        if username:
            self._client.username_pw_set(username, password)
        self._client.on_connect = self._on_connect
        self._client.on_message = self._on_message
        self._client.connect(host, int(port), keepalive)
        self._client.loop_start()
        if not self._connected.wait(timeout=30):
            raise TimeoutError(f"MQTT broker {host}:{port} unreachable")

    def _on_connect(self, client, userdata, flags, rc, *a):
        self._connected.set()
        with self._lock:  # re-subscribe after reconnects
            for topic in self._handlers:
                client.subscribe(topic, qos=self.qos)

    def _on_message(self, client, userdata, msg):
        with self._lock:
            handler = self._handlers.get(msg.topic)
        if handler is not None:
            handler(msg.payload)

    def subscribe(self, topic, handler):
        with self._lock:
            self._handlers[topic] = handler
        self._client.subscribe(topic, qos=self.qos)

    def publish(self, topic, body):
        self._client.publish(topic, body, qos=self.qos)

    def close(self):
        self._client.loop_stop()
        self._client.disconnect()


PROTOCOLS = {
    "tcp": TcpBrokerClient,
    "mqtt": PahoMqttClient,
}


def create_pubsub_client(protocol: str, host: str, port: int,
                         **kwargs) -> PubSubClient:
    key = str(protocol or "tcp").lower()
    if key not in PROTOCOLS:
        raise ValueError(
            f"unknown broker_protocol {protocol!r}; choose from "
            f"{sorted(PROTOCOLS)}")
    return PROTOCOLS[key](host, port, **kwargs)
