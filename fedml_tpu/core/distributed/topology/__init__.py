"""Topology managers for decentralized FL.

Parity: reference ``core/distributed/topology/`` —
``base_topology_manager.py``, ``symmetric_topology_manager.py``,
``asymmetric_topology_manager.py``. A topology yields per-node neighbor
lists and a row-stochastic mixing matrix W; the decentralized engine
(``simulation/decentralized``) gossips with W, and on TPU the whole gossip
round compiles to one program (mixing is a single [N,N]×[N,D] matmul on
the MXU instead of per-edge messaging).
"""
from __future__ import annotations

import abc
from typing import Any, List

import numpy as np


class BaseTopologyManager(abc.ABC):
    """n nodes, directed edges; W[i, j] = weight node i gives node j."""

    def __init__(self, n: int):
        self.n = int(n)
        self.topology: np.ndarray = np.eye(self.n)

    @abc.abstractmethod
    def generate_topology(self) -> None:
        ...

    def get_in_neighbor_idx_list(self, node: int) -> List[int]:
        return [j for j in range(self.n)
                if self.topology[j, node] > 0 and j != node]

    def get_out_neighbor_idx_list(self, node: int) -> List[int]:
        return [j for j in range(self.n)
                if self.topology[node, j] > 0 and j != node]

    def get_in_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[:, node]

    def get_out_neighbor_weights(self, node: int) -> np.ndarray:
        return self.topology[node]

    @property
    def mixing_matrix(self) -> np.ndarray:
        return self.topology


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring with ``neighbor_num`` symmetric neighbors per side, uniform
    weights (doubly stochastic — gossip converges to the true average)."""

    def __init__(self, n: int, neighbor_num: int = 2):
        super().__init__(n)
        self.neighbor_num = int(neighbor_num)

    def generate_topology(self) -> None:
        w = np.zeros((self.n, self.n))
        per_side = max(1, self.neighbor_num // 2)
        for i in range(self.n):
            w[i, i] = 1.0
            for k in range(1, per_side + 1):
                w[i, (i + k) % self.n] = 1.0
                w[i, (i - k) % self.n] = 1.0
        self.topology = w / w.sum(axis=1, keepdims=True)


class AsymmetricTopologyManager(BaseTopologyManager):
    """Each node picks ``out_neighbor_num`` random out-edges (directed),
    row-normalized. Matches the reference's asymmetric generator."""

    def __init__(self, n: int, out_neighbor_num: int = 2, seed: int = 0):
        super().__init__(n)
        self.out_neighbor_num = min(int(out_neighbor_num), self.n - 1)
        self.seed = int(seed)

    def generate_topology(self) -> None:
        rng = np.random.default_rng(self.seed)
        w = np.eye(self.n)
        for i in range(self.n):
            others = [j for j in range(self.n) if j != i]
            picks = rng.choice(others, size=self.out_neighbor_num, replace=False)
            for j in picks:
                w[i, j] = 1.0
        self.topology = w / w.sum(axis=1, keepdims=True)


class FullyConnectedTopologyManager(BaseTopologyManager):
    def generate_topology(self) -> None:
        self.topology = np.full((self.n, self.n), 1.0 / self.n)
