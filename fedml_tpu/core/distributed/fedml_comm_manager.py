"""FedMLCommManager — backend-agnostic messaging hub.

Parity: ``core/distributed/fedml_comm_manager.py:11-209``: a registry of
``msg_type → handler`` callbacks observing a pluggable transport, with
``_init_manager`` instantiating the backend by name.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Optional

from fedml_tpu import constants
from fedml_tpu.core.distributed.communication.base_com_manager import (
    BaseCommunicationManager,
    Observer,
)
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)


class FedMLCommManager(Observer):
    def __init__(
        self,
        args: Any,
        comm: Any = None,
        rank: int = 0,
        size: int = 0,
        backend: str = constants.COMM_BACKEND_LOCAL,
    ):
        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.com_manager: Optional[BaseCommunicationManager] = comm
        self.message_handler_dict: Dict[str, Callable] = {}
        self._receive_thread: Optional[threading.Thread] = None
        self.handler_error: Optional[BaseException] = None
        # transport resilience: unique msg ids + receiver-side dedup make
        # sends idempotent; a bounded backoff retry absorbs transient
        # transport failures (broker reconnecting, peer restarting);
        # liveness notes every sender for the dropout/rejoin layer; the
        # chaos injector (None in production) sits at this same boundary
        from itertools import count
        from uuid import uuid4

        from fedml_tpu.resilience import (
            MessageDeduper,
            PeerLiveness,
            ResilienceConfig,
            chaos_from_args,
            transient_exceptions,
        )

        self.resilience = ResilienceConfig(args)
        self._mgr_uid = uuid4().hex[:8]
        # precomputed prefix: the msg-id stamp is on the hot send path;
        # itertools.count is atomic under the GIL — the deadline timer,
        # heartbeat thread, and receive thread all send concurrently, and
        # a shared non-atomic seq would mint duplicate ids (the receiver
        # would then drop a legitimate message as a duplicate)
        self._msg_id_prefix = f"{self._mgr_uid}:{self.rank}:"
        self._send_seq = count(1)
        self._deduper = MessageDeduper()
        self.liveness = PeerLiveness(
            silent_after_s=max(30.0,
                               3 * self.resilience.heartbeat_interval_s))
        self._send_retry = self.resilience.retry_policy(key=f"rank{rank}")
        self._retry_on = transient_exceptions()
        # live telemetry plane: when a MetricStreamer is attached, every
        # outgoing message can carry one prepared metric frame (rate-
        # limited by the streamer, so chatty transports don't amplify);
        # inbound frames route to this process's LivePlane if one is
        # bound. Both default off — the production hot path is two
        # None-checks.
        self.live_streamer = None
        # causal tracing: a SpanStreamer piggybacks bounded span-batch
        # frames the same way (rate-limited, drop/duplicate-tolerant);
        # inbound trace frames route to the LivePlane's TraceCollector
        self.trace_streamer = None
        # the authoritative round for windowed chaos faults: the client
        # FSM's own round_idx, or the server's args.round_idx
        self._chaos = chaos_from_args(
            args, self.rank,
            round_provider=lambda: getattr(
                self, "round_idx", getattr(self.args, "round_idx", None)))
        if self.com_manager is None:
            self._init_manager()
        self.com_manager.add_observer(self)

    # -- public surface (reference names) ---------------------------------
    def register_comm_manager(self, comm_manager: BaseCommunicationManager) -> None:
        self.com_manager = comm_manager

    MSG_TYPE_CONNECTION_IS_READY = "MSG_TYPE_CONNECTION_IS_READY"

    def run(self) -> None:
        self.register_message_receive_handlers()
        logger.debug("rank %d running (%s backend)", self.rank, self.backend)
        self._notify_connection_ready()
        self.com_manager.handle_receive_message()

    def run_async(self) -> threading.Thread:
        """Run the receive loop on a daemon thread (in-proc federation)."""
        self.register_message_receive_handlers()
        t = threading.Thread(target=self.com_manager.handle_receive_message, daemon=True)
        t.start()
        self._receive_thread = t
        return t

    def _notify_connection_ready(self) -> None:
        """Self-deliver CONNECTION_IS_READY on distributed backends.

        Parity: the reference's MQTT manager dispatches
        MSG_TYPE_CONNECTION_IS_READY from its on_connect callback, which
        is what kicks each rank's FSM in a standalone multi-process run.
        The in-proc LOCAL path keeps its explicit orchestration (run
        helpers kick after ALL managers are up, which the deterministic
        tests rely on)."""
        if str(self.backend).upper() in (
            constants.COMM_BACKEND_BROKER,
            constants.COMM_BACKEND_GRPC,
            constants.COMM_BACKEND_TRPC,
        ):
            self.receive_message(
                self.MSG_TYPE_CONNECTION_IS_READY,
                Message(self.MSG_TYPE_CONNECTION_IS_READY, self.rank, self.rank),
            )

    def get_sender_id(self) -> int:
        return self.rank

    def receive_message(self, msg_type: str, msg_params: Message) -> None:
        from fedml_tpu import telemetry
        from fedml_tpu.telemetry import flight_recorder

        # chaos inbound filter: a partitioned/killed peer's in-flight
        # messages must not leak through the cut (None in production)
        if self._chaos is not None and not self._chaos.on_deliver(msg_params):
            return
        # receiver-side dedup: transport resends (reconnect replays,
        # sender retries after an uncertain failure) carry the SAME
        # msg_id and must be applied exactly once
        msg_id = msg_params.get(Message.MSG_ARG_KEY_MSG_ID)
        if msg_id is not None and self._deduper.seen(msg_id):
            telemetry.get_registry().counter(
                "resilience/duplicates_dropped").inc()
            flight_recorder.record("duplicate_dropped", rank=self.rank,
                                   msg_type=str(msg_type), msg_id=msg_id)
            logger.debug("rank %d: duplicate %s dropped (%s)",
                         self.rank, msg_type, msg_id)
            return
        self.liveness.note(msg_params.get_sender_id())
        # live telemetry: a piggybacked metric frame merges into this
        # process's collector (if one is bound) regardless of msg_type —
        # duplicates of the SAME frame on a retried/duplicated message
        # are absorbed by the collector's seq gate
        frame = msg_params.get(Message.MSG_ARG_KEY_TELEMETRY)
        if frame is not None:
            try:
                from fedml_tpu.telemetry.live import ingest_frame

                ingest_frame(frame)
            except Exception:  # observability must not break the round
                logger.exception("telemetry frame ingest failed")
        tframe = msg_params.get(Message.MSG_ARG_KEY_TRACE)
        if tframe is not None:
            try:
                from fedml_tpu.telemetry.live import ingest_trace_frame

                ingest_trace_frame(tframe)
            except Exception:  # observability must not break the round
                logger.exception("trace frame ingest failed")
        handler = self.message_handler_dict.get(str(msg_type))
        if handler is None:
            logger.warning("rank %d: no handler for %s", self.rank, msg_type)
            return
        # re-activate the sender's trace context (injected by send_message)
        # so this rank's handler spans stitch into the sender's timeline

        rnd = msg_params.get("round")
        flight_recorder.record(
            "comm_recv", msg_type=str(msg_type), rank=self.rank,
            sender=msg_params.get_sender_id(),
            **({"round": rnd} if rnd is not None else {}))
        # receive-side half of the clock-alignment pair: a point event on
        # THIS node's wall clock for the sender's msg_id (the send-side
        # twin was stamped by the peer's send_message)
        if msg_id is not None:
            telemetry.get_tracer().event(
                "comm/recv", msg_id=msg_id,
                peer=msg_params.get_sender_id(),
                msg_type=str(msg_type),
                **({"round": rnd} if rnd is not None else {}))
        ctx = telemetry.extract_context(msg_params.get_params())
        token = telemetry.activate_context(ctx)
        try:
            if ctx is not None:
                with telemetry.get_tracer().span(
                    "comm/dispatch", msg_type=str(msg_type), rank=self.rank,
                    sender=msg_params.get_sender_id(),
                    **({"msg_id": msg_id} if msg_id is not None else {}),
                    **({"round": rnd} if rnd is not None else {}),
                ):
                    handler(msg_params)
            else:
                handler(msg_params)
        except BaseException as e:
            # a raising handler must not silently kill the receive thread
            # and hang the federation — record, log, and stop this rank's
            # loop so joins return promptly and callers can surface it
            self.handler_error = e
            logger.exception(
                "rank %d: handler for %s raised; stopping receive loop",
                self.rank,
                msg_type,
            )
            # the exception is caught here (never reaches threading's
            # excepthook), so this IS the unhandled-crash moment for a
            # federation rank — land the black box now
            flight_recorder.record("handler_error", msg_type=str(msg_type),
                                   rank=self.rank, error=repr(e))
            flight_recorder.get_flight_recorder().dump(
                reason="handler_error", exc=e)
            self.com_manager.stop_receive_message()
        finally:
            from fedml_tpu import telemetry

            telemetry.deactivate_context(token)

    def send_message(self, message: Message) -> None:
        from fedml_tpu import telemetry
        from fedml_tpu.telemetry import flight_recorder

        # carry the current trace context as a message header so the
        # receiving rank's spans join this round's timeline
        telemetry.inject_context(message.get_params())
        # idempotent-send header: stamped once per logical message (a
        # retried send reuses it, so the receiver's deduper catches the
        # case where the first attempt DID land). Stamped before the
        # send event below so the event can carry the id the receiver's
        # comm/recv twin will match on — chaos duplicate copies share the
        # id on purpose, which keeps the pairing unambiguous
        if message.get(Message.MSG_ARG_KEY_MSG_ID) is None:
            message.add_params(Message.MSG_ARG_KEY_MSG_ID,
                               self._msg_id_prefix + str(next(self._send_seq)))
        rnd = message.get("round")
        # send-side half of the clock-alignment pair; recorded under the
        # current span so the critical-path walk can cross the wire back
        # to the span that caused this message
        telemetry.get_tracer().event(
            "comm/send", msg_id=message.get(Message.MSG_ARG_KEY_MSG_ID),
            peer=message.get_receiver_id(), msg_type=message.get_type(),
            **({"round": rnd} if rnd is not None else {}))
        flight_recorder.record(
            "comm_send", msg_type=message.get_type(), rank=self.rank,
            receiver=message.get_receiver_id(),
            **({"round": rnd} if rnd is not None else {}))
        reg = telemetry.get_registry()
        reg.counter("comm/messages_sent",
                    labels={"backend": str(self.backend).lower()}).inc()
        payload = message.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
        if payload is not None:
            # uncompressed payload size — the numerator of the
            # compression ratio the telemetry report computes against
            # the transport-recorded comm/wire_bytes_* counters
            from fedml_tpu.compression import CompressedTree
            from fedml_tpu.utils.serialization import tree_nbytes

            try:
                raw = (payload.raw_nbytes
                       if isinstance(payload, CompressedTree)
                       else tree_nbytes(payload))
                reg.counter("comm/raw_bytes").inc(raw)
            except TypeError:
                pass  # not a tree of arrays
        # live telemetry: pop a prepared frame onto this message (rate-
        # limited inside the streamer; the frame is cumulative, so the
        # collector absorbs duplicate deliveries). BEFORE the chaos seam
        # on purpose — injected drop/duplicate exercises frame recovery.
        if (self.live_streamer is not None
                and message.get(Message.MSG_ARG_KEY_TELEMETRY) is None):
            try:
                frame = self.live_streamer.pop_frame()
                if frame is not None:
                    message.add_params(Message.MSG_ARG_KEY_TELEMETRY, frame)
                    reg.counter("live/frames_piggybacked").inc()
            except Exception:  # observability must not break the send
                logger.exception("telemetry frame piggyback failed")
        # causal tracing: one prepared span-batch frame per message, same
        # contract as the metric frame above (rate-limited, BEFORE the
        # chaos seam — the collector's index merge absorbs drop/duplicate)
        if (self.trace_streamer is not None
                and message.get(Message.MSG_ARG_KEY_TRACE) is None):
            try:
                tframe = self.trace_streamer.pop_frame()
                if tframe is not None:
                    message.add_params(Message.MSG_ARG_KEY_TRACE, tframe)
            except Exception:  # observability must not break the send
                logger.exception("trace frame piggyback failed")
        # chaos: update-corruption windows mutate the model payload at
        # exactly this seam — after encode, before the wire (None-check
        # in production; the injector no-ops without corrupt windows)
        if self._chaos is not None:
            self._chaos.corrupt_payload(message)
        copies, delay_s = (1, 0.0) if self._chaos is None else (
            self._chaos.on_send(message))
        if delay_s > 0:
            import time as _time

            _time.sleep(delay_s)
        for _ in range(copies):
            self._send_with_retry(message)

    def _send_with_retry(self, message: Message) -> None:
        """One transport send under the jittered-backoff retry policy."""
        from fedml_tpu import telemetry

        reg = telemetry.get_registry()

        def on_retry(attempt: int, exc: BaseException) -> None:
            reg.counter("resilience/send_retries").inc()
            telemetry.flight_recorder.record(
                "send_retry", rank=self.rank, attempt=attempt,
                msg_type=message.get_type(), error=repr(exc))

        try:
            self._send_retry.call(
                lambda: self.com_manager.send_message(message),
                retry_on=self._retry_on, on_retry=on_retry)
        except self._retry_on:
            reg.counter("resilience/send_failures").inc()
            raise

    def register_message_receive_handler(self, msg_type: str, handler: Callable) -> None:
        self.message_handler_dict[str(msg_type)] = handler

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM handlers here."""

    def finish(self) -> None:
        logger.debug("rank %d finishing", self.rank)
        self.com_manager.stop_receive_message()

    # -- backend factory (parity: fedml_comm_manager.py:131) --------------
    def _init_manager(self) -> None:
        backend = str(self.backend).upper()
        run_id = str(getattr(self.args, "run_id", "0"))
        if backend == constants.COMM_BACKEND_LOCAL:
            from fedml_tpu.core.distributed.communication.local_comm import (
                LocalCommManager,
            )

            self.com_manager = LocalCommManager(run_id, self.rank)
        elif backend == constants.COMM_BACKEND_GRPC:
            from fedml_tpu.core.distributed.communication.grpc_comm import (
                GRPCCommManager,
            )

            ip_config = getattr(self.args, "grpc_ipconfig", None)
            self.com_manager = GRPCCommManager(
                ip_config=ip_config,
                client_id=self.rank,
                client_num=self.size,
                base_port=int(getattr(self.args, "grpc_base_port", 8890)),
            )
        elif backend == constants.COMM_BACKEND_TRPC:
            from fedml_tpu.core.distributed.communication.trpc_comm import (
                TRPCCommManager,
            )

            self.com_manager = TRPCCommManager(
                client_id=self.rank,
                client_num=self.size,
                master_addr=str(getattr(self.args, "trpc_master_addr",
                                        "127.0.0.1")),
                master_port=int(getattr(self.args, "trpc_master_port",
                                        29500)),
            )
        elif backend == constants.COMM_BACKEND_XLA_ICI:
            from fedml_tpu.core.distributed.communication.xla_ici_comm import (
                XlaIciCommManager,
            )

            self.com_manager = XlaIciCommManager(run_id, self.rank, self.size)
        elif backend in (constants.COMM_BACKEND_BROKER,
                         constants.COMM_BACKEND_MQTT_S3):
            # one manager, two protocols: BROKER = in-tree TCP pub/sub (or
            # broker_protocol: mqtt); MQTT_S3 = the reference's default
            # backend, forcing the paho-mqtt protocol (mqtt_compat raises
            # with instructions when paho is absent)
            from fedml_tpu.core.distributed.communication.broker_comm import (
                BrokerCommManager,
            )
            from fedml_tpu.core.distributed.communication.object_store import (
                create_object_store,
            )

            if backend == constants.COMM_BACKEND_MQTT_S3:
                protocol = "mqtt"
                host = getattr(self.args, "mqtt_host",
                               getattr(self.args, "broker_host", "127.0.0.1"))
                port = getattr(self.args, "mqtt_port",
                               getattr(self.args, "broker_port", 1883))
            else:
                protocol = str(getattr(self.args, "broker_protocol", "tcp"))
                host = getattr(self.args, "broker_host", "127.0.0.1")
                port = getattr(self.args, "broker_port", 1883)
            self.com_manager = BrokerCommManager(
                run_id,
                self.rank,
                host=str(host),
                port=int(port),
                object_store=create_object_store(self.args),
                offload_bytes=int(
                    getattr(self.args, "payload_offload_bytes", 64 * 1024)
                ),
                protocol=protocol,
            )
        else:
            raise ValueError(f"unknown comm backend {self.backend!r}")
