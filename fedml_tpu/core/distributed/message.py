"""Typed message — the unit of federation-plane communication.

Parity with the reference's ``core/distributed/communication/message.py:5-83``
(type/sender/receiver + params payload), with one TPU-era difference: model
payloads are JAX pytrees and stay on device until a transport actually needs
bytes. Serialization to a flat numpy archive happens lazily at the transport
boundary (see :mod:`fedml_tpu.utils.serialization`).
"""
from __future__ import annotations

import json
from typing import Any, Dict


class Message:
    MSG_ARG_KEY_OPERATION = "operation"
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_OPERATION_SEND = "send"
    MSG_OPERATION_RECEIVE = "receive"
    MSG_OPERATION_BROADCAST = "broadcast"
    MSG_OPERATION_REDUCE = "reduce"

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
    MSG_ARG_KEY_MODEL_PARAMS_KEY = "model_params_key"
    # negotiation header: the codec tag the receiver should use for its
    # own model uploads (see fedml_tpu/compression); payloads are
    # additionally self-describing via the wire format's __codec__ node
    MSG_ARG_KEY_COMPRESSION = "compression"
    # negotiation header: the robust-aggregation spec every aggregation
    # point of this round applies (trimmed_mean@0.1 / median — see
    # fedml_tpu/integrity/robust_agg.py); informational for flat
    # clients, authoritative for interior tiers of an aggregation tree
    MSG_ARG_KEY_AGG_ROBUST = "agg_robust"
    # piggybacked heartbeat/health fields (JSON-safe scalars only: train
    # wall, train loss, live memory bytes) — rides existing status and
    # model-upload messages, never its own round-trip
    MSG_ARG_KEY_HEALTH = "health"
    # idempotent-send header: unique per logical message, stamped once by
    # FedMLCommManager.send_message and preserved across transport-level
    # resends so the receiver's deduper can drop duplicate deliveries
    MSG_ARG_KEY_MSG_ID = "msg_id"
    # rejoin marker on a server->client resync after an eviction: the
    # client must reset per-identity compression state (EF residuals)
    MSG_ARG_KEY_REJOIN = "rejoin"
    # live telemetry: one seq-numbered metric frame (JSON-safe dict, see
    # telemetry/live/frames.py) piggybacked on an existing message — the
    # collector side merges it; like health, never its own round-trip
    MSG_ARG_KEY_TELEMETRY = "telemetry_frame"
    # causal tracing: one seq-numbered span-batch frame (JSON-safe dict,
    # see telemetry/tracing/stream.py) piggybacked the same way — the
    # TraceCollector merges it idempotently by absolute record index
    MSG_ARG_KEY_TRACE = "trace_frame"

    def __init__(self, type_: str = "default", sender_id: int = 0, receiver_id: int = 0):
        self.type = str(type_)
        self.sender_id = int(sender_id)
        self.receiver_id = int(receiver_id)
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: self.type,
            Message.MSG_ARG_KEY_SENDER: self.sender_id,
            Message.MSG_ARG_KEY_RECEIVER: self.receiver_id,
        }

    # -- accessors (reference-compatible names) ---------------------------
    def get_sender_id(self) -> int:
        return self.sender_id

    def get_receiver_id(self) -> int:
        return self.receiver_id

    def get_type(self) -> str:
        return self.type

    def add_params(self, key: str, value: Any) -> "Message":
        self.msg_params[key] = value
        return self

    add = add_params

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def get_content(self, key: str) -> Any:
        return self.msg_params[key]

    # -- (de)serialization of the *control* part --------------------------
    # Array payloads are handled by the transport; to_json only carries
    # JSON-safe fields and records which keys were arrays.
    def to_json_control(self) -> str:
        safe = {
            k: v
            for k, v in self.msg_params.items()
            if isinstance(v, (str, int, float, bool, type(None), list, dict))
        }
        return json.dumps(safe)

    @classmethod
    def construct_from_params(cls, params: Dict[str, Any]) -> "Message":
        msg = cls(
            params.get(cls.MSG_ARG_KEY_TYPE, "default"),
            params.get(cls.MSG_ARG_KEY_SENDER, 0),
            params.get(cls.MSG_ARG_KEY_RECEIVER, 0),
        )
        msg.msg_params.update(params)
        return msg

    def __repr__(self) -> str:  # pragma: no cover
        keys = [k for k in self.msg_params if k not in (
            self.MSG_ARG_KEY_TYPE, self.MSG_ARG_KEY_SENDER, self.MSG_ARG_KEY_RECEIVER)]
        return (
            f"Message(type={self.type}, {self.sender_id}->{self.receiver_id}, "
            f"keys={keys})"
        )
