"""FedMLAlgorithmFlow — declarative multi-step FL over the comm layer.

Parity: reference ``core/distributed/flow/fedml_flow.py:20`` — register
named flow steps bound to a role (SERVER / CLIENT), sequence them (with a
loop section for the round body), and run them as a message-driven
federation: server steps run on the server; for a client step the server
broadcasts the current payload, every client executes the step's function
and sends its result back, and the server collects all results before the
next step. The transport is the standard ``FedMLCommManager`` stack, so a
flow runs unchanged over LOCAL / BROKER / gRPC.

    flow = FedMLAlgorithmFlow(args, n_clients=4)
    flow.add_flow("init", FLOW_SERVER, init_fn)        # (ctx, inputs)->out
    flow.add_flow("train", FLOW_CLIENT, train_fn)      # (ctx, payload)->out
    flow.add_flow("agg", FLOW_SERVER, agg_fn)          # (ctx, [outs])->out
    flow.set_loop(["train", "agg"], rounds=10)
    result = flow.run_inproc()
"""
from __future__ import annotations

import copy
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from fedml_tpu import constants
from fedml_tpu.core.distributed.communication.local_comm import LocalBroker
from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager
from fedml_tpu.core.distributed.message import Message

logger = logging.getLogger(__name__)

FLOW_SERVER = "server"
FLOW_CLIENT = "client"

MSG_FLOW_EXEC = "MSG_FLOW_EXEC"
MSG_FLOW_RESULT = "MSG_FLOW_RESULT"
MSG_FLOW_FINISH = "MSG_FLOW_FINISH"
MSG_FLOW_READY = "MSG_TYPE_CONNECTION_IS_READY"


@dataclass
class FlowStep:
    name: str
    role: str
    fn: Callable


@dataclass
class FlowContext:
    args: Any
    rank: int
    round_idx: int


class _FlowClientManager(FedMLCommManager):
    def __init__(self, args, steps: Dict[str, FlowStep], rank, size,
                 backend=constants.COMM_BACKEND_LOCAL):
        super().__init__(args, None, rank, size, backend)
        self.steps = steps

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_FLOW_READY, lambda m: None)
        self.register_message_receive_handler(MSG_FLOW_EXEC, self.handle_exec)
        self.register_message_receive_handler(
            MSG_FLOW_FINISH, lambda m: self.finish())

    def handle_exec(self, msg: Message) -> None:
        step = self.steps[msg.get("step")]
        ctx = FlowContext(self.args, self.rank, int(msg.get("round", 0)))
        out = step.fn(ctx, msg.get("payload"))
        reply = Message(MSG_FLOW_RESULT, self.get_sender_id(), 0)
        reply.add_params("step", step.name)
        reply.add_params("round", msg.get("round", 0))
        reply.add_params("payload", out)
        self.send_message(reply)


class _FlowServerManager(FedMLCommManager):
    def __init__(self, args, schedule: List[FlowStep], n_clients, rounds,
                 loop_names: List[str],
                 backend=constants.COMM_BACKEND_LOCAL):
        super().__init__(args, None, 0, n_clients + 1, backend)
        self.schedule = schedule
        self.n_clients = n_clients
        self.rounds = rounds
        self.loop_names = set(loop_names)
        self.result: Any = None
        self._step_idx = 0
        self._round = 0
        self._payload: Any = None
        self._collected: Dict[int, Any] = {}
        self._started = False

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_FLOW_READY, self.handle_ready)
        self.register_message_receive_handler(MSG_FLOW_RESULT, self.handle_result)

    def handle_ready(self, msg: Message) -> None:
        if not self._started:
            self._started = True
            self._advance()

    def _advance(self) -> None:
        """Run server steps until a client step needs the federation."""
        while self._step_idx < len(self.schedule):
            step = self.schedule[self._step_idx]
            ctx = FlowContext(self.args, 0, self._round)
            if step.role == FLOW_SERVER:
                self._payload = step.fn(ctx, self._payload)
                self._step_idx += 1
                continue
            # client step: broadcast, wait for all results
            self._collected = {}
            for cid in range(1, self.n_clients + 1):
                m = Message(MSG_FLOW_EXEC, 0, cid)
                m.add_params("step", step.name)
                m.add_params("round", self._round)
                m.add_params("payload", self._payload)
                self.send_message(m)
            return  # resume in handle_result
        self._finish_or_loop()

    def handle_result(self, msg: Message) -> None:
        if int(msg.get("round", 0)) != self._round:
            return
        self._collected[msg.get_sender_id()] = msg.get("payload")
        if len(self._collected) < self.n_clients:
            return
        self._payload = [self._collected[c] for c in sorted(self._collected)]
        self._step_idx += 1
        self._advance()

    def _finish_or_loop(self) -> None:
        self._round += 1
        if self._round < self.rounds and self.loop_names:
            self._step_idx = next(
                i for i, s in enumerate(self.schedule)
                if s.name in self.loop_names
            )
            self._advance()
            return
        self.result = self._payload
        for cid in range(1, self.n_clients + 1):
            self.send_message(Message(MSG_FLOW_FINISH, 0, cid))
        self.finish()


class FedMLAlgorithmFlow:
    def __init__(self, args: Any, n_clients: Optional[int] = None):
        self.args = args
        self.n_clients = int(
            n_clients
            if n_clients is not None
            else getattr(args, "client_num_per_round", 2)
        )
        self.steps: List[FlowStep] = []
        self.loop_names: List[str] = []
        self.rounds = 1

    def add_flow(self, name: str, role: str, fn: Callable) -> "FedMLAlgorithmFlow":
        self.steps.append(FlowStep(name, role, fn))
        return self

    def set_loop(self, names: List[str], rounds: int) -> "FedMLAlgorithmFlow":
        """The named contiguous tail section repeats ``rounds`` times total."""
        self.loop_names = list(names)
        self.rounds = int(rounds)
        return self

    def build(self) -> "FedMLAlgorithmFlow":  # reference API parity
        return self

    def run_inproc(self, timeout: float = 300.0) -> Any:
        from fedml_tpu.cross_silo.run_inproc import run_managers_to_completion

        run_id = str(getattr(self.args, "run_id", "flow"))
        LocalBroker.destroy(run_id)
        step_map = {s.name: s for s in self.steps}
        server = _FlowServerManager(
            self.args, self.steps, self.n_clients, self.rounds, self.loop_names
        )
        clients = []
        for rank in range(1, self.n_clients + 1):
            cargs = copy.copy(self.args)
            cargs.rank = rank
            clients.append(_FlowClientManager(
                cargs, step_map, rank, self.n_clients + 1))
        return run_managers_to_completion(
            [server] + clients, run_id, MSG_FLOW_READY, timeout
        )
