"""``fedml_tpu.api`` — programmatic control surface.

Parity target: ``python/fedml/api/__init__.py`` (``launch_job`` :42,
``run_stop`` :121, ``run_list``/``run_status``/``run_logs`` :125-135,
``model_deploy`` :266, storage upload/download :181-204). The reference
routes everything through the hosted Nexus backend; here the same verbs
drive the local/cluster schedulers, the deploy plane, and the object
store directly — no login, no REST hop.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

DEFAULT_WORKDIR = ".fedml_runs"


# -- jobs (local agent) ------------------------------------------------------

def launch_job(yaml_file: str, workdir: str = DEFAULT_WORKDIR) -> str:
    """Run a job yaml on the local agent; returns the run id."""
    from fedml_tpu.scheduler.launch import launch_job as _launch

    return _launch(yaml_file, workdir=workdir)


def run_stop(run_id: str, workdir: str = DEFAULT_WORKDIR) -> bool:
    from fedml_tpu.scheduler.launch import run_stop as _stop

    return _stop(run_id, workdir=workdir)


def run_status(run_id: str, workdir: str = DEFAULT_WORKDIR) -> Optional[str]:
    from fedml_tpu.scheduler.launch import run_status as _status

    return _status(run_id, workdir=workdir)


def run_logs(run_id: str, tail: Optional[int] = None,
             workdir: str = DEFAULT_WORKDIR) -> str:
    from fedml_tpu.scheduler.launch import run_logs as _logs

    return _logs(run_id, tail=tail, workdir=workdir)


def run_list(workdir: str = DEFAULT_WORKDIR) -> List[Dict]:
    from fedml_tpu.scheduler.launch import list_jobs

    return list_jobs(workdir=workdir)


# -- cluster jobs (master agent) ---------------------------------------------

def launch_job_on_cluster(yaml_file: str, broker: str, n_ranks: int = 1,
                          nodes: Optional[List[str]] = None,
                          wait: bool = True, timeout: float = 3600.0) -> Dict:
    """Submit a job yaml across node agents; returns the job view."""
    from fedml_tpu.scheduler.job_yaml import JobSpec
    from fedml_tpu.scheduler.master_agent import MasterAgent

    host, _, port = broker.rpartition(":")
    master = MasterAgent(host, int(port)).start()
    try:
        master.wait_for_nodes(len(nodes) if nodes else 1,
                              timeout=min(30.0, timeout))
        job_id = master.submit_job(JobSpec.load(yaml_file), n_ranks=n_ranks,
                                   nodes=nodes)
        if not wait:
            return {"job_id": job_id, "status": "RUNNING"}
        try:
            return master.wait_job(job_id, timeout=timeout)
        except TimeoutError:
            master.stop_job(job_id)
            raise
    finally:
        master.shutdown()


# -- model cards + deployment ------------------------------------------------

def model_create(name: str, workspace: str,
                 registry: Optional[str] = None) -> Dict:
    from fedml_tpu.deploy.model_cards import FedMLModelCards

    return FedMLModelCards(registry).create_model(name, workspace)


def model_list(registry: Optional[str] = None) -> List[Dict]:
    from fedml_tpu.deploy.model_cards import FedMLModelCards

    return FedMLModelCards(registry).list_models()


def model_delete(name: str, version: Optional[int] = None,
                 registry: Optional[str] = None) -> bool:
    from fedml_tpu.deploy.model_cards import FedMLModelCards

    return FedMLModelCards(registry).delete_model(name, version)


def model_deploy(name: str, broker: str, n_replicas: int = 1,
                 registry: Optional[str] = None,
                 store_dir: Optional[str] = None,
                 cache_path: str = ".fedml_deploy/endpoints.json",
                 timeout: float = 180.0, with_token: bool = False) -> Dict:
    """Deploy a model card to live deploy workers (reference
    ``api.model_deploy`` :266 / ``serve_model_on_premise``)."""
    from fedml_tpu.core.distributed.communication.object_store import (
        LocalDirObjectStore,
    )
    from fedml_tpu.deploy import DeployMaster, EndpointCache
    from fedml_tpu.deploy.model_cards import FedMLModelCards

    host, _, port = broker.rpartition(":")
    master = DeployMaster(
        host, int(port), LocalDirObjectStore(store_dir),
        EndpointCache(cache_path), cards=FedMLModelCards(registry),
    ).start()
    try:
        master.wait_for_workers(n_replicas, timeout=min(30.0, timeout))
        return master.deploy(name, n_replicas=n_replicas, timeout=timeout,
                             with_token=with_token)
    finally:
        master.shutdown()


# -- storage (artifact catalog over the object-store seam) -------------------
# Reference surface: api/__init__.py:181-204 upload/download/
# list_storage_obects/get_storage_metadata/delete over the hosted R2
# service; here the backend is selectable (local CAS default, s3/web3/
# theta) via fedml_tpu.storage.StorageManager.

def _storage_manager(service: str, store_dir: Optional[str], backend_kw):
    from fedml_tpu.storage import StorageManager

    kw = dict(backend_kw)
    index_dir = kw.pop("index_dir", None)
    if store_dir is not None:  # one-dir convenience: bytes + index together
        if service == "local":
            kw.setdefault("root", os.path.join(store_dir, "cas"))
        index_dir = index_dir or os.path.join(store_dir, "index")
    return StorageManager(service, index_dir=index_dir, **kw)


def upload(data_path: str, name: Optional[str] = None,
           description: str = "", metadata: Optional[Dict] = None,
           service: str = "local", store_dir: Optional[str] = None,
           **backend_kw):
    """Store a file or directory as a named artifact; returns its
    :class:`~fedml_tpu.storage.StorageMetadata`."""
    return _storage_manager(service, store_dir, backend_kw).upload(
        data_path, name=name, description=description, metadata=metadata)


def download(name: str, dest_path: Optional[str] = None,
             service: str = "local", store_dir: Optional[str] = None,
             **backend_kw) -> str:
    """Fetch artifact ``name``; returns the written path."""
    return _storage_manager(service, store_dir, backend_kw).download(
        name, dest=dest_path)


def delete(name: str, service: str = "local",
           store_dir: Optional[str] = None, **backend_kw) -> bool:
    return _storage_manager(service, store_dir, backend_kw).delete(name)


def list_storage_objects(service: str = "local",
                         store_dir: Optional[str] = None, **backend_kw):
    return _storage_manager(service, store_dir, backend_kw).list()


def get_storage_metadata(name: str, service: str = "local",
                         store_dir: Optional[str] = None, **backend_kw):
    return _storage_manager(service, store_dir, backend_kw).get_metadata(name)


def get_storage_user_defined_metadata(
        name: str, service: str = "local",
        store_dir: Optional[str] = None, **backend_kw) -> Optional[Dict]:
    return get_storage_metadata(
        name, service=service, store_dir=store_dir,
        **backend_kw).user_metadata


__all__ = [
    "delete",
    "download",
    "get_storage_metadata",
    "get_storage_user_defined_metadata",
    "launch_job",
    "launch_job_on_cluster",
    "list_storage_objects",
    "model_create",
    "model_delete",
    "model_deploy",
    "model_list",
    "run_list",
    "run_logs",
    "run_status",
    "run_stop",
    "upload",
]
