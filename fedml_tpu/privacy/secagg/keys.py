"""X25519 key agreement for pair-seed derivation — dependency-gated.

``core/mpc/secagg`` uses the ``cryptography`` package for X25519; this
module prefers that implementation when it is importable and otherwise
falls back to a pure-Python RFC 7748 Montgomery ladder (exact same
curve, clamping and output encoding, so mixed deployments agree on the
shared secret byte-for-byte). The fallback is ~1ms per exchange — key
agreement runs once per (client, peer) pair per process, never per
round, so this is nowhere near a hot path.

Security note: the pure-Python ladder is not constant-time. The secrets
it protects are per-run mask seeds for an honest-but-curious-server
model (docs/privacy.md), not long-lived identity keys; install
``cryptography`` to get the constant-time implementation.
"""
from __future__ import annotations

import hashlib
import os
from typing import Tuple

__all__ = ["kx_agree", "kx_keygen"]

_P = 2 ** 255 - 19
_A24 = 121665
_BASE_U = 9


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def _x25519(k_bytes: bytes, u_bytes: bytes) -> bytes:
    """RFC 7748 §5 scalar multiplication on curve25519."""
    k = _decode_scalar(k_bytes)
    x1 = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


def _have_cryptography() -> bool:
    try:
        import cryptography.hazmat.primitives.asymmetric.x25519  # noqa: F401

        return True
    except ImportError:
        return False


def kx_keygen() -> Tuple[bytes, bytes]:
    """(private scalar bytes, 32-byte public key) from OS entropy."""
    if _have_cryptography():
        from fedml_tpu.core.mpc.secagg import kx_keygen as _kg

        sk_obj, pk = _kg()
        return sk_obj.private_bytes_raw(), pk
    sk = os.urandom(32)
    return sk, _x25519(sk, _BASE_U.to_bytes(32, "little"))


def kx_agree(sk: bytes, their_pk: bytes) -> int:
    """Shared secret → 128-bit PRF seed (SHA-256 of the raw exchange —
    identical derivation to ``core/mpc/secagg.kx_agree``)."""
    if _have_cryptography():
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey,
        )

        from fedml_tpu.core.mpc.secagg import kx_agree as _ka

        return _ka(X25519PrivateKey.from_private_bytes(bytes(sk)),
                   bytes(their_pk))
    secret = _x25519(bytes(sk), bytes(their_pk))
    return int.from_bytes(hashlib.sha256(secret).digest()[:16], "little")
