"""Per-edge-cohort SecAgg for the in-process aggregation tree.

In a hierarchical federation the EDGE tier is the curious party: it
buffers its cohort's uploads, so without masking it sees every leaf
client's individual delta. :class:`SecAggLeafCohort` drops into the
:class:`~fedml_tpu.hierarchy.edge.LeafCohort` slot of a
:class:`~fedml_tpu.hierarchy.runner.TreeRunner` and masks INSIDE the
cohort: each virtual client quantizes with the cohort-shared scale and
adds its pairwise masks in the same chunk program, the edge sums masked
words mod ``2^k``, and only the cohort SUM is ever unmasked — the edge
re-encodes that mean for its uplink, so no tier (edge or above) ever
holds an individual leaf delta. Chaos kills are recovered exactly like
the cross-silo path: the surviving pairs' seeds reproduce the evicted
clients' half-cancelled masks, subtracted from the cohort sum.

Pair seeds are derived deterministically from the tree seed (both
"endpoints" of a virtual pair live in this process — there is nothing
to key-exchange), so two same-seed runs are digest-identical; the
cross-silo protocol (real key agreement, reveal messages) lives in
:mod:`fedml_tpu.privacy.secagg.protocol`.
"""
from __future__ import annotations

import functools
import hashlib
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression.codecs import derive_key_data_batch
from fedml_tpu.hierarchy.edge import LeafCohort
from fedml_tpu.privacy.secagg import masking

__all__ = ["SecAggLeafCohort"]

_UINT = {8: jnp.uint8, 16: jnp.uint16}


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _secagg_leaf_chunk_program(meta, delta_fn, clip: float, bound: int,
                               mod_bits: int, key_data, alive, masks):
    """generate → clip → shared-scale quant → +mask → masked SUM, one
    program. ``alive`` zeroes dead/padded slots (their mask half never
    "arrived"); per-client deltas and quantized words are XLA
    temporaries only — the program's output is the masked cohort sum."""
    scale = jnp.float32(clip / float(bound))
    wrap = (1 << mod_bits) - 1
    udt = _UINT[mod_bits]

    def per_client(kd, m_leaves):
        key = jax.random.wrap_key_data(kd)
        leaves = tuple(delta_fn(jax.random.fold_in(key, 1)))
        enc_key = jax.random.fold_in(key, 2)
        ys = []
        for i, (x, m) in enumerate(zip(leaves, m_leaves)):
            xc = jnp.clip(x.astype(jnp.float32), -clip, clip)
            u = jax.random.uniform(jax.random.fold_in(enc_key, i), xc.shape)
            q = jnp.clip(jnp.floor(xc / scale + u), -bound, bound)
            ys.append(((q.astype(jnp.int32) + m.astype(jnp.int32)) & wrap)
                      .astype(udt))
        return tuple(ys)

    ys = jax.vmap(per_client)(key_data, masks)
    a = alive.astype(udt)
    return tuple(
        jnp.sum(y * a.reshape((-1,) + (1,) * (y.ndim - 1)), axis=0,
                dtype=udt)
        for y in ys)


from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit  # noqa: E402

_secagg_leaf_chunk_program = _wrap_jit(
    "secagg/leaf_chunk", _secagg_leaf_chunk_program,
    static_argnums=(0, 1, 2, 3, 4), multi_shape=True)


class SecAggLeafCohort(LeafCohort):
    """A leaf cohort whose edge only ever sees the masked sum.

    Same reduce contract as :class:`LeafCohort` (unnormalized f32 sum
    leaves + total weight), but per-client contributions are pairwise-
    masked in the cohort-shared int domain. Weights must be uniform
    (masked sums are unweighted by construction) and EF is unsupported
    in this mode (the masked path has no per-client decode to feed it).
    """

    def __init__(self, tier: int, edge_id: int, client_ids, codec, meta,
                 delta_fn, seed: int, chunk: int = 2048,
                 clip: float = 0.1, mod_bits: int = 8, **kw):
        if kw.pop("ef", False):
            raise ValueError(
                "secagg leaf cohorts do not support per-client error "
                "feedback (there is no per-client decode to feed it)")
        if kw.pop("weights", None) is not None:
            raise ValueError(
                "secagg leaf cohorts are uniform-weight by construction")
        super().__init__(tier, edge_id, client_ids, codec, meta, delta_fn,
                         seed, chunk=chunk, ef=False, **kw)
        self.clip = float(clip)
        self.mod_bits = int(mod_bits)
        # the shared quant bound is sized for the FULL roster: the mask
        # domain must absorb the worst-case cohort sum, and a constant
        # bound keeps one compiled program across kill/rejoin rounds
        self.bound = masking.client_bound(len(self.client_ids),
                                          self.mod_bits)
        self._pair_secret_cache = {}

    # -- deterministic in-process pair seeds --------------------------------
    def _pair_secret(self, i: int, j: int) -> int:
        lo, hi = (int(i), int(j)) if i < j else (int(j), int(i))
        ck = (lo, hi)
        if ck not in self._pair_secret_cache:
            h = hashlib.blake2b(
                b"fedml_tpu/secagg/hier%d/%d/%d/%d" % (
                    self.seed, self.edge_id, lo, hi),
                digest_size=16)
            self._pair_secret_cache[ck] = int.from_bytes(h.digest(),
                                                         "little")
        return self._pair_secret_cache[ck]

    def _seeds_for(self, i: int, others, round_idx: int):
        return {int(j): masking.pair_round_seed(self._pair_secret(i, j),
                                                round_idx)
                for j in others if int(j) != int(i)}

    def reduce(self, round_idx: int, alive_local: np.ndarray) -> Tuple[
            Optional[list], float, int]:
        from fedml_tpu.telemetry import get_registry

        live = np.asarray(alive_local, bool) & ~self.evicted_mask
        expected = np.nonzero(~self.evicted_mask)[0]
        n_recv = int(live.sum())
        if n_recv == 0:
            return None, 0.0, 0
        # every EXPECTED client derived masks over the full expected
        # roster this round; dead-but-expected clients are the recovery
        # set (their uploads never arrived, their pair halves dangle)
        dead_expected = [int(i) for i in expected if not live[i]]
        live_idx = np.nonzero(live)[0]
        udt = {8: np.uint8, 16: np.uint16}[self.mod_bits]
        total = None
        n = len(live_idx)
        for start in range(0, n, self.chunk):
            idx = live_idx[start:start + self.chunk]
            # pad every chunk to the bucketed size: kills change inputs
            # (alive mask + zero masks), never program shapes
            pad = self.chunk - len(idx)
            # masks for the chunk's clients, host-side (numpy, wrapping)
            chunk_masks = []
            for i in idx:
                seeds = self._seeds_for(int(i), expected, round_idx)
                chunk_masks.append(masking.net_mask_leaves(
                    int(i), seeds, self.meta, self.mod_bits))
            for _ in range(pad):
                chunk_masks.append([np.zeros(sh, udt)
                                    for _, sh in self.meta])
            cids = np.concatenate([self.client_ids[idx],
                                   np.zeros(pad, np.int64)])
            kd = derive_key_data_batch(self.seed, round_idx, cids)
            alive_chunk = np.concatenate([np.ones(len(idx), np.uint8),
                                          np.zeros(pad, np.uint8)])
            masks_stacked = tuple(
                jnp.asarray(np.stack([m[li] for m in chunk_masks]))
                for li in range(len(self.meta)))
            summed = _secagg_leaf_chunk_program(
                self.meta, self.delta_fn, self.clip, self.bound,
                self.mod_bits, jnp.asarray(kd), jnp.asarray(alive_chunk),
                masks_stacked)
            summed = [np.asarray(s) for s in summed]
            if total is None:
                total = summed
            else:
                total = [a + b for a, b in zip(total, summed)]  # uint wrap
        # dropout recovery: reproduce the live↔dead halves and strip them
        if dead_expected:
            pairs = [(int(i), j, self._seeds_for(int(i), [j], round_idx)[j])
                     for i in live_idx for j in dead_expected]
            rec = masking.recovery_adjustment(pairs, self.meta,
                                              self.mod_bits)
            total = [a - r for a, r in zip(total, rec)]
            get_registry().counter("secagg/hier_recoveries").inc()
        get_registry().counter("secagg/hier_cohort_rounds").inc()
        # re-center mod 2^k and scale: the cohort's unnormalized f32 sum
        half = 1 << (self.mod_bits - 1)
        scale = self.clip / float(self.bound)
        sum_leaves = []
        for s in total:
            c = s.astype(np.int32)
            c = c - ((c >= half).astype(np.int32) << self.mod_bits)
            sum_leaves.append(jnp.asarray(c.astype(np.float32) * scale))
        return sum_leaves, float(n_recv), n_recv
